"""Torch-verb Tensor facade (ref tensor/Tensor.scala:35, TensorMath.scala:28).

The reference's tensor layer (SURVEY.md §2.2) is a strided view over a flat
JVM array with Torch semantics: 1-based indexing, aliasing ``narrow /
select / view``, in-place math.  On TPU the *compute* path is ``jax.numpy``
under ``jax.jit`` — XLA plays MKL's role — so this facade is deliberately a
**host-side** tensor backed by numpy (mutation-friendly, strided, aliasing),
used by the interop layers (.t7 / Caffe loaders), data pipeline, and user
code that expects the Torch API.  ``to_jax()`` / ``from_jax()`` bridge to
device arrays at the jit boundary.

Dim / index arguments are 1-based exactly like the reference
(``tensor/DenseTensor.scala:30-35``); negative dims are not supported, as in
Torch7.  Methods ending in ``_`` or documented as in-place mutate the
underlying storage (and therefore every aliasing view), matching
``narrow``'s aliasing contract that the reference's flattened-parameter
trick relies on (``nn/Module.scala:41``).
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import numpy as np

from bigdl_tpu.utils.rng import RNG

__all__ = ["Tensor", "Storage"]

Number = Union[int, float]


class Storage:
    """Flat 1-D storage (ref tensor/Storage.scala). Wraps a numpy 1-D array."""

    def __init__(self, data: Union[int, Sequence, np.ndarray], dtype=np.float32):
        if isinstance(data, (int, np.integer)):
            self._arr = np.zeros(int(data), dtype=dtype)
        else:
            self._arr = np.ascontiguousarray(np.asarray(data, dtype=dtype)).reshape(-1)

    def array(self) -> np.ndarray:
        return self._arr

    def __len__(self) -> int:
        return self._arr.size

    def __getitem__(self, i: int):  # 1-based
        return self._arr[i - 1].item()

    def __setitem__(self, i: int, v) -> None:  # 1-based
        self._arr[i - 1] = v

    def copy(self, other: "Storage") -> "Storage":
        np.copyto(self._arr, other._arr)
        return self

    def fill(self, v, offset: int = 1, length: Optional[int] = None) -> "Storage":
        length = len(self) - offset + 1 if length is None else length
        self._arr[offset - 1:offset - 1 + length] = v
        return self


def _as_np(x):
    if isinstance(x, Tensor):
        return x._np()
    return x


class Tensor:
    """N-d strided tensor with Torch verbs over a flat Storage.

    Constructors::

        Tensor()                      # empty
        Tensor(3, 4)                  # zeros of shape (3,4)
        Tensor([3, 4])                # zeros of shape (3,4)
        Tensor(np_array)              # copy of an ndarray
        Tensor(storage, offset, sizes, strides)  # aliasing view
    """

    def __init__(self, *args, dtype=np.float32):
        if len(args) == 0:
            self._set_view(Storage(0, dtype), 1, (), ())
            return
        a0 = args[0]
        if isinstance(a0, Storage):
            offset = args[1] if len(args) > 1 else 1
            sizes = tuple(args[2]) if len(args) > 2 and args[2] is not None else (len(a0),)
            strides = tuple(args[3]) if len(args) > 3 and args[3] is not None \
                else _contiguous_strides(sizes)
            self._set_view(a0, offset, sizes, strides)
        elif isinstance(a0, Tensor):
            arr = np.array(a0._np())
            self._from_array(arr)
        elif isinstance(a0, np.ndarray):
            self._from_array(np.array(a0, dtype=a0.dtype if a0.dtype.kind == "f" or
                                      a0.dtype.kind in "iu" else dtype))
        elif isinstance(a0, (list, tuple)) and len(args) == 1:
            arr0 = np.asarray(a0)
            if arr0.dtype.kind in "iu" and arr0.ndim == 1 and not any(
                    isinstance(e, (list, tuple, np.ndarray, float)) for e in a0):
                # Tensor([3,4]) = zeros of that shape (Torch convention)
                sizes = tuple(int(s) for s in a0)
                st = Storage(int(np.prod(sizes)) if sizes else 0, dtype)
                self._set_view(st, 1, sizes, _contiguous_strides(sizes))
            else:
                self._from_array(np.asarray(a0, dtype=dtype))
        else:  # Tensor(3, 4, ...)
            sizes = tuple(int(s) for s in args)
            st = Storage(int(np.prod(sizes)) if sizes else 0, dtype)
            self._set_view(st, 1, sizes, _contiguous_strides(sizes))

    # ---------------------------------------------------------------- #
    # internals                                                        #
    # ---------------------------------------------------------------- #
    def _set_view(self, storage: Storage, offset: int, sizes, strides) -> None:
        self._storage = storage
        self._offset = int(offset)
        self._sizes = tuple(int(s) for s in sizes)
        self._strides = tuple(int(s) for s in strides)

    def _from_array(self, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        st = Storage(arr.reshape(-1), dtype=arr.dtype)
        self._set_view(st, 1, arr.shape, _contiguous_strides(arr.shape))

    def _np(self) -> np.ndarray:
        """A (possibly aliasing) numpy view of this tensor."""
        base = self._storage.array()
        if self.dim() == 0:
            return base[self._offset - 1:self._offset - 1]
        itemsize = base.itemsize
        return np.lib.stride_tricks.as_strided(
            base[self._offset - 1:],
            shape=self._sizes,
            strides=tuple(s * itemsize for s in self._strides),
            writeable=True,
        )

    # ---------------------------------------------------------------- #
    # shape / metadata (ref Tensor.scala:35-200)                       #
    # ---------------------------------------------------------------- #
    def dim(self) -> int:
        return len(self._sizes)

    n_dimension = dim
    nDimension = dim

    def size(self, dim: Optional[int] = None):
        if dim is None:
            return tuple(self._sizes)
        return self._sizes[dim - 1]

    def stride(self, dim: Optional[int] = None):
        if dim is None:
            return tuple(self._strides)
        return self._strides[dim - 1]

    def n_element(self) -> int:
        return int(np.prod(self._sizes)) if self._sizes else 0

    nElement = n_element

    def storage(self) -> Storage:
        return self._storage

    def storage_offset(self) -> int:
        return self._offset

    def is_contiguous(self) -> bool:
        return self._strides == _contiguous_strides(self._sizes)

    def contiguous(self) -> "Tensor":
        if self.is_contiguous():
            return self
        return Tensor(self._np())

    @property
    def dtype(self):
        return self._storage.array().dtype

    # ---------------------------------------------------------------- #
    # element access (1-based)                                         #
    # ---------------------------------------------------------------- #
    def value_at(self, *indices: int):
        return float(self._np()[tuple(i - 1 for i in indices)])

    valueAt = value_at

    def set_value(self, *args) -> "Tensor":
        *indices, v = args
        self._np()[tuple(i - 1 for i in indices)] = v
        return self

    setValue = set_value

    def __getitem__(self, idx):
        if isinstance(idx, int):
            if self.dim() == 1:
                return self.value_at(idx)
            return self.select(1, idx)
        if isinstance(idx, tuple):
            return self.value_at(*idx)
        raise TypeError(f"unsupported index {idx!r}")

    def __setitem__(self, idx, v) -> None:
        if isinstance(idx, int):
            if self.dim() == 1:
                self.set_value(idx, v)
            else:
                self.select(1, idx).copy(v if isinstance(v, Tensor) else Tensor(np.asarray(v)))
        elif isinstance(idx, tuple):
            self.set_value(*idx, v)
        else:
            raise TypeError(f"unsupported index {idx!r}")

    # ---------------------------------------------------------------- #
    # views (aliasing, ref Tensor.scala narrow/select/view/…)          #
    # ---------------------------------------------------------------- #
    def narrow(self, dim: int, index: int, size: int) -> "Tensor":
        d = dim - 1
        assert 1 <= index and index + size - 1 <= self._sizes[d], "narrow out of range"
        offset = self._offset + (index - 1) * self._strides[d]
        sizes = list(self._sizes)
        sizes[d] = size
        return Tensor(self._storage, offset, sizes, self._strides)

    def select(self, dim: int, index: int) -> "Tensor":
        d = dim - 1
        assert self.dim() > 0, "cannot select on a scalar"
        offset = self._offset + (index - 1) * self._strides[d]
        sizes = self._sizes[:d] + self._sizes[d + 1:]
        strides = self._strides[:d] + self._strides[d + 1:]
        return Tensor(self._storage, offset, sizes, strides)

    def view(self, *sizes) -> "Tensor":
        sizes = _unpack_sizes(sizes)
        sizes = _infer_size(sizes, self.n_element())
        assert self.is_contiguous(), "view requires a contiguous tensor"
        return Tensor(self._storage, self._offset, sizes, _contiguous_strides(sizes))

    def reshape(self, *sizes) -> "Tensor":
        sizes = _infer_size(_unpack_sizes(sizes), self.n_element())
        return Tensor(self._np().reshape(sizes))

    def transpose(self, dim1: int, dim2: int) -> "Tensor":
        d1, d2 = dim1 - 1, dim2 - 1
        sizes = list(self._sizes)
        strides = list(self._strides)
        sizes[d1], sizes[d2] = sizes[d2], sizes[d1]
        strides[d1], strides[d2] = strides[d2], strides[d1]
        return Tensor(self._storage, self._offset, sizes, strides)

    def t(self) -> "Tensor":
        assert self.dim() == 2, "t() expects a 2D tensor"
        return self.transpose(1, 2)

    def unfold(self, dim: int, size: int, step: int) -> "Tensor":
        d = dim - 1
        n = (self._sizes[d] - size) // step + 1
        sizes = self._sizes[:d] + (n,) + self._sizes[d + 1:] + (size,)
        strides = self._strides[:d] + (self._strides[d] * step,) + \
            self._strides[d + 1:] + (self._strides[d],)
        return Tensor(self._storage, self._offset, sizes, strides)

    def expand(self, *sizes) -> "Tensor":
        sizes = _unpack_sizes(sizes)
        assert len(sizes) == self.dim()
        strides = list(self._strides)
        for i, (have, want) in enumerate(zip(self._sizes, sizes)):
            if have != want:
                assert have == 1, f"cannot expand dim {i+1} from {have} to {want}"
                strides[i] = 0
        return Tensor(self._storage, self._offset, sizes, strides)

    def expand_as(self, other: "Tensor") -> "Tensor":
        return self.expand(*other.size())

    def squeeze(self, dim: Optional[int] = None) -> "Tensor":
        if dim is None:
            keep = [i for i, s in enumerate(self._sizes) if s != 1]
        else:
            keep = [i for i in range(self.dim()) if not (i == dim - 1 and self._sizes[i] == 1)]
        sizes = tuple(self._sizes[i] for i in keep)
        strides = tuple(self._strides[i] for i in keep)
        return Tensor(self._storage, self._offset, sizes, strides)

    def unsqueeze(self, dim: int) -> "Tensor":
        d = dim - 1
        sizes = self._sizes[:d] + (1,) + self._sizes[d:]
        stride_here = self._strides[d] * self._sizes[d] if d < self.dim() else 1
        strides = self._strides[:d] + (stride_here,) + self._strides[d:]
        return Tensor(self._storage, self._offset, sizes, strides)

    def split(self, size: int, dim: int = 1) -> list["Tensor"]:
        out, i = [], 1
        total = self._sizes[dim - 1]
        while i <= total:
            out.append(self.narrow(dim, i, min(size, total - i + 1)))
            i += size
        return out

    def set(self, other: Optional["Tensor"] = None, storage: Optional[Storage] = None,
            storage_offset: int = 1, sizes=None, strides=None) -> "Tensor":
        """Re-point this tensor at another tensor's storage (ref Tensor.set)."""
        if other is not None:
            self._set_view(other._storage, other._offset, other._sizes, other._strides)
        elif storage is not None:
            sizes = tuple(sizes) if sizes is not None else (len(storage),)
            strides = tuple(strides) if strides is not None else _contiguous_strides(sizes)
            self._set_view(storage, storage_offset, sizes, strides)
        else:
            self._set_view(Storage(0, self.dtype), 1, (), ())
        return self

    def resize(self, *sizes) -> "Tensor":
        sizes = _unpack_sizes(sizes)
        n = int(np.prod(sizes)) if sizes else 0
        if n > len(self._storage) - self._offset + 1 or not self.is_contiguous():
            self._set_view(Storage(n, self.dtype), 1, sizes, _contiguous_strides(sizes))
        else:
            self._set_view(self._storage, self._offset, sizes, _contiguous_strides(sizes))
        return self

    def resize_as(self, other: "Tensor") -> "Tensor":
        return self.resize(*other.size())

    resizeAs = resize_as

    # ---------------------------------------------------------------- #
    # fill / randomization (in-place)                                  #
    # ---------------------------------------------------------------- #
    def fill(self, v: Number) -> "Tensor":
        self._np()[...] = v
        return self

    def zero(self) -> "Tensor":
        return self.fill(0)

    def copy(self, other: "Tensor") -> "Tensor":
        np.copyto(self._np(), np.broadcast_to(other._np(), self._sizes))
        return self

    def rand(self) -> "Tensor":
        self._assign_flat(RNG.current().uniform_array(self.n_element()))
        return self

    def randn(self) -> "Tensor":
        self._assign_flat(RNG.current().normal_array(self.n_element()))
        return self

    def bernoulli(self, p: float) -> "Tensor":
        self._assign_flat(RNG.current().bernoulli_array(self.n_element(), p))
        return self

    def _assign_flat(self, vals) -> None:
        view = self._np()
        np.copyto(view, np.asarray(vals, dtype=self.dtype).reshape(self._sizes))

    def apply1(self, fn) -> "Tensor":
        view = self._np()
        it = np.nditer(view, flags=["multi_index"], op_flags=["readwrite"])
        for x in it:
            x[...] = fn(float(x))
        return self

    # ---------------------------------------------------------------- #
    # math (ref TensorMath.scala:28-642) — out-of-place unless noted   #
    # ---------------------------------------------------------------- #
    def _wrap(self, arr: np.ndarray) -> "Tensor":
        return Tensor(np.asarray(arr, dtype=self.dtype))

    def __add__(self, other):
        return self._wrap(self._np() + _as_np(other))

    def __radd__(self, other):
        return self._wrap(_as_np(other) + self._np())

    def __sub__(self, other):
        return self._wrap(self._np() - _as_np(other))

    def __rsub__(self, other):
        return self._wrap(_as_np(other) - self._np())

    def __mul__(self, other):
        return self._wrap(self._np() * _as_np(other))

    def __rmul__(self, other):
        return self._wrap(_as_np(other) * self._np())

    def __truediv__(self, other):
        return self._wrap(self._np() / _as_np(other))

    def __neg__(self):
        return self._wrap(-self._np())

    # in-place accumulate family (Torch add/cmul/… mutate the receiver)
    def add(self, *args) -> "Tensor":
        """add(value) | add(tensor) | add(alpha, tensor) — in place."""
        if len(args) == 1:
            self._np()[...] += _as_np(args[0])
        else:
            alpha, t = args
            self._np()[...] += alpha * _as_np(t)
        return self

    def sub(self, *args) -> "Tensor":
        if len(args) == 1:
            self._np()[...] -= _as_np(args[0])
        else:
            alpha, t = args
            self._np()[...] -= alpha * _as_np(t)
        return self

    def cmul(self, other: "Tensor") -> "Tensor":
        self._np()[...] *= _as_np(other)
        return self

    def cdiv(self, other: "Tensor") -> "Tensor":
        self._np()[...] /= _as_np(other)
        return self

    def mul(self, v: Number) -> "Tensor":
        self._np()[...] *= v
        return self

    def div(self, v: Number) -> "Tensor":
        self._np()[...] /= v
        return self

    def addcmul(self, value: Number, t1: "Tensor", t2: "Tensor") -> "Tensor":
        self._np()[...] += value * (_as_np(t1) * _as_np(t2))
        return self

    def addcdiv(self, value: Number, t1: "Tensor", t2: "Tensor") -> "Tensor":
        self._np()[...] += value * (_as_np(t1) / _as_np(t2))
        return self

    # BLAS family
    def addmm(self, *args) -> "Tensor":
        """addmm([beta,] [alpha,] mat1, mat2): self = beta*self + alpha*mat1@mat2."""
        beta, alpha, m1, m2 = _parse_blas_args(args)
        self._np()[...] = beta * self._np() + alpha * (_as_np(m1) @ _as_np(m2))
        return self

    def addmv(self, *args) -> "Tensor":
        beta, alpha, m, v = _parse_blas_args(args)
        self._np()[...] = beta * self._np() + alpha * (_as_np(m) @ _as_np(v))
        return self

    def addr(self, *args) -> "Tensor":
        beta, alpha, v1, v2 = _parse_blas_args(args)
        self._np()[...] = beta * self._np() + alpha * np.outer(_as_np(v1), _as_np(v2))
        return self

    def baddbmm(self, *args) -> "Tensor":
        beta, alpha, b1, b2 = _parse_blas_args(args)
        self._np()[...] = beta * self._np() + alpha * np.matmul(_as_np(b1), _as_np(b2))
        return self

    def mm(self, m1: "Tensor", m2: "Tensor") -> "Tensor":
        r = _as_np(m1) @ _as_np(m2)
        self.resize(*r.shape)
        self._np()[...] = r
        return self

    def mv(self, m: "Tensor", v: "Tensor") -> "Tensor":
        r = _as_np(m) @ _as_np(v)
        self.resize(*r.shape)
        self._np()[...] = r
        return self

    def bmm(self, b1: "Tensor", b2: "Tensor") -> "Tensor":
        r = np.matmul(_as_np(b1), _as_np(b2))
        self.resize(*r.shape)
        self._np()[...] = r
        return self

    def dot(self, other: "Tensor") -> float:
        return float(np.dot(self._np().reshape(-1), _as_np(other).reshape(-1)))

    # elementwise transcendental (in-place, mirrors MKL VML usage)
    def pow(self, n: Number) -> "Tensor":
        self._np()[...] = np.power(self._np(), n)
        return self

    def log(self) -> "Tensor":
        self._np()[...] = np.log(self._np())
        return self

    def exp(self) -> "Tensor":
        self._np()[...] = np.exp(self._np())
        return self

    def sqrt(self) -> "Tensor":
        self._np()[...] = np.sqrt(self._np())
        return self

    def log1p(self) -> "Tensor":
        self._np()[...] = np.log1p(self._np())
        return self

    def abs(self) -> "Tensor":
        self._np()[...] = np.abs(self._np())
        return self

    # reductions
    def sum(self, dim: Optional[int] = None):
        if dim is None:
            return float(self._np().sum())
        return self._wrap(self._np().sum(axis=dim - 1, keepdims=True))

    def mean(self, dim: Optional[int] = None):
        if dim is None:
            return float(self._np().mean())
        return self._wrap(self._np().mean(axis=dim - 1, keepdims=True))

    def max(self, dim: Optional[int] = None):
        if dim is None:
            return float(self._np().max())
        a = self._np()
        vals = a.max(axis=dim - 1, keepdims=True)
        idx = a.argmax(axis=dim - 1) + 1  # 1-based
        return self._wrap(vals), Tensor(np.expand_dims(idx, dim - 1).astype(np.float32))

    def min(self, dim: Optional[int] = None):
        if dim is None:
            return float(self._np().min())
        a = self._np()
        vals = a.min(axis=dim - 1, keepdims=True)
        idx = a.argmin(axis=dim - 1) + 1
        return self._wrap(vals), Tensor(np.expand_dims(idx, dim - 1).astype(np.float32))

    def topk(self, k: int, dim: Optional[int] = None, increase: bool = True):
        """(values, 1-based indices) of the k smallest (increase) or largest."""
        a = self._np()
        d = (dim if dim is not None else self.dim()) - 1
        order = np.argsort(a, axis=d, kind="stable")
        if not increase:
            order = np.flip(order, axis=d)
        idx = np.take(order, np.arange(k), axis=d)
        vals = np.take_along_axis(a, idx, axis=d)
        return self._wrap(vals), Tensor((idx + 1).astype(np.float32))

    def norm(self, p: Number = 2) -> float:
        a = self._np().reshape(-1)
        if p == 1:
            return float(np.abs(a).sum())
        return float(np.power(np.power(np.abs(a), p).sum(), 1.0 / p))

    def dist(self, other: "Tensor", p: Number = 2) -> float:
        return (self - other).norm(p)

    def uniform(self, a: float = 0.0, b: float = 1.0) -> float:
        return RNG.uniform(a, b)

    # comparison masks (out-of-place, 0/1 tensors like the reference)
    def gt(self, other) -> "Tensor":
        return self._wrap((self._np() > _as_np(other)).astype(self.dtype))

    def lt(self, other) -> "Tensor":
        return self._wrap((self._np() < _as_np(other)).astype(self.dtype))

    def le(self, other) -> "Tensor":
        return self._wrap((self._np() <= _as_np(other)).astype(self.dtype))

    def ge(self, other) -> "Tensor":
        return self._wrap((self._np() >= _as_np(other)).astype(self.dtype))

    def eq(self, other) -> "Tensor":
        return self._wrap((self._np() == _as_np(other)).astype(self.dtype))

    def masked_fill(self, mask: "Tensor", v: Number) -> "Tensor":
        self._np()[_as_np(mask).astype(bool)] = v
        return self

    maskedFill = masked_fill

    def masked_copy(self, mask: "Tensor", src: "Tensor") -> "Tensor":
        m = _as_np(mask).astype(bool)
        self._np()[m] = _as_np(src).reshape(-1)[: int(m.sum())]
        return self

    maskedCopy = masked_copy

    def masked_select(self, mask: "Tensor") -> "Tensor":
        return self._wrap(self._np()[_as_np(mask).astype(bool)])

    maskedSelect = masked_select

    # scatter / gather (1-based index tensors, ref TensorMath.scala)
    def gather(self, dim: int, index: "Tensor") -> "Tensor":
        idx = (_as_np(index) - 1).astype(np.int64)
        return self._wrap(np.take_along_axis(self._np(), idx, axis=dim - 1))

    def scatter(self, dim: int, index: "Tensor", src: "Tensor") -> "Tensor":
        idx = (_as_np(index) - 1).astype(np.int64)
        np.put_along_axis(self._np(), idx, _as_np(src), axis=dim - 1)
        return self

    def index_select(self, dim: int, indices: "Tensor") -> "Tensor":
        idx = (_as_np(indices).astype(np.int64).reshape(-1) - 1)
        return self._wrap(np.take(self._np(), idx, axis=dim - 1))

    # conv2 / xcorr2 (ref DenseTensorConv.scala — 'valid' mode)
    def conv2(self, kernel: "Tensor") -> "Tensor":
        return self._wrap(_corr2(self._np(), np.flip(_as_np(kernel))))

    def xcorr2(self, kernel: "Tensor") -> "Tensor":
        return self._wrap(_corr2(self._np(), _as_np(kernel)))

    # ---------------------------------------------------------------- #
    # interop                                                          #
    # ---------------------------------------------------------------- #
    def numpy(self) -> np.ndarray:
        return np.array(self._np())

    def to_jax(self):
        import jax.numpy as jnp
        return jnp.asarray(self._np())

    @staticmethod
    def from_jax(arr) -> "Tensor":
        return Tensor(np.asarray(arr))

    def clone(self) -> "Tensor":
        return Tensor(np.array(self._np()))

    def __eq__(self, other) -> bool:
        if not isinstance(other, Tensor):
            return NotImplemented
        return self._sizes == other._sizes and np.array_equal(self._np(), other._np())

    def __hash__(self):
        return id(self)

    def almost_equal(self, other: "Tensor", tol: float = 1e-6) -> bool:
        return self._sizes == other._sizes and \
            np.allclose(self._np(), other._np(), atol=tol, rtol=0)

    def __repr__(self) -> str:
        return f"Tensor(size={self._sizes})\n{self._np()!r}"

    # ---------------------------------------------------------------- #
    # factories (ref Tensor object, Tensor.scala:610-897)              #
    # ---------------------------------------------------------------- #
    @staticmethod
    def ones(*sizes, dtype=np.float32) -> "Tensor":
        return Tensor(np.ones(_unpack_sizes(sizes), dtype=dtype))

    @staticmethod
    def zeros(*sizes, dtype=np.float32) -> "Tensor":
        return Tensor(np.zeros(_unpack_sizes(sizes), dtype=dtype))

    @staticmethod
    def arange(xmin: Number, xmax: Number, step: Number = 1) -> "Tensor":
        """Inclusive range like Torch's torch.range."""
        # epsilon guards float quotients that land just below an integer
        # (e.g. 0.3/0.1 -> 2.9999...), which would drop the endpoint
        n = int(np.floor((xmax - xmin) / step + 1e-7)) + 1
        return Tensor((xmin + step * np.arange(n)).astype(np.float32))

    range = arange

    @staticmethod
    def randperm(n: int) -> "Tensor":
        """1-based random permutation drawn from the shared Torch RNG."""
        return Tensor(RNG.current().randperm(n).astype(np.float32))

    @staticmethod
    def gaussian1D(size: int = 3, sigma: float = 0.25, amplitude: float = 1.0,
                   normalize: bool = False, mean: float = 0.5, tensor=None) -> "Tensor":
        """1-D gaussian kernel (ref Tensor.scala:827-897)."""
        center = mean * size + 0.5
        x = np.arange(1, size + 1, dtype=np.float64)
        g = amplitude * np.exp(-(((x - center) / (sigma * size)) ** 2) / 2)
        if normalize:
            g = g / g.sum()
        out = Tensor(g.astype(np.float32))
        if tensor is not None:
            tensor.resize(size)
            tensor._np()[...] = out._np()
            return tensor
        return out


def _contiguous_strides(sizes) -> tuple:
    strides, acc = [], 1
    for s in reversed(sizes):
        strides.append(acc)
        acc *= s
    return tuple(reversed(strides))


def _unpack_sizes(sizes):
    if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
        return tuple(int(s) for s in sizes[0])
    return tuple(int(s) for s in sizes)


def _infer_size(sizes, numel):
    sizes = list(sizes)
    if -1 in sizes:
        i = sizes.index(-1)
        rest = int(np.prod([s for s in sizes if s != -1])) or 1
        sizes[i] = numel // rest
    return tuple(sizes)


def _parse_blas_args(args):
    """[beta,] [alpha,] t1, t2 → (beta, alpha, t1, t2)."""
    nums = [a for a in args if isinstance(a, (int, float)) and not isinstance(a, Tensor)]
    tensors = [a for a in args if isinstance(a, (Tensor, np.ndarray))]
    assert len(tensors) == 2, "expected two tensor operands"
    if len(nums) == 0:
        return 1.0, 1.0, tensors[0], tensors[1]
    if len(nums) == 1:
        return 1.0, nums[0], tensors[0], tensors[1]
    return nums[0], nums[1], tensors[0], tensors[1]


def _corr2(a: np.ndarray, k: np.ndarray) -> np.ndarray:
    """2-D 'valid' cross-correlation (ref DenseTensorConv.scala:262)."""
    oh, ow = a.shape[0] - k.shape[0] + 1, a.shape[1] - k.shape[1] + 1
    win = np.lib.stride_tricks.sliding_window_view(a, k.shape)
    return np.einsum("ijkl,kl->ij", win[:oh, :ow], k)
