from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.engine import Engine

__all__ = ["Table", "T", "Engine"]
