from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils import torch_file
from bigdl_tpu.utils import torch_import

__all__ = ["Table", "T", "Engine", "torch_file", "torch_import"]
