"""Caffe model import (ref utils/CaffeLoader.scala:38-160).

The reference depends on 95,952 LoC of generated protobuf Java
(``caffe/Caffe.java``); here the needed subset of ``caffe.proto`` is decoded
directly from the wire format (same approach as
``bigdl_tpu.visualization.proto``):

  NetParameter: name=1, layers=2 (repeated V1LayerParameter),
                input=3, layer=100 (repeated LayerParameter)
  V1LayerParameter: bottom=2, top=3, name=4, type=5 (enum), blobs=6
  LayerParameter:   name=1, type=2 (string), bottom=3, top=4, blobs=7
  BlobProto: num=1, channels=2, height=3, width=4,
             data=5 (repeated float), shape=7 (BlobShape: dim=1 int64)

``load(model, def_path, model_path, match_all)`` copies blob 0 -> weight and
blob 1 -> bias into same-named modules of the given model, matching the
reference's element-count-checked flat copy (CaffeLoader.scala:86-125).
"""
from __future__ import annotations

import logging
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from bigdl_tpu.visualization.proto import _iter_fields, _read_varint

log = logging.getLogger("bigdl_tpu.caffe")


@dataclass
class BlobProto:
    shape: List[int] = field(default_factory=list)
    data: Optional[np.ndarray] = None


@dataclass
class CaffeLayer:
    name: str = ""
    type: Any = None  # string (V2) or enum int (V1)
    bottom: List[str] = field(default_factory=list)
    top: List[str] = field(default_factory=list)
    blobs: List[BlobProto] = field(default_factory=list)


@dataclass
class CaffeNet:
    name: str = ""
    layers_v1: List[CaffeLayer] = field(default_factory=list)
    layers_v2: List[CaffeLayer] = field(default_factory=list)

    def by_name(self) -> Dict[str, CaffeLayer]:
        # V2 wins on duplicate names, like the reference's lookup order
        out = {l.name: l for l in self.layers_v1}
        out.update({l.name: l for l in self.layers_v2})
        return out


def _floats(wt: int, v) -> np.ndarray:
    if wt == 2:  # packed
        return np.frombuffer(v, dtype="<f4").copy()
    return np.array([struct.unpack("<f", v)[0]], np.float32)


def _parse_blob(buf: bytes) -> BlobProto:
    blob = BlobProto()
    legacy = {}
    chunks = []
    for fnum, wt, v in _iter_fields(buf):
        if fnum in (1, 2, 3, 4) and wt == 0:
            legacy[fnum] = v
        elif fnum == 5:
            chunks.append(_floats(wt, v))
        elif fnum == 7 and wt == 2:  # BlobShape
            dims = []
            for f2, w2, v2 in _iter_fields(v):
                if f2 == 1:
                    if w2 == 2:  # packed int64
                        pos = 0
                        while pos < len(v2):
                            d, pos = _read_varint(v2, pos)
                            dims.append(d)
                    elif w2 == 0:
                        dims.append(v2)
            blob.shape = dims
        elif fnum == 8 and wt == 2:  # double_data
            chunks.append(np.frombuffer(v, dtype="<f8").astype(np.float32))
    if chunks:
        blob.data = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
    if not blob.shape and legacy:
        blob.shape = [legacy.get(1, 1), legacy.get(2, 1),
                      legacy.get(3, 1), legacy.get(4, 1)]
    return blob


def _parse_layer(buf: bytes, v1: bool) -> CaffeLayer:
    layer = CaffeLayer()
    if v1:
        f_bottom, f_top, f_name, f_type, f_blobs = 2, 3, 4, 5, 6
    else:
        f_name, f_type, f_bottom, f_top, f_blobs = 1, 2, 3, 4, 7
    for fnum, wt, v in _iter_fields(buf):
        if fnum == f_name and wt == 2:
            layer.name = v.decode("utf-8", "replace")
        elif fnum == f_type:
            layer.type = (v if wt == 0 else v.decode("utf-8", "replace"))
        elif fnum == f_bottom and wt == 2:
            layer.bottom.append(v.decode("utf-8", "replace"))
        elif fnum == f_top and wt == 2:
            layer.top.append(v.decode("utf-8", "replace"))
        elif fnum == f_blobs and wt == 2:
            layer.blobs.append(_parse_blob(v))
    return layer


def parse_caffemodel(data: bytes) -> CaffeNet:
    net = CaffeNet()
    for fnum, wt, v in _iter_fields(data):
        if fnum == 1 and wt == 2:
            net.name = v.decode("utf-8", "replace")
        elif fnum == 2 and wt == 2:
            net.layers_v1.append(_parse_layer(v, v1=True))
        elif fnum == 100 and wt == 2:
            net.layers_v2.append(_parse_layer(v, v1=False))
    return net


# ------------------------- prototxt (text format) ----------------------- #

def parse_prototxt(text: str) -> Dict[str, Any]:
    """Minimal protobuf text-format parser: returns a nested dict; repeated
    fields become lists.  Handles ``key: value``, ``key { ... }``, quoted
    strings, comments."""
    import re
    tokens = re.findall(
        r'"(?:[^"\\]|\\.)*"|[{}]|[^\s{}:#]+|:|#[^\n]*', text)
    tokens = [t for t in tokens if not t.startswith("#")]
    pos = 0

    def parse_value(tok: str):
        if tok.startswith('"'):
            return tok[1:-1].encode().decode("unicode_escape")
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            try:
                return float(tok)
            except ValueError:
                return tok  # enum identifier

    def parse_message() -> Dict[str, Any]:
        nonlocal pos
        msg: Dict[str, Any] = {}

        def put(key, value):
            if key in msg:
                if not isinstance(msg[key], list):
                    msg[key] = [msg[key]]
                msg[key].append(value)
            else:
                msg[key] = value

        while pos < len(tokens) and tokens[pos] != "}":
            key = tokens[pos]
            pos += 1
            if pos < len(tokens) and tokens[pos] == ":":
                pos += 1
                put(key, parse_value(tokens[pos]))
                pos += 1
            elif pos < len(tokens) and tokens[pos] == "{":
                pos += 1
                put(key, parse_message())
                pos += 1  # closing }
            else:
                raise ValueError(f"prototxt parse error at token {key!r}")
        return msg

    return parse_message()


# ------------------------------ loader ---------------------------------- #

class CaffeLoader:
    """Copy caffe blobs into same-named modules (ref CaffeLoader.scala)."""

    def __init__(self, def_path: str, model_path: str, match_all: bool = True):
        self.def_path = def_path
        self.model_path = model_path
        self.match_all = match_all
        self._net: Optional[CaffeNet] = None
        self._prototxt: Optional[dict] = None

    @property
    def prototxt(self) -> dict:
        """The parsed network definition (structure only; weights come from
        the binary).  Parsed lazily — weight copying never needs it."""
        if self._prototxt is None:
            with open(self.def_path) as f:
                self._prototxt = parse_prototxt(f.read())
        return self._prototxt

    @property
    def net(self) -> CaffeNet:
        if self._net is None:
            log.info("start loading caffe model from %s", self.model_path)
            with open(self.model_path, "rb") as f:
                self._net = parse_caffemodel(f.read())
            log.info("load caffe model done")
        return self._net

    def copy_parameters(self, model):
        by_name = self.net.by_name()
        new_params = self._copy_module(model, model.params, by_name)
        model.params = new_params
        return model

    def _copy_module(self, module, params, by_name):
        from bigdl_tpu.nn.containers import Container
        if isinstance(module, Container):
            out = dict(params) if isinstance(params, dict) else params
            for i, child in enumerate(module.modules):
                key = str(i)
                if isinstance(params, dict) and key in params:
                    out[key] = self._copy_module(child, params[key], by_name)
            return out
        if not isinstance(params, dict) or not (
                "weight" in params or "bias" in params):
            return params
        name = module.get_name()
        layer = by_name.get(name)
        if layer is None:
            if self.match_all:
                raise ValueError(
                    f"module {name} cannot map a layer in caffe model")
            log.info("%s uses initialized parameters", name)
            return params
        out = dict(params)
        for idx, pname in ((0, "weight"), (1, "bias")):
            if len(layer.blobs) <= idx:
                continue
            blob = layer.blobs[idx]
            if pname not in params:
                raise ValueError(f"{name} should contain {pname}")
            target = np.asarray(params[pname])
            if blob.data is None or blob.data.size != target.size:
                got = 0 if blob.data is None else blob.data.size
                raise ValueError(
                    f"{pname} element number is not equal between caffe layer "
                    f"and module {name}: caffe {got} (shape {blob.shape}), "
                    f"module {list(target.shape)}")
            log.info("load parameters for %s ...", name)
            out[pname] = blob.data.reshape(target.shape).astype(target.dtype)
        return out


def load(model, def_path: str, model_path: str, match_all: bool = True):
    """ref CaffeLoader.load / Module.loadCaffe (nn/Module.scala:35-39)."""
    return CaffeLoader(def_path, model_path, match_all).copy_parameters(model)
