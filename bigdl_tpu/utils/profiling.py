"""Per-layer cost attribution from compiled XLA programs.

The reference accumulates per-module wall time in ``forward``/``backward``
(nn/abstractnn/AbstractModule.scala:125-135) plus conv ``im2colTime``
(nn/SpatialConvolution.scala:72-77).  Under ``jax.jit`` a training step is
ONE fused XLA program, so there is no per-layer clock to read — but the
compiler knows exactly what each layer costs.  This module reborn-s the
reference's timing hooks the way SURVEY.md §2.3 prescribes: per-layer cost
from compiled-HLO cost analysis, scaled by the measured step time.

How it works:
 1. a recording pass runs the model forward once (eagerly, any input) and
    captures every container child's input via ``Module._probe``;
 2. each leaf layer's ``apply`` (and its value-and-grad, i.e. the cost it
    contributes to a *training* step) is lowered and compiled standalone;
    ``compiled.cost_analysis()['flops']`` is XLA's own number;
 3. the measured wall time of the real fused step is attributed to layers
    proportionally to their compiled training flops, and written into the
    existing ``forward_time``/``backward_time`` fields so ``get_times()``
    (the reference API) reports it.

Also here: ``collective_footprint`` — bytes moved by all-gather /
reduce-scatter / all-reduce / collective-permute in a compiled program,
the analog of the reference's "get weights average" / "aggregate gradient
time" Metrics split (optim/DistriOptimizer.scala:115-213), which measured
the two halves of its BlockManager all-reduce.
"""
from __future__ import annotations

import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8, "c64": 8, "c128": 16}


def _dtype_bytes(name: str) -> int:
    if name.startswith("f8") or name.startswith("s4") or name.startswith("u4"):
        return 1
    return _DTYPE_BYTES.get(name, 4)


def record_layer_inputs(model: Module, x, training: bool = False,
                        rng=None) -> list:
    """Run one eager forward, returning [(parent, index, child, input,
    child_params, child_buffers)] for every container-dispatched child.
    The dispatched params slice is recorded because nested containers'
    OO-shell ``.params`` is None — only the root holds the full tree."""
    model._built()
    records = []

    def probe(parent, idx, child, inp, p, b):
        records.append((parent, idx, child, inp, p, b))

    Module._probe = probe
    try:
        model.apply(model.params, x, buffers=model.buffers,
                    training=training,
                    rng=rng if rng is not None else jax.random.PRNGKey(0))
    finally:
        Module._probe = None
    return records


import os as _os


#: where each planning constant's value actually came from at import
#: time: "env" | "default" | "env-malformed-default".  Consumers that
#: report provenance (models/utils/perf.py's ici_gbps_source) must read
#: THIS, not re-read os.environ at call time — the env can change (or
#: be set malformed) after import without changing the constant.
_ENV_SOURCES: dict = {}


def _env_float(name: str, default: float) -> float:
    """Env override with a loud-but-survivable parse: a malformed value
    must not break `import bigdl_tpu.parallel` for code that never
    touches the roofline numbers.  Read at import time — set the vars
    before importing (they are planning constants, not runtime knobs)."""
    raw = _os.environ.get(name)
    if raw is None:
        _ENV_SOURCES[name] = "default"
        return default
    try:
        value = float(raw)
        _ENV_SOURCES[name] = "env"
        return value
    except ValueError:
        import warnings
        warnings.warn(f"{name}={raw!r} is not a number; using the "
                      f"default {default}")
        _ENV_SOURCES[name] = "env-malformed-default"
        return default


def env_source(name: str) -> str:
    """Provenance of a planning constant as read at import:
    "env", "default", or "env-malformed-default"."""
    return _ENV_SOURCES.get(name, "default")


#: planning numbers for the roofline attribution — default v5e (~197
#: TFLOP/s bf16 MXU peak, ~819 GB/s HBM).  Override for other chip
#: generations via BIGDL_TPU_PEAK_TFLOPS / BIGDL_TPU_HBM_GBPS (before
#: first import).  Only their RATIO matters for splitting a measured
#: step across layers, so being a generation off shifts the split, not
#: the total.
PEAK_FLOPS = _env_float("BIGDL_TPU_PEAK_TFLOPS", 197.0) * 1e12
PEAK_HBM_BYTES_S = _env_float("BIGDL_TPU_HBM_GBPS", 819.0) * 1e9


def _cost_of_compiled(compiled) -> tuple[float, float]:
    """(flops, bytes accessed) of a compiled program, per XLA."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # one dict per device on old jax
        cost = cost[0]
    return (float(cost.get("flops", 0.0) or 0.0),
            float(cost.get("bytes accessed", 0.0) or 0.0))




def _layer_flops(child: Module, params, buffers, inp, training: bool,
                 include_train: bool = True):
    """(fwd flops, train flops, fwd bytes, train bytes) of one layer,
    per XLA cost analysis."""
    rng = jax.random.PRNGKey(0)

    def fwd(p, i):
        y, _ = child.apply(p, i, buffers=buffers, training=training, rng=rng)
        return y

    lowered = jax.jit(fwd).lower(params, inp)
    f_fwd, b_fwd = _cost_of_compiled(lowered.compile())
    if not include_train:
        return f_fwd, f_fwd, b_fwd, b_fwd

    def train(p, i):
        def scalar(pp):
            y = fwd(pp, i)
            leaves = jax.tree_util.tree_leaves(y)
            return sum(jnp.sum(jnp.asarray(l).astype(jnp.float32))
                       for l in leaves)
        loss, grads = jax.value_and_grad(scalar)(p)
        return loss, grads

    try:
        lowered_t = jax.jit(train).lower(params, inp)
        f_train, b_train = _cost_of_compiled(lowered_t.compile())
    except Exception:
        f_train, b_train = f_fwd, b_fwd  # non-differentiable: fwd only
    return f_fwd, f_train, b_fwd, b_train


def profile_layers(model: Module, x, training: bool = True,
                   include_train: bool = True) -> list[dict]:
    """Per-LEAF-layer compiled flops for one forward and one training step.
    Returns [{'module', 'name', 'flops_fwd', 'flops_train'}] in execution
    order.  ``include_train=False`` skips the value-and-grad compile
    (flops_train then mirrors flops_fwd) — half the compile cost when the
    caller only needs forward flops (e.g. pipeline stage balancing)."""
    records = record_layer_inputs(model, x, training=training)
    rows = []
    for parent, idx, child, inp, p, b in records:
        if getattr(child, "modules", None):
            continue  # containers: attributed via their leaves
        try:
            f_fwd, f_train, b_fwd, b_train = _layer_flops(
                child, p, b, inp, training, include_train=include_train)
        except Exception:
            f_fwd = f_train = b_fwd = b_train = 0.0  # XLA folds away
        rows.append({"module": child, "name": child.get_name(),
                     "flops_fwd": f_fwd, "flops_train": f_train,
                     "bytes_fwd": b_fwd, "bytes_train": b_train})
    return rows


def attribute_step_time(model: Module, x, step_time_s: float,
                        training: bool = True,
                        mode: str = "roofline") -> list[dict]:
    """Distribute a measured fused-step wall time over layers and write
    the result into each layer's ``forward_time``/``backward_time`` so
    ``get_times()`` — the reference's per-module timing API — reports
    per-layer cost from a *jitted* run.

    ``mode="roofline"`` (default) weighs each layer by
    max(flops/PEAK_FLOPS, bytes/PEAK_HBM_BYTES_S) — a bandwidth-bound
    BatchNorm or transpose is billed for its HBM traffic instead of its
    ~0 flops (which the old flop-share split mis-billed to the convs).
    ``mode="flops"`` keeps the pure flop-proportional split.  Each row
    carries ``bound`` ("compute"/"memory") for roofline mode."""
    if mode not in ("roofline", "flops"):
        raise ValueError(f"mode must be 'roofline'|'flops', got {mode!r}")
    rows = profile_layers(model, x, training=training)

    def weight(flops, bytes_):
        if mode == "flops":
            return flops
        return max(flops / PEAK_FLOPS, bytes_ / PEAK_HBM_BYTES_S)

    total = sum(weight(r["flops_train"], r["bytes_train"]) for r in rows) or 1.0
    for r in rows:
        w = weight(r["flops_train"], r["bytes_train"])
        t = (w / total) * step_time_s
        if mode == "roofline":
            r["bound"] = ("compute"
                          if r["flops_train"] / PEAK_FLOPS
                          >= r["bytes_train"] / PEAK_HBM_BYTES_S
                          else "memory")
        # forward/backward split from the compiled fwd vs train weights
        # (the backward ~2x forward rule falls out of the numbers
        # instead of being assumed)
        w_fwd = weight(r["flops_fwd"], r["bytes_fwd"])
        fwd_frac = min(w_fwd / w, 1.0) if w > 0 else 1.0
        r["time_s"] = t
        r["attribution"] = mode
        r["module"].forward_time += t * fwd_frac
        r["module"].backward_time += t * (1.0 - fwd_frac)
    return rows


def measure_layer_times(model: Module, x, training: bool = True,
                        iters: int = 10, warmup: int = 2) -> list[dict]:
    """ACTUAL wall time per layer, measured by executing each leaf layer's
    compiled forward (and, when differentiable, value-and-grad) standalone
    on the current backend (ref nn/abstractnn/AbstractModule.scala:125-135
    accumulates real per-module time the same way, because the reference
    executes layer by layer).

    Honest caveat, stated in the row ("granularity": "standalone"): in the
    real training step XLA fuses layers together, so standalone sums run
    slower than the fused step — use these to RANK layers and find the
    memory/compute balance, and ``attribute_step_time`` (roofline over the
    measured fused step) for shares that add up to the real step time.
    Results are also written into forward_time/backward_time."""
    import time

    records = record_layer_inputs(model, x, training=training)
    rows = []
    for parent, idx, child, inp, p, b in records:
        if getattr(child, "modules", None):
            continue
        rng = jax.random.PRNGKey(0)

        def fwd(pp, i):
            y, _ = child.apply(pp, i, buffers=b, training=training, rng=rng)
            return y

        def train_fn(pp, i):
            def scalar(q):
                leaves = jax.tree_util.tree_leaves(fwd(q, i))
                return sum(jnp.sum(jnp.asarray(l).astype(jnp.float32))
                           for l in leaves)
            return jax.value_and_grad(scalar)(pp)

        def timed(fn):
            try:
                jitted = jax.jit(fn)
                out = None
                for _ in range(warmup):
                    out = jitted(p, inp)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = jitted(p, inp)
                jax.block_until_ready(out)
                # host transfer: block_until_ready alone does not
                # guarantee completion on every backend
                _ = float(jnp.asarray(
                    jax.tree_util.tree_leaves(out)[0]).ravel()[0])
                return (time.perf_counter() - t0) / iters
            except Exception:
                return None

        t_fwd = timed(fwd)
        t_train = timed(train_fn) if training else t_fwd
        row = {"module": child, "name": child.get_name(),
               "measured_fwd_s": t_fwd, "measured_train_s": t_train,
               "granularity": "standalone"}
        rows.append(row)
        if t_fwd is not None:
            child.forward_time += t_fwd
        if t_train is not None and t_fwd is not None:
            child.backward_time += max(t_train - t_fwd, 0.0)
    return rows


def _shape_bytes(shape_str: str) -> int:
    """bytes of an HLO shape literal like 'f32[128,1024]{1,0}' or a tuple
    '(f32[8], f32[8])'."""
    total = 0
    for m in re.finditer(r"\b([a-z][a-z0-9]*)\[([\d,]*)\]", shape_str):
        dtype, dims = m.group(1), m.group(2)
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _dtype_bytes(dtype)
    return total


#: v5e ICI: ~45 GB/s per link per direction; ring collectives stream both
#: directions of one axis concurrently, so ~90 GB/s effective per chip is
#: the planning number (the "How to Scale Your Model" recipe: bytes moved /
#: ICI bandwidth = collective time; bytes from the compiled program below).
ICI_GBPS_DEFAULT = _env_float("BIGDL_TPU_ICI_GBPS", 90.0)


def wire_bytes(footprint: dict[str, int], n: int) -> float:
    """Bytes a ring implementation actually moves per chip for the
    collectives in a ``collective_footprint`` dict, on an ``n``-device
    axis.  The footprint records bytes *produced* (HLO result shapes);
    ring algorithms move:

      all-gather:          out × (N-1)/N      (each chip receives the
                                               other N-1 slices)
      reduce-scatter:      in × (N-1)/N = out × (N-1)
      all-reduce:          out × 2(N-1)/N     (reduce-scatter + all-gather)
      collective-permute:  out                (one hop, all bytes)
      all-to-all:          out × (N-1)/N

    With the DP cycle (bf16 all-gather of weights + bf16 reduce-scatter of
    grads, parameters/AllReduceParameter.scala's split) this comes to
    2(N-1)/N x param-bytes — the classic ring all-reduce volume."""
    if n <= 1:
        return 0.0
    factors = {"all-gather": (n - 1) / n, "reduce-scatter": float(n - 1),
               "all-reduce": 2 * (n - 1) / n, "collective-permute": 1.0,
               "all-to-all": (n - 1) / n}
    return float(sum(bytes_ * factors.get(op, 1.0)
                     for op, bytes_ in footprint.items()))


def predict_ici_efficiency(compute_s: float, wire_bytes_per_chip: float,
                           ici_gbps: float = ICI_GBPS_DEFAULT) -> dict:
    """Roofline weak-scaling prediction as an INTERVAL, not a point.

    The truth depends on how much of the collective XLA's latency-hiding
    scheduler hides behind compute, which cannot be known without a
    profile from the target pod; what CAN be known are the two bounds:

      zero overlap:  step = compute + comm   (serial; the floor)
      full overlap:  step = max(compute, comm)  (comm fully hidden; the
                     ceiling — parameters.py:16-17 notes XLA does overlap
                     the DP all-gather with the forward pass in practice)

    ``predicted_efficiency`` stays the conservative zero-overlap bound —
    a claim against a scaling target must hold at the floor."""
    comm_s = wire_bytes_per_chip / (ici_gbps * 1e9)
    step_serial = compute_s + comm_s
    step_overlap = max(compute_s, comm_s)
    eff_lo = compute_s / step_serial if step_serial else 1.0
    eff_hi = compute_s / step_overlap if step_overlap else 1.0
    return {"predicted_comm_s": comm_s, "predicted_step_s": step_serial,
            "predicted_step_s_full_overlap": step_overlap,
            "predicted_efficiency": eff_lo,
            "predicted_efficiency_interval": [eff_lo, eff_hi]}


def collective_footprint(compiled_text: str) -> dict[str, int]:
    """Bytes produced per step by each collective family in an optimized
    HLO dump (``jitted.lower(...).compile().as_text()``).  The all-gather
    row is the reference's getWeights ("get weights average") traffic; the
    reduce-scatter/all-reduce row is putGradients+aggregate ("aggregate
    gradient time") traffic."""
    out = {"all-gather": 0, "reduce-scatter": 0, "all-reduce": 0,
           "collective-permute": 0, "all-to-all": 0}
    for line in compiled_text.splitlines():
        s = line.strip()
        m = re.match(r"^(?:ROOT )?%?[\w.\-]+ = (\(?[^)=]*\)?) (all-gather|"
                     r"reduce-scatter|all-reduce|collective-permute|"
                     r"all-to-all)(-start|-done)?\(", s)
        if not m:
            continue
        shape, op, phase = m.group(1), m.group(2), m.group(3)
        if phase == "-done":
            continue  # the async pair's bytes are counted on -start
        if phase == "-start":
            # async start shapes are (operand..., result...) tuples with
            # one result per operand; count the result half
            shapes = re.findall(r"[a-z][a-z0-9]*\[[\d,]*\](?:\{[\d,]*\})?",
                                shape)
            if shapes:
                shape = " ".join(shapes[len(shapes) // 2:])
        out[op] += _shape_bytes(shape)
    return {k: v for k, v in out.items() if v}
