"""Module / object persistence (ref utils/File.scala:26-122 — java
serialization with hdfs: support; here pickle with numpy-materialized
arrays, the Python-native analog).  The orbax-style training checkpoints
live in ``bigdl_tpu.optim.checkpoint``; this is the ``Module.save`` /
``Module.load`` whole-model path (ref nn/Module.scala:27-39)."""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import numpy as np


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def save(obj: Any, path: str, overwrite: bool = False) -> None:
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(obj, f)
    os.replace(tmp, path)


def load(path: str) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


def save_module(module, path: str, overwrite: bool = False) -> None:
    """Persist a module (hyperparams + params + buffers) as one file."""
    state = {
        "module": module,  # picklable: jit caches dropped via __getstate__
        "params": _to_host(module.params),
        "buffers": _to_host(module.buffers),
    }
    save(state, path, overwrite=overwrite)


def load_module(path: str):
    state = load(path)
    module = state["module"]
    module.params = jax.tree_util.tree_map(lambda a: a, state["params"])
    module.buffers = state["buffers"]
    return module
