"""Module / object persistence (ref utils/File.scala:26-122 — java
serialization with hdfs: support).

Two deliberate upgrades over a naive pickle:

1. **Remote-capable**: every read/write flows through
   ``bigdl_tpu.utils.fs`` so ``gs://`` / ``hdfs://`` / ``memory://`` paths
   work wherever a local path does (pod workers cannot checkpoint to
   local disk; the reference has the same property via hdfs:).
2. **No live objects in checkpoints**: the on-disk format (version 1) is
   a dict of plain builtins + numpy arrays — a *spec* describing each
   module (class path + hyperparameter state + children) plus the
   param/buffer array trees.  Pickled live modules break on any class
   rename/refactor; arrays + a declarative spec survive, and
   ``load_module(path, template=...)`` restores into caller-constructed
   architecture without consulting the spec's class names at all.

The orbax-style training checkpoints live in ``bigdl_tpu.optim``; this is
the ``Module.save`` / ``Module.load`` whole-model path
(ref nn/Module.scala:27-39).
"""
from __future__ import annotations

import importlib
import pickle
from typing import Any, Optional

import jax
import numpy as np

from bigdl_tpu.utils import fs

FORMAT = "bigdl_tpu.module"
VERSION = 1

_PLAIN = (int, float, bool, str, bytes, type(None), np.ndarray, np.generic)
# OO-shell state that is NOT a hyperparameter (rebuilt fresh on load)
_SHELL_ATTRS = {"params", "buffers", "grad_params", "output", "grad_input",
                "forward_time", "backward_time", "modules"}
_SHELL_PREFIXES = ("_jit", "_rng", "_vjp", "_fwd", "_step")


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def _is_plain(v) -> bool:
    if isinstance(v, _PLAIN):
        return True
    if isinstance(v, (tuple, list)):
        return all(_is_plain(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, (str, int)) and _is_plain(x)
                   for k, x in v.items())
    return False


def _class_path(cls: type) -> str:
    return f"{cls.__module__}:{cls.__qualname__}"


def _resolve_class(path: str) -> type:
    mod, _, qual = path.partition(":")
    obj = importlib.import_module(mod)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


def _encode_value(v):
    from bigdl_tpu.nn.module import Criterion, Module

    if isinstance(v, jax.Array):
        return np.asarray(v)  # device arrays persist as host numpy
    if isinstance(v, Module):
        return {"__kind__": "module", **module_spec(v)}
    if isinstance(v, Criterion):
        return {"__kind__": "object", "class": _class_path(type(v)),
                "state": _encode_state(v.__dict__)}
    if isinstance(v, type):
        return {"__kind__": "class", "class": _class_path(v)}
    if isinstance(v, (tuple, list)):
        kind = "tuple" if isinstance(v, tuple) else "list"
        if _is_plain(v):
            return v
        return {"__kind__": kind, "items": [_encode_value(x) for x in v]}
    if isinstance(v, dict) and not _is_plain(v):
        return {"__kind__": "dict",
                "items": {k: _encode_value(x) for k, x in v.items()}}
    if _is_plain(v):
        return v
    raise TypeError(
        f"cannot serialize hyperparameter of type {type(v).__name__}; "
        f"only builtins, numpy arrays, classes, Modules and Criterions "
        f"belong in module state")


def _decode_value(v):
    if isinstance(v, dict) and "__kind__" in v:
        kind = v["__kind__"]
        if kind == "module":
            return build_module(v)
        if kind == "object":
            cls = _resolve_class(v["class"])
            obj = cls.__new__(cls)
            obj.__dict__.update(_decode_state(v["state"]))
            # criterion shells carry a jit cache; rebuild empty
            if not hasattr(obj, "_jit_cache"):
                obj._jit_cache = {}
            return obj
        if kind == "class":
            return _resolve_class(v["class"])
        if kind == "tuple":
            return tuple(_decode_value(x) for x in v["items"])
        if kind == "list":
            return [_decode_value(x) for x in v["items"]]
        if kind == "dict":
            return {k: _decode_value(x) for k, x in v["items"].items()}
        raise ValueError(f"unknown encoded kind {kind!r}")
    return v


def _encode_state(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        if k in _SHELL_ATTRS or any(k.startswith(p) for p in _SHELL_PREFIXES):
            continue
        from bigdl_tpu.nn.module import Criterion, Module
        if (callable(v) and not isinstance(v, (type, Module, Criterion))):
            if k.startswith("_"):
                continue  # private machinery (caches etc.), rebuilt lazily
            raise TypeError(
                f"cannot serialize callable hyperparameter {k!r} "
                f"({type(v).__name__}); persistence would silently drop "
                f"it — hold a Module/class instead of a bare function")
        out[k] = _encode_value(v)
    return out


def _decode_state(d: dict) -> dict:
    return {k: _decode_value(v) for k, v in d.items()}


def module_spec(module) -> dict:
    """Declarative description: class path + hyperparameter state +
    children.  Contains no class objects or live instances."""
    spec = {"class": _class_path(type(module)),
            "state": _encode_state(module.__dict__)}
    children = getattr(module, "modules", None)
    if children is not None:
        spec["children"] = [module_spec(m) for m in children]
    return spec


def build_module(spec: dict):
    """Instantiate a module tree from its spec (no saved class references
    are executed — classes resolve by name against the current code)."""
    from bigdl_tpu.nn.module import Module

    cls = _resolve_class(spec["class"])
    obj = cls.__new__(cls)
    Module.__init__(obj)  # baseline shell state
    obj.__dict__.update(_decode_state(spec["state"]))
    if "children" in spec:
        obj.modules = [build_module(s) for s in spec["children"]]
    return obj


# --------------------------------------------------------------------- #
# generic object IO (driver state tables etc. — plain data only)        #
# --------------------------------------------------------------------- #
def save(obj: Any, path: str, overwrite: bool = False) -> None:
    if fs.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    fs.atomic_write(path, pickle.dumps(obj))


def load(path: str) -> Any:
    with fs.open_file(path, "rb") as f:
        return pickle.load(f)


def latest_checkpoint(directory: str):
    """Newest ``model.<n>`` / ``state.<n>`` pair written by
    ``Optimizer.set_checkpoint`` under ``directory`` (any fs scheme), as
    ``(model_path, state_path, n)`` — or None when the directory holds no
    complete pair.  The resume counterpart of the reference's
    checkpoint-and-restart cycle (models/lenet/Train.scala:55-68 loads
    model.<n> + state.<n> by hand)."""
    try:
        names = fs.listdir(directory)
    except FileNotFoundError:
        return None  # no checkpoints yet; scheme/permission errors raise
    models, states = set(), set()
    for name in names:
        stem, _, idx = name.partition(".")
        if not idx.isdigit():
            continue
        if stem == "model":
            models.add(int(idx))
        elif stem == "state":
            states.add(int(idx))
    complete = sorted(models & states)
    if not complete:
        return None
    n = complete[-1]
    return (fs.join(directory, f"model.{n}"),
            fs.join(directory, f"state.{n}"), n)


# --------------------------------------------------------------------- #
# module IO                                                             #
# --------------------------------------------------------------------- #
def save_module(module, path: str, overwrite: bool = False) -> None:
    """Persist spec + params + buffers (format v1, no live objects)."""
    state = {
        "format": FORMAT,
        "version": VERSION,
        "spec": module_spec(module),
        "params": _to_host(module.params),
        "buffers": _to_host(module.buffers),
    }
    save(state, path, overwrite=overwrite)


def load_module(path: str, template=None):
    """Load a saved module.

    With ``template`` (an un/re-built instance of the architecture), the
    arrays are restored into it and the stored spec is ignored — this
    path is immune to class renames.  Without a template the spec rebuilds
    the tree by class name.  Old (round-1) checkpoints that pickled the
    live module still load.
    """
    state = load(path)
    if not (isinstance(state, dict) and state.get("format") == FORMAT):
        # legacy format: {"module": <pickled Module>, "params", "buffers"}
        module = state["module"]
        module.params = jax.tree_util.tree_map(lambda a: a, state["params"])
        module.buffers = state["buffers"]
        return module
    if state["version"] > VERSION:
        raise ValueError(f"checkpoint version {state['version']} is newer "
                         f"than this library ({VERSION})")
    module = template if template is not None else build_module(state["spec"])
    params = state["params"]
    if template is not None:
        # structure + shape check without materializing a throwaway init
        ref = jax.eval_shape(module.init, jax.random.PRNGKey(0))
        want = jax.tree_util.tree_structure(ref)
        got = jax.tree_util.tree_structure(params)
        if want != got:
            raise ValueError(
                f"checkpoint param tree does not match template: "
                f"{got} vs {want}")
        for (kp, r), l in zip(
                jax.tree_util.tree_flatten_with_path(ref)[0],
                jax.tree_util.tree_leaves(params)):
            if tuple(r.shape) != tuple(np.shape(l)):
                name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                                for p in kp)
                raise ValueError(
                    f"checkpoint param {name} has shape {np.shape(l)}, "
                    f"template expects {tuple(r.shape)}")
    module.params = params
    module.buffers = state["buffers"]
    if module.grad_params is None:
        module.zero_grad_parameters()
    return module
