"""Torch-compatible Mersenne-Twister RNG (host side).

Rebuild of the reference's ``utils/RandomGenerator.scala:23-265``, which is a
faithful MT19937 matching Torch7 so that layer initializations and test
oracles are bit-reproducible against Torch.  We implement the *standard*
MT19937 algorithm (Matsumoto & Nishimura, public) with Torch's seeding and
double-generation conventions:

- state N=624, M=397, seeded by the LCG ``s[i] = 1812433253*(s[i-1] ^ (s[i-1]>>30)) + i``
- ``random()`` draws 53-bit doubles in [0,1) via (a*2^26+b)/2^53
- ``normal`` uses the polar (Marsaglia) method with one cached value,
  matching Torch's ``torch.normal`` consumption order.

This RNG runs on host (numpy) and seeds parameter init; on-device stochastic
ops (Dropout) use ``jax.random`` keys derived from it.
"""
from __future__ import annotations

import threading

import numpy as np

_N = 624
_M = 397
_MATRIX_A = 0x9908B0DF
_UPPER_MASK = 0x80000000
_LOWER_MASK = 0x7FFFFFFF


def _native_lib():
    """The C MT19937 backend (csrc/bigdl_tpu_native.cpp) or None."""
    try:
        from bigdl_tpu import native
        return native.get()
    except Exception:
        return None


class RandomGenerator:
    def __init__(self, seed: int = 5489):
        self._mt = np.zeros(_N, dtype=np.uint64)
        self._mti = _N + 1
        self._normal_cached = None
        self._native = None  # C generator handle; same algorithm bit-for-bit
        nl = _native_lib()
        if nl is not None:
            self._native = nl.mt_new(seed)
        self.set_seed(seed)

    def __del__(self):
        try:  # may run at interpreter shutdown with modules half-torn-down
            if getattr(self, "_native", None) is not None:
                nl = _native_lib()
                if nl is not None:
                    nl.mt_free(self._native)
        except Exception:
            pass

    def set_seed(self, seed: int) -> "RandomGenerator":
        self._seed = seed
        if self._native is not None:
            _native_lib().mt_set_seed(self._native, seed)
            return self
        mt = self._mt
        mt[0] = seed & 0xFFFFFFFF
        for i in range(1, _N):
            mt[i] = (1812433253 * (mt[i - 1] ^ (mt[i - 1] >> np.uint64(30))) + i) & 0xFFFFFFFF
        self._mti = _N
        self._normal_cached = None
        return self

    def get_seed(self) -> int:
        return self._seed

    def _generate(self) -> None:
        mt = self._mt.astype(np.uint64)
        mag01 = np.array([0, _MATRIX_A], dtype=np.uint64)
        # standard block update, vectorized in three strips
        y = (mt[:_N - _M] & _UPPER_MASK) | (mt[1:_N - _M + 1] & _LOWER_MASK)
        mt[:_N - _M] = mt[_M:] ^ (y >> np.uint64(1)) ^ mag01[(y & np.uint64(1)).astype(np.int64)]
        y = (mt[_N - _M:_N - 1] & _UPPER_MASK) | (mt[_N - _M + 1:] & _LOWER_MASK)
        mt[_N - _M:_N - 1] = mt[:_M - 1] ^ (y >> np.uint64(1)) ^ mag01[(y & np.uint64(1)).astype(np.int64)]
        y = (mt[_N - 1] & np.uint64(_UPPER_MASK)) | (mt[0] & np.uint64(_LOWER_MASK))
        mt[_N - 1] = mt[_M - 1] ^ (y >> np.uint64(1)) ^ mag01[int(y & np.uint64(1))]
        self._mt = mt
        self._mti = 0

    def _next_uint32(self) -> int:
        if self._native is not None:
            return _native_lib().mt_random_int(self._native)
        if self._mti >= _N:
            self._generate()
        y = int(self._mt[self._mti])
        self._mti += 1
        y ^= y >> 11
        y ^= (y << 7) & 0x9D2C5680
        y &= 0xFFFFFFFF
        y ^= (y << 15) & 0xEFC60000
        y &= 0xFFFFFFFF
        y ^= y >> 18
        return y

    # -- draws -------------------------------------------------------------
    def random_int(self) -> int:
        return self._next_uint32()

    def random(self) -> float:
        """53-bit double in [0,1)."""
        if self._native is not None:
            return _native_lib().mt_random(self._native)
        a = self._next_uint32() >> 5
        b = self._next_uint32() >> 6
        return (a * 67108864.0 + b) * (1.0 / 9007199254740992.0)

    def uniform(self, a: float = 0.0, b: float = 1.0) -> float:
        return self.random() * (b - a) + a

    def normal(self, mean: float = 0.0, stdv: float = 1.0) -> float:
        if self._native is not None:
            return float(_native_lib().mt_normal(self._native, 1, mean, stdv)[0])
        if self._normal_cached is not None:
            v = self._normal_cached
            self._normal_cached = None
            return mean + stdv * v
        while True:
            u = 2.0 * self.random() - 1.0
            v = 2.0 * self.random() - 1.0
            s = u * u + v * v
            if 0.0 < s < 1.0:
                break
        mult = np.sqrt(-2.0 * np.log(s) / s)
        self._normal_cached = v * mult
        return mean + stdv * (u * mult)

    def exponential(self, lam: float) -> float:
        return -1.0 / lam * np.log(1.0 - self.random())

    def cauchy(self, median: float, sigma: float) -> float:
        return median + sigma * np.tan(np.pi * (self.random() - 0.5))

    def log_normal(self, mean: float, stdv: float) -> float:
        zm = mean * mean
        zs = stdv * stdv
        return float(np.exp(self.normal(np.log(zm / np.sqrt(zs + zm)), np.sqrt(np.log(zs / zm + 1)))))

    def geometric(self, p: float) -> int:
        return int(np.log(1.0 - self.random()) / np.log(p)) + 1

    def bernoulli(self, p: float) -> bool:
        return self.random() <= p

    # -- array helpers (for init parity tests) ----------------------------
    def uniform_array(self, n: int, a: float = 0.0, b: float = 1.0) -> np.ndarray:
        if self._native is not None:
            return _native_lib().mt_uniform(self._native, n, a, b)
        return np.array([self.uniform(a, b) for _ in range(n)])

    def normal_array(self, n: int, mean: float = 0.0, stdv: float = 1.0) -> np.ndarray:
        if self._native is not None:
            return _native_lib().mt_normal(self._native, n, mean, stdv)
        return np.array([self.normal(mean, stdv) for _ in range(n)])

    def bernoulli_array(self, n: int, p: float) -> np.ndarray:
        if self._native is not None:
            return _native_lib().mt_bernoulli(self._native, n, p)
        return np.array([1.0 if self.bernoulli(p) else 0.0 for _ in range(n)])

    def randperm(self, n: int) -> np.ndarray:
        """1-based random permutation (Torch randperm semantics)."""
        if self._native is not None:
            return _native_lib().mt_randperm(self._native, n)
        perm = np.arange(1, n + 1)
        for i in range(n - 1, 0, -1):
            j = int(self.random() * (i + 1))
            perm[i], perm[j] = perm[j], perm[i]
        return perm


class _ThreadLocalRNG(threading.local):
    gen: RandomGenerator = None  # created on first use, not at import
    # (constructing a RandomGenerator may build/load the native library;
    # keep module import free of that side effect)


_tls = _ThreadLocalRNG()


def _gen() -> RandomGenerator:
    if _tls.gen is None:
        _tls.gen = RandomGenerator()
    return _tls.gen


class RNG:
    """Global thread-shared generator facade (ref RandomGenerator.scala RNG)."""

    @staticmethod
    def current() -> RandomGenerator:
        return _gen()

    @staticmethod
    def set_seed(seed: int) -> None:
        _gen().set_seed(seed)

    @staticmethod
    def uniform(a: float = 0.0, b: float = 1.0) -> float:
        return _gen().uniform(a, b)

    @staticmethod
    def normal(mean: float = 0.0, stdv: float = 1.0) -> float:
        return _gen().normal(mean, stdv)

    @staticmethod
    def bernoulli(p: float) -> bool:
        return _gen().bernoulli(p)
