"""Incremental, resumable measurement artifacts — the shared protocol.

Every measurement tool in this package (attention_bench, lm_perf,
tpu_profile_bench, tunnel_stress) follows one contract, born of a
backend with short availability windows (NOTES_r4.md):

- the artifact is rewritten ATOMICALLY after every row, so a sweep
  killed when the window closes keeps everything it measured;
- ``complete`` stays false until the final flush, so the opportunist
  runner keeps firing a stage until its sweep truly finished;
- on restart, rows are reused only when the caller's ``match``
  predicate accepts them (platform + full configuration + iteration
  count — a CPU debug row must never publish as a TPU number).

This module is that contract's single implementation.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Callable

log = logging.getLogger("bigdl_tpu.artifacts")


def write_artifact(path: str, result: dict) -> None:
    """Atomic JSON rewrite (no-op when path is falsy): a kill mid-write
    must never leave truncated JSON that zeroes out resume progress."""
    if not path:
        return
    from bigdl_tpu.utils import fs
    fs.atomic_write(path, (json.dumps(result, indent=2) + "\n").encode())


def load_artifact(path: str):
    """The prior artifact document, or None.  A MISSING file resumes
    nothing silently (cold start); an EXISTING-but-unparseable one
    (truncated by a kill mid-flush on a non-atomic writer, disk
    corruption) is treated as absent with a loud warning — the sweep
    restarts instead of crashing the round on a json decode error.
    Parse ONCE per run: callers indexing several sections must not
    re-read a file a concurrent runner may be rewriting between
    reads."""
    if path and os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            log.warning(
                "artifact %s exists but is unreadable (%s: %s) — "
                "treating it as absent and restarting the sweep",
                path, type(e).__name__, e)
    return None


def index_rows(doc, *, match: Callable[[dict, dict], bool],
               key: Callable[[dict], object],
               section: str = "rows") -> dict:
    """Reusable rows of one section, keyed by ``key(row)``.
    ``match(document, row)`` decides reuse — it sees the whole document
    so platform/config headers can gate every row."""
    prev: dict = {}
    if isinstance(doc, dict):
        for r in doc.get(section, []):
            if match(doc, r):
                prev[key(r)] = r
    return prev


def load_resumable_rows(path: str, *, match: Callable[[dict, dict], bool],
                        key: Callable[[dict], object],
                        section: str = "rows") -> dict:
    """One-shot convenience: load_artifact + index_rows."""
    return index_rows(load_artifact(path), match=match, key=key,
                      section=section)
