"""Chunked host->device staging — the shared transfer discipline.

The tunneled TPU backend dies on oversized single-buffer transfers (the
round-4 relay was lost to one ~154 MB host->device push, NOTES_r4.md);
every tool that stages real batches must therefore slice the upload
along the leading dim into <=32 MB pieces with exactly one slice in
flight at a time, then assemble on device.  bench.py carried this
inline; serving needs it too, so the pattern lives here once.

Resilience (bigdl_tpu.resilience): each slice upload runs under
``with_backoff`` — a transient relay wobble retries with exponential
backoff AND halves the chunk size toward an 8 MB floor (a flaky tunnel
degrades to smaller frames instead of dying), while a lost backend
surfaces as a classified ``BackendLostError`` after bounded attempts
instead of the round-4 indefinite hang.

One devicewise concat costs a copy; losing the backend costs the round.
"""
from __future__ import annotations

from bigdl_tpu.resilience.faults import fault_point
from bigdl_tpu.resilience.retry import with_backoff

#: Conservative per-transfer ceiling; the relay died somewhere between
#: 32 MB (fine in round 4) and ~154 MB (fatal).
DEFAULT_CHUNK_BYTES = 32 << 20

#: Downshift floor: halving below 8 MB buys no more relay safety and
#: multiplies per-slice dispatch overhead.
MIN_CHUNK_BYTES = 8 << 20


def chunked_device_put(x_host, dtype=None, *,
                       chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                       device=None,
                       max_retries: int = 4,
                       min_chunk_bytes: int = MIN_CHUNK_BYTES):
    """Stage ``x_host`` onto the device in <= ``chunk_bytes`` slices
    along the leading dim, one in flight at a time, and return the
    assembled (blocked-until-ready) device array.

    ``dtype`` is the wire/device dtype (chunk sizing uses it — a f64
    host batch uploaded as bf16 moves a quarter of the bytes).  Arrays
    that fit in one chunk take the single device_put fast path; 0-d
    arrays always do.

    ``device`` may be a ``jax.sharding.Sharding`` (e.g. a placement
    slice's ``NamedSharding``): each chunk then lands pre-sharded —
    dtype conversion happens host-side and ``jax.device_put`` goes
    straight to the sharded layout, never materializing the dense
    array on one device first.  When dim 0 is itself sharded, chunk
    row counts are rounded to a multiple of the dim-0 shard count so
    every slice splits evenly.

    A slice that fails transiently retries up to ``max_retries`` times
    with backoff, halving the working chunk size toward
    ``min_chunk_bytes`` before each retry; exhausted retries and dead
    backends raise :class:`~bigdl_tpu.resilience.errors.BackendLostError`.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.obs.tracer import get_tracer
    _tr = get_tracer()

    x_host = np.asarray(x_host)
    target = jnp.dtype(dtype) if dtype is not None else x_host.dtype
    is_sharding = isinstance(device, jax.sharding.Sharding)

    def _put(a):
        if is_sharding:
            # host-side dtype conversion (ml_dtypes covers bf16), then
            # one device_put directly onto the sharded layout — going
            # through jnp.asarray would stage the dense array on the
            # default device first, the detour this path exists to avoid
            arr = np.asarray(a, target)
            return jax.device_put(arr, device)
        arr = jnp.asarray(a, target)
        if device is not None:
            arr = jax.device_put(arr, device)
        return arr

    if x_host.ndim == 0 or x_host.size == 0:
        def _small():
            fault_point("transfer.chunk", rows=0, bytes=0)
            out = _put(x_host)
            out.block_until_ready()
            return out
        return with_backoff(_small, retries=max_retries, label="h2d put")

    itemsize = jnp.dtype(target).itemsize
    per_row = max(1, int(x_host[0:1].size) * itemsize)
    n = x_host.shape[0]
    # dim-0 shard count: chunks must split evenly across it
    shard0 = 1
    if is_sharding:
        try:
            shard0 = max(1, n // device.shard_shape(x_host.shape)[0])
        except Exception:  # noqa: BLE001 — unsized/indivisible: single put
            shard0 = n if n > 0 else 1
    # mutable so the on_transient hook below downshifts mid-transfer;
    # later slices keep the reduced size (the relay stays flaky)
    state = {"chunk": max(int(chunk_bytes), per_row * shard0)}
    floor = max(1, min(int(min_chunk_bytes), state["chunk"]))

    def _downshift(attempt, exc):
        new = max(floor, state["chunk"] // 2)
        if new < state["chunk"]:
            state["chunk"] = new
            from bigdl_tpu.obs import get_registry
            get_registry().counter("resilience/transfer_downshifts").add(1)
            _tr.instant("h2d/downshift", cat="transfer", chunk_bytes=new)

    parts = []
    i = 0
    while i < n:
        def _stage(i=i):
            rows = max(1, state["chunk"] // per_row)
            if shard0 > 1:
                rows = max(shard0, rows - rows % shard0)
            piece = x_host[i:i + rows]
            with _tr.span("h2d/chunk", cat="transfer", offset_rows=i,
                          rows=int(piece.shape[0]),
                          bytes=int(piece.size) * itemsize):
                fault_point("transfer.chunk", offset_rows=i,
                            rows=int(piece.shape[0]),
                            bytes=int(piece.size) * itemsize)
                p = _put(piece)
                # one in-flight slice at a time — device_put is async,
                # so building the list without blocking would enqueue
                # every slice at once, recreating the oversized burst
                p.block_until_ready()
            return p, int(piece.shape[0])
        p, took = with_backoff(_stage, retries=max_retries,
                               on_transient=_downshift, label="h2d chunk")
        parts.append(p)
        i += took
    if len(parts) == 1:
        return parts[0]
    with _tr.span("h2d/assemble", cat="transfer", chunks=len(parts)):
        out = jnp.concatenate(parts, axis=0)
        if is_sharding:
            # re-commit: concatenation of sharded parts lets XLA pick
            # the output layout; the caller was promised ``device``.
            # Device-to-device only — no further host transfer.
            out = jax.device_put(out, device)
        out.block_until_ready()
    del parts  # don't hold a second copy of the batch alive
    return out
