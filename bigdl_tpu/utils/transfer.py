"""Chunked host->device staging — the shared transfer discipline.

The tunneled TPU backend dies on oversized single-buffer transfers (the
round-4 relay was lost to one ~154 MB host->device push, NOTES_r4.md);
every tool that stages real batches must therefore slice the upload
along the leading dim into <=32 MB pieces with exactly one slice in
flight at a time, then assemble on device.  bench.py carried this
inline; serving needs it too, so the pattern lives here once.

One devicewise concat costs a copy; losing the backend costs the round.
"""
from __future__ import annotations

#: Conservative per-transfer ceiling; the relay died somewhere between
#: 32 MB (fine in round 4) and ~154 MB (fatal).
DEFAULT_CHUNK_BYTES = 32 << 20


def chunked_device_put(x_host, dtype=None, *,
                       chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                       device=None):
    """Stage ``x_host`` onto the device in <= ``chunk_bytes`` slices
    along the leading dim, one in flight at a time, and return the
    assembled (blocked-until-ready) device array.

    ``dtype`` is the wire/device dtype (chunk sizing uses it — a f64
    host batch uploaded as bf16 moves a quarter of the bytes).  Arrays
    that fit in one chunk take the single device_put fast path; 0-d
    arrays always do.
    """
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.obs.tracer import get_tracer
    _tr = get_tracer()

    x_host = np.asarray(x_host)
    target = jnp.dtype(dtype) if dtype is not None else x_host.dtype

    def _put(a):
        arr = jnp.asarray(a, target)
        if device is not None:
            import jax
            arr = jax.device_put(arr, device)
        return arr

    if x_host.ndim == 0 or x_host.size == 0:
        out = _put(x_host)
        out.block_until_ready()
        return out

    per_row = max(1, int(x_host[0:1].size) * jnp.dtype(target).itemsize)
    rows = max(1, int(chunk_bytes) // per_row)
    n = x_host.shape[0]
    if rows >= n:
        out = _put(x_host)
        out.block_until_ready()
        return out

    parts = []
    itemsize = jnp.dtype(target).itemsize
    for i in range(0, n, rows):
        piece = x_host[i:i + rows]
        with _tr.span("h2d/chunk", cat="transfer", offset_rows=i,
                      rows=int(piece.shape[0]),
                      bytes=int(piece.size) * itemsize):
            p = _put(piece)
            # one in-flight slice at a time — device_put is async, so
            # building the list without blocking would enqueue every
            # slice at once, recreating the oversized burst
            p.block_until_ready()
        parts.append(p)
    with _tr.span("h2d/assemble", cat="transfer", chunks=len(parts)):
        out = jnp.concatenate(parts, axis=0)
        out.block_until_ready()
    del parts  # don't hold a second copy of the batch alive
    return out
