"""Binary-compatible Torch7 ``.t7`` serialization.

Rebuild of ``utils/TorchFile.scala:36-330``: the t7 format is a little-endian
stream of tagged objects (NIL=0, NUMBER=1 f64, STRING=2, TABLE=3, TORCH=4,
BOOLEAN=5); TABLE and TORCH objects carry a heap index for shared-reference
memoization; TORCH objects carry a version string ("V 1") + class name, then
a class-specific payload.  Tensors are (i32 ndim, i64 sizes, i64 strides,
i64 offset(1-based), Storage object); Storages are (i64 n, raw data).

``load``/``save`` handle the raw object graph (numbers, strings, booleans,
tables, numpy tensors).  ``load_model``/``save_model`` map torch ``nn.*``
module tables onto ``bigdl_tpu.nn`` layers with the same class coverage as
the reference reader (TorchFile.scala:144-161) and writer (:257-290).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Dict, Optional

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
LEGACY_TYPE_RECUR_FUNCTION = 7
TYPE_RECUR_FUNCTION = 8


@dataclass
class TorchObject:
    """A torch class instance that has no native mapping here — carries the
    class name and its element table so nothing is lost on load."""
    class_name: str
    elements: Dict[str, Any] = field(default_factory=dict)

    def get(self, key, default=None):
        return self.elements.get(key, default)

    def __getitem__(self, key):
        return self.elements[key]


# ----------------------------------------------------------------------- #
# reader                                                                  #
# ----------------------------------------------------------------------- #

class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def _i32(self) -> int:
        return struct.unpack("<i", self.f.read(4))[0]

    def _i64(self) -> int:
        return struct.unpack("<q", self.f.read(8))[0]

    def _f64(self) -> float:
        return struct.unpack("<d", self.f.read(8))[0]

    def _string(self) -> str:
        n = self._i32()
        return self.f.read(n).decode("utf-8", "replace")

    def read_object(self) -> Any:
        type_id = self._i32()
        if type_id == TYPE_NIL:
            return None
        if type_id == TYPE_NUMBER:
            v = self._f64()
            return int(v) if v.is_integer() and abs(v) < 2**53 else v
        if type_id == TYPE_STRING:
            return self._string()
        if type_id == TYPE_BOOLEAN:
            return self._i32() != 0
        if type_id == TYPE_TABLE:
            idx = self._i32()
            if idx in self.memo:
                return self.memo[idx]
            result: Dict[Any, Any] = {}
            self.memo[idx] = result  # pre-register: tables may self-reference
            n = self._i32()
            for _ in range(n):
                k = self.read_object()
                v = self.read_object()
                result[k] = v
            return result
        if type_id == TYPE_TORCH:
            idx = self._i32()
            if idx in self.memo:
                return self.memo[idx]
            version = self._string()
            if version.startswith("V "):
                class_name = self._string()
            else:  # legacy files have no version record
                class_name = version
            result = self._read_torch(class_name, idx)
            self.memo[idx] = result
            return result
        if type_id in (TYPE_FUNCTION, TYPE_RECUR_FUNCTION,
                       LEGACY_TYPE_RECUR_FUNCTION):
            raise NotImplementedError("t7 serialized lua functions")
        raise ValueError(f"unknown t7 type tag {type_id}")

    _TENSOR_DTYPES = {
        "torch.FloatTensor": np.float32, "torch.DoubleTensor": np.float64,
        "torch.LongTensor": np.int64, "torch.IntTensor": np.int32,
        "torch.ByteTensor": np.uint8, "torch.CharTensor": np.int8,
        "torch.ShortTensor": np.int16,
        "torch.CudaTensor": np.float32, "torch.CudaDoubleTensor": np.float64,
        "torch.CudaLongTensor": np.int64,
    }
    _STORAGE_DTYPES = {
        "torch.FloatStorage": np.float32, "torch.DoubleStorage": np.float64,
        "torch.LongStorage": np.int64, "torch.IntStorage": np.int32,
        "torch.ByteStorage": np.uint8, "torch.CharStorage": np.int8,
        "torch.ShortStorage": np.int16,
        "torch.CudaStorage": np.float32, "torch.CudaDoubleStorage": np.float64,
        "torch.CudaLongStorage": np.int64,
    }

    def _read_torch(self, class_name: str, idx: int) -> Any:
        if class_name in self._TENSOR_DTYPES:
            return self._read_tensor()
        if class_name in self._STORAGE_DTYPES:
            dtype = self._STORAGE_DTYPES[class_name]
            n = self._i64()
            return np.frombuffer(self.f.read(n * np.dtype(dtype).itemsize),
                                 dtype=dtype).copy()
        # any other torch class: its payload is one element table
        elements = self.read_object() or {}
        str_elems = {k: v for k, v in elements.items() if isinstance(k, str)}
        # keep the integer-keyed array part too (e.g. container "modules")
        for k, v in elements.items():
            if not isinstance(k, str):
                str_elems[str(k)] = v
        obj = TorchObject(class_name, str_elems)
        self.memo[idx] = obj
        return obj

    def _read_tensor(self) -> Optional[np.ndarray]:
        ndim = self._i32()
        sizes = [self._i64() for _ in range(ndim)]
        strides = [self._i64() for _ in range(ndim)]
        offset = self._i64()  # 1-based
        storage = self.read_object()
        if storage is None or ndim == 0:
            return np.zeros(sizes, dtype=np.float32) if ndim else None
        base = storage[offset - 1:]
        itemsize = base.dtype.itemsize
        out = np.lib.stride_tricks.as_strided(
            base, shape=sizes, strides=[s * itemsize for s in strides])
        return out.copy()


# ----------------------------------------------------------------------- #
# writer                                                                  #
# ----------------------------------------------------------------------- #

class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self._next_index = 1
        self._indices: Dict[int, int] = {}  # id(obj) -> heap index
        self._keepalive = []  # ids are only stable while objects live

    def _i32(self, v: int):
        self.f.write(struct.pack("<i", v))

    def _i64(self, v: int):
        self.f.write(struct.pack("<q", v))

    def _f64(self, v: float):
        self.f.write(struct.pack("<d", v))

    def _string(self, s: str):
        b = s.encode("utf-8")
        self._i32(len(b))
        self.f.write(b)

    def _heap(self, obj) -> Optional[int]:
        """Returns the index to write, or None if already memoized (in which
        case the caller writes just the index and stops)."""
        key = id(obj)
        if key in self._indices:
            self._i32(self._indices[key])
            return None
        idx = self._next_index
        self._next_index += 1
        self._indices[key] = idx
        self._keepalive.append(obj)
        self._i32(idx)
        return idx

    def write_object(self, obj: Any):
        from bigdl_tpu.nn.module import Module
        if obj is None:
            self._i32(TYPE_NIL)
        elif isinstance(obj, bool):
            self._i32(TYPE_BOOLEAN)
            self._i32(1 if obj else 0)
        elif isinstance(obj, (int, float)):
            self._i32(TYPE_NUMBER)
            self._f64(float(obj))
        elif isinstance(obj, str):
            self._i32(TYPE_STRING)
            self._string(obj)
        elif isinstance(obj, np.ndarray) and obj.dtype == np.int64:
            # LongStorage (torch stores shape vectors this way)
            self._i32(TYPE_TORCH)
            if self._heap(obj) is None:
                return
            self._string("V 1")
            self._string("torch.LongStorage")
            self._i64(obj.size)
            self.f.write(np.ascontiguousarray(obj).tobytes())
        elif hasattr(obj, "shape"):  # numpy / jax array -> tensor
            self._write_tensor(np.asarray(obj))
        elif isinstance(obj, Module):
            write_module(self, obj)
        elif isinstance(obj, TorchObject):
            self._i32(TYPE_TORCH)
            if self._heap(obj) is None:
                return
            self._string("V 1")
            self._string(obj.class_name)
            self.write_object(dict(obj.elements))
        elif isinstance(obj, (dict,)):
            self._i32(TYPE_TABLE)
            if self._heap(obj) is None:
                return
            self._i32(len(obj))
            for k, v in obj.items():
                self.write_object(k)
                self.write_object(v)
        elif isinstance(obj, (list, tuple)):
            # lua array-style table, 1-based keys (shares the heap with
            # dicts so aliased/cyclic lists memoize correctly)
            self._i32(TYPE_TABLE)
            if self._heap(obj) is None:
                return
            self._i32(len(obj))
            for i, v in enumerate(obj):
                self.write_object(i + 1)
                self.write_object(v)
        else:
            raise TypeError(f"cannot serialize {type(obj).__name__} to .t7")

    def _write_tensor(self, arr: np.ndarray):
        if arr.dtype == np.float32:
            cls, scls = "torch.FloatTensor", "torch.FloatStorage"
        elif arr.dtype == np.float64:
            cls, scls = "torch.DoubleTensor", "torch.DoubleStorage"
        else:
            arr = arr.astype(np.float64)
            cls, scls = "torch.DoubleTensor", "torch.DoubleStorage"
        self._i32(TYPE_TORCH)
        if self._heap(arr) is None:
            return
        self._string("V 1")
        self._string(cls)
        arr = np.ascontiguousarray(arr)
        self._i32(arr.ndim)
        for s in arr.shape:
            self._i64(s)
        # contiguous strides in elements
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self._i64(s)
        self._i64(1)  # storage offset, 1-based
        # storage sub-object
        self._i32(TYPE_TORCH)
        self._i32(self._next_index)
        self._next_index += 1
        self._string("V 1")
        self._string(scls)
        self._i64(arr.size)
        self.f.write(arr.tobytes())


# ----------------------------------------------------------------------- #
# public API                                                              #
# ----------------------------------------------------------------------- #

def load(path: str) -> Any:
    """Load the first object of a .t7 file (ref TorchFile.load)."""
    with open(path, "rb") as f:
        return _Reader(f).read_object()


def save(obj: Any, path: str, overwrite: bool = True):
    import os
    if not overwrite and os.path.exists(path):
        raise FileExistsError(path)
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)


def load_model(path: str):
    """Load a torch nn model saved as .t7 into bigdl_tpu layers
    (ref Module.loadTorch, nn/Module.scala:31)."""
    obj = load(path)
    return module_from_torch(obj)


def _sync_child_shells(m) -> None:
    """Containers hold the whole params pytree ({"0": ..., "1": ...}) on
    their own shell; push the slices down so each child's shell sees its own
    weights (children are exported individually)."""
    from bigdl_tpu.nn.containers import Container
    if isinstance(m, Container) and isinstance(m.params, dict):
        for i, c in enumerate(m.modules):
            key = str(i)
            if c.params is None and key in m.params:
                c.params = m.params[key]
            if not c.buffers and isinstance(m.buffers, dict) and m.buffers.get(key):
                c.buffers = m.buffers[key]
            _sync_child_shells(c)


def save_model(model, path: str, overwrite: bool = True):
    """Save a bigdl_tpu model as a torch-readable .t7 (ref module.saveTorch)."""
    import os
    if not overwrite and os.path.exists(path):
        raise FileExistsError(path)
    _sync_child_shells(model)
    with open(path, "wb") as f:
        write_module(_Writer(f), model)


# -- torch nn.* <-> bigdl_tpu.nn mapping -------------------------------- #

def _num(elements, key, default=None):
    v = elements.get(key, default)
    return int(v) if v is not None else default


def _copy_filter_2d_or_4d(w: np.ndarray, n_out, n_in, kh, kw,
                          groups: int = 1) -> np.ndarray:
    """Accept both SpatialConvolutionMM 2-D (out, in*kh*kw) and 4-D layouts
    (grouped weights reshape to (out, in/groups, kh, kw))."""
    return np.asarray(w, np.float32).reshape(n_out, n_in // groups, kh, kw)


def module_from_torch(obj) -> "Any":
    m = _module_from_torch(obj)
    if m.params is None:  # parameterless leaves still need a built shell
        m.build(seed=0)
    return m


def _module_from_torch(obj) -> "Any":
    from bigdl_tpu import nn
    if not isinstance(obj, TorchObject):
        raise ValueError(f"not a torch module object: {type(obj).__name__}")
    cls = obj.class_name
    el = obj.elements

    def seq_children(container):
        mods = el.get("modules", {})
        n = len(mods)
        for i in range(1, n + 1):
            key = i if i in mods else (str(i) if str(i) in mods else float(i))
            container.add(module_from_torch(mods[key]))
        # assemble container params from the already-loaded children —
        # container.build() would re-randomize them
        container.params = {str(i): c.params for i, c in enumerate(container.modules)}
        container.buffers = {str(i): c.buffers for i, c in enumerate(container.modules)}
        return container

    def with_params(m, **arrays):
        m.build(seed=0)
        for name, arr in arrays.items():
            if arr is not None:
                m.params[name] = np.asarray(arr, np.float32)
        return m

    if cls.startswith("cudnn."):
        # the reference maps cudnn.* onto the plain module set the same way
        # (TorchFile.scala:138-142)
        cls = "nn." + cls[len("cudnn."):]

    if cls == "nn.Sequential":
        return seq_children(nn.Sequential())
    if cls == "nn.Concat":
        return seq_children(nn.Concat(_num(el, "dimension", 2)))
    if cls == "nn.DepthConcat":
        return seq_children(nn.DepthConcat())
    if cls == "nn.ConcatTable":
        return seq_children(nn.ConcatTable())
    if cls == "nn.ParallelTable":
        return seq_children(nn.ParallelTable())
    if cls == "nn.CAddTable":
        return nn.CAddTable()
    if cls == "nn.Linear":
        w = np.asarray(el["weight"], np.float32)
        m = nn.Linear(w.shape[1], w.shape[0], with_bias="bias" in el)
        return with_params(m, weight=w, bias=el.get("bias"))
    if cls in ("nn.SpatialConvolution", "nn.SpatialConvolutionMM"):
        n_in, n_out = _num(el, "nInputPlane"), _num(el, "nOutputPlane")
        kw_, kh = _num(el, "kW"), _num(el, "kH")
        groups = _num(el, "nGroup", _num(el, "groups", 1)) or 1
        m = nn.SpatialConvolution(
            n_in, n_out, kw_, kh, _num(el, "dW", 1), _num(el, "dH", 1),
            _num(el, "padW", 0), _num(el, "padH", 0), n_group=groups,
            with_bias="bias" in el and el["bias"] is not None)
        w = _copy_filter_2d_or_4d(el["weight"], n_out, n_in, kh, kw_, groups)
        return with_params(m, weight=w, bias=el.get("bias"))
    if cls == "nn.SpatialFullConvolution":
        n_in, n_out = _num(el, "nInputPlane"), _num(el, "nOutputPlane")
        kw_, kh = _num(el, "kW"), _num(el, "kH")
        groups = _num(el, "nGroup", 1) or 1
        m = nn.SpatialFullConvolution(
            n_in, n_out, kw_, kh, _num(el, "dW", 1), _num(el, "dH", 1),
            _num(el, "padW", 0), _num(el, "padH", 0),
            _num(el, "adjW", 0), _num(el, "adjH", 0), n_group=groups,
            no_bias=el.get("bias") is None)
        # torch layout: (nInput, nOutput/group, kH, kW)
        w = np.asarray(el["weight"], np.float32).reshape(
            n_in, n_out // groups, kh, kw_)
        return with_params(m, weight=w, bias=el.get("bias"))
    if cls == "nn.SpatialDilatedConvolution":
        n_in, n_out = _num(el, "nInputPlane"), _num(el, "nOutputPlane")
        kw_, kh = _num(el, "kW"), _num(el, "kH")
        m = nn.SpatialDilatedConvolution(
            n_in, n_out, kw_, kh, _num(el, "dW", 1), _num(el, "dH", 1),
            _num(el, "padW", 0), _num(el, "padH", 0),
            _num(el, "dilationW", 1), _num(el, "dilationH", 1))
        w = _copy_filter_2d_or_4d(el["weight"], n_out, n_in, kh, kw_)
        m = with_params(m, weight=w, bias=el.get("bias"))
        if el.get("bias") is None:
            m.with_bias = False
            m.params.pop("bias", None)
        return m
    if cls == "nn.SpatialConvolutionMap":
        conn = np.asarray(el["connTable"], np.float32).astype(np.int32)
        kw_, kh = _num(el, "kW"), _num(el, "kH")
        m = nn.SpatialConvolutionMap(
            conn, kw_, kh, _num(el, "dW", 1), _num(el, "dH", 1),
            _num(el, "padW", 0), _num(el, "padH", 0))
        m.build(seed=0)
        # torch stores (nConn, kH, kW); scatter into our dense masked layout
        wt = np.asarray(el["weight"], np.float32).reshape(len(conn), kh, kw_)
        dense = np.zeros((m.n_output_plane, m.n_input_plane, kh, kw_), np.float32)
        for k, (i, o) in enumerate(conn):
            dense[o - 1, i - 1] = wt[k]
        m.params["weight"] = dense
        if el.get("bias") is not None:
            m.params["bias"] = np.asarray(el["bias"], np.float32)
        return m
    if cls == "nn.SpatialMaxPooling":
        m = nn.SpatialMaxPooling(_num(el, "kW"), _num(el, "kH"),
                                 _num(el, "dW"), _num(el, "dH"),
                                 _num(el, "padW", 0), _num(el, "padH", 0))
        return m.ceil() if el.get("ceil_mode", False) else m.floor()
    if cls == "nn.SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            _num(el, "kW"), _num(el, "kH"), _num(el, "dW", 1),
            _num(el, "dH", 1), _num(el, "padW", 0), _num(el, "padH", 0),
            ceil_mode=el.get("ceil_mode", False),
            count_include_pad=el.get("count_include_pad", True),
            divide=el.get("divide", True))
    if cls in ("nn.BatchNormalization", "nn.SpatialBatchNormalization"):
        mean = np.asarray(el["running_mean"], np.float32)
        layer_cls = (nn.SpatialBatchNormalization
                     if cls == "nn.SpatialBatchNormalization"
                     else nn.BatchNormalization)
        m = layer_cls(mean.shape[0], eps=float(el.get("eps", 1e-5)),
                      momentum=float(el.get("momentum", 0.1)),
                      affine="weight" in el and el["weight"] is not None)
        m = with_params(m, weight=el.get("weight"), bias=el.get("bias"))
        m.buffers["running_mean"] = np.asarray(mean, np.float32)
        if el.get("running_var") is not None:
            var = np.asarray(el["running_var"], np.float32)
        elif el.get("running_std") is not None:
            # legacy torch stored running_std = 1/sqrt(var + eps)
            std = np.asarray(el["running_std"], np.float64)
            var = (std ** -2 - float(el.get("eps", 1e-5))).astype(np.float32)
        else:
            var = np.ones_like(mean)
        m.buffers["running_var"] = var
        return m
    if cls == "nn.ReLU":
        return nn.ReLU(bool(el.get("inplace", False)))
    if cls == "nn.Tanh":
        return nn.Tanh()
    if cls == "nn.Sigmoid":
        return nn.Sigmoid()
    if cls == "nn.LogSoftMax":
        return nn.LogSoftMax()
    if cls == "nn.SoftMax":
        return nn.SoftMax()
    if cls == "nn.Threshold":
        return nn.Threshold(float(el.get("threshold", 1e-6)),
                            float(el.get("val", 0.0)),
                            bool(el.get("inplace", False)))
    if cls == "nn.Dropout":
        return nn.Dropout(float(el.get("p", 0.5)),
                          inplace=bool(el.get("inplace", False)))
    if cls == "nn.View":
        return nn.View(tuple(int(s) for s in np.asarray(el["size"]).ravel()))
    if cls == "nn.Reshape":
        return nn.Reshape(tuple(int(s) for s in np.asarray(el["size"]).ravel()))
    if cls == "nn.SpatialZeroPadding":
        return nn.SpatialZeroPadding(_num(el, "pad_l"), _num(el, "pad_r"),
                                     _num(el, "pad_t"), _num(el, "pad_b"))
    if cls == "nn.SpatialCrossMapLRN":
        return nn.SpatialCrossMapLRN(
            _num(el, "size", 5), float(el.get("alpha", 1.0)),
            float(el.get("beta", 0.75)), float(el.get("k", 1.0)))
    if cls == "nn.LookupTable":
        w = np.asarray(el["weight"], np.float32)
        m = nn.LookupTable(w.shape[0], w.shape[1],
                           padding_value=float(el.get("paddingValue", 0)),
                           max_norm=float(el.get("maxNorm") or float("inf")),
                           norm_type=float(el.get("normType", 2.0)))
        return with_params(m, weight=w)
    if cls == "nn.PReLU":
        w = np.asarray(el["weight"], np.float32).ravel()
        m = nn.PReLU(_num(el, "nOutputPlane", 0))
        return with_params(m, weight=w)
    if cls == "nn.Mul":
        return with_params(nn.Mul(), weight=np.asarray(el["weight"]).ravel())
    if cls == "nn.Add":
        b = np.asarray(el["bias"], np.float32).ravel()
        return with_params(nn.Add(b.shape[0]), bias=b)
    if cls == "nn.CMul":
        w = np.asarray(el["weight"], np.float32)
        return with_params(nn.CMul(w.shape), weight=w)
    if cls == "nn.CAdd":
        b = np.asarray(el["bias"], np.float32)
        return with_params(nn.CAdd(b.shape), bias=b)
    if cls == "nn.Euclidean":
        w = np.asarray(el["weight"], np.float32)
        # torch stores (inputSize, outputSize); ours is (out, in)
        return with_params(nn.Euclidean(w.shape[0], w.shape[1]), weight=w.T)
    if cls == "nn.LeakyReLU":
        return nn.LeakyReLU(float(el.get("negval", 0.01)),
                            bool(el.get("inplace", False)))
    if cls == "nn.ELU":
        return nn.ELU(float(el.get("alpha", 1.0)), bool(el.get("inplace", False)))
    if cls == "nn.SoftPlus":
        return nn.SoftPlus(float(el.get("beta", 1.0)))
    if cls == "nn.HardTanh":
        return nn.HardTanh(float(el.get("min_val", -1.0)),
                           float(el.get("max_val", 1.0)),
                           bool(el.get("inplace", False)))
    if cls == "nn.Power":
        return nn.Power(float(el.get("pow", 1.0)), float(el.get("scale", 1.0)),
                        float(el.get("shift", 0.0)))
    if cls == "nn.MulConstant":
        return nn.MulConstant(float(el.get("constant_scalar", 1.0)))
    if cls == "nn.AddConstant":
        return nn.AddConstant(float(el.get("constant_scalar", 0.0)))
    if cls == "nn.Mean":
        return nn.Mean(_num(el, "dimension", 1), _num(el, "nInputDims", -1))
    if cls == "nn.Sum":
        return nn.Sum(_num(el, "dimension", 1), _num(el, "nInputDims", -1),
                      size_average=bool(el.get("sizeAverage", False)))
    if cls == "nn.Max":
        return nn.Max(_num(el, "dim", 1), _num(el, "numInputDims", -1))
    if cls == "nn.Min":
        return nn.Min(_num(el, "dim", 1), _num(el, "numInputDims", -1))
    if cls == "nn.Select":
        return nn.Select(_num(el, "dimension"), _num(el, "index"))
    if cls == "nn.Narrow":
        return nn.Narrow(_num(el, "dimension"), _num(el, "index"),
                         _num(el, "length", 1))
    if cls == "nn.Replicate":
        return nn.Replicate(_num(el, "nfeatures"), _num(el, "dim", 1),
                            _num(el, "ndim", -1))
    if cls == "nn.Transpose":
        perms = el.get("permutations", {})
        pairs = []
        for i in range(1, len(perms) + 1):
            p = perms.get(i, perms.get(float(i), perms.get(str(i))))
            vals = ([p[k] for k in sorted(p, key=float)]
                    if isinstance(p, dict) else list(p))
            pairs.append((int(vals[0]), int(vals[1])))
        return nn.Transpose(pairs)
    if cls == "nn.Squeeze":
        return nn.Squeeze(_num(el, "dim"), _num(el, "numInputDims", -1))
    if cls == "nn.Unsqueeze":
        return nn.Unsqueeze(_num(el, "pos"), _num(el, "numInputDims", -1))
    if cls == "nn.Padding":
        return nn.Padding(_num(el, "dim"), _num(el, "pad"),
                          _num(el, "nInputDim", -1),
                          float(el.get("value", 0.0)), _num(el, "index", 1))
    if cls == "nn.JoinTable":
        return nn.JoinTable(_num(el, "dimension"), _num(el, "nInputDims", -1))
    if cls == "nn.SplitTable":
        return nn.SplitTable(_num(el, "dimension"), _num(el, "nInputDims", -1))
    if cls == "nn.Normalize":
        return nn.Normalize(float(el.get("p", 2.0)), float(el.get("eps", 1e-10)))

    # reflection-style fallback for parameter-free modules, mirroring the
    # reference's createInstanceFor path (TorchFile.scala:163-177): any
    # nn.<Name> whose constructor needs no arguments loads by name.
    if cls.startswith("nn."):
        layer_cls = getattr(nn, cls[3:], None)
        from bigdl_tpu.nn.module import Module as _Module
        if (isinstance(layer_cls, type) and issubclass(layer_cls, _Module)):
            try:
                return layer_cls()
            except TypeError:
                pass  # requires constructor args we don't know
    raise NotImplementedError(f"t7 import of {cls}")


def _grad_like(params, name):
    arr = params.get(name)
    return np.zeros_like(np.asarray(arr)) if arr is not None else None


def _grouped_conv_as_concat(m, params):
    """Grouped conv -> Concat(channel){Sequential{Narrow(ch), conv_g}}:
    the Torch-readable rendering of feature groups (torch's own AlexNet
    reimplementations used exactly this shape before cunn grew a groups
    arg).  Forward-equivalent to the fused grouped conv."""
    from bigdl_tpu import nn
    in_per, out_per = m.n_input_plane // m.n_group, m.n_output_plane // m.n_group
    w4 = np.asarray(params["weight"], np.float32)  # (O, I/g, kH, kW)
    bias = (np.asarray(params["bias"], np.float32)
            if "bias" in params else None)
    cat = nn.Concat(2)
    for g in range(m.n_group):
        conv = nn.SpatialConvolution(
            in_per, out_per, m.kernel_w, m.kernel_h, m.stride_w, m.stride_h,
            m.pad_w, m.pad_h, with_bias=bias is not None)
        conv.build(seed=0)
        conv.params["weight"] = w4[g * out_per:(g + 1) * out_per]
        if bias is not None:
            conv.params["bias"] = bias[g * out_per:(g + 1) * out_per]
        nar = nn.Narrow(2, g * in_per + 1, in_per)
        nar.build(seed=0)
        branch = nn.Sequential(nar, conv)
        branch.params = {"0": nar.params, "1": conv.params}
        branch.buffers = {"0": nar.buffers, "1": conv.buffers}
        cat.add(branch)
    cat.params = {str(i): c.params for i, c in enumerate(cat.modules)}
    cat.buffers = {str(i): c.buffers for i, c in enumerate(cat.modules)}
    return cat


def write_module(w: _Writer, m) -> None:
    """Write one bigdl_tpu module as a torch nn.* object (same writable set
    as the reference, TorchFile.scala:257-290, plus a few extras)."""
    from bigdl_tpu import nn
    params = m._built()

    def header(cls_name) -> bool:
        w._i32(TYPE_TORCH)
        if w._heap(m) is None:
            return False
        w._string("V 1")
        w._string(cls_name)
        return True

    def body(**el):
        el.setdefault("train", bool(m.train))
        w.write_object({k: v for k, v in el.items()})

    if isinstance(m, nn.DepthConcat):
        if not header("nn.DepthConcat"):
            return
        body(modules={i + 1: c for i, c in enumerate(m.modules)},
             dimension=float(m.dimension))
    elif isinstance(m, nn.Concat):
        if not header("nn.Concat"):
            return
        body(modules={i + 1: c for i, c in enumerate(m.modules)},
             dimension=float(m.dimension))
    elif isinstance(m, nn.Sequential):
        if not header("nn.Sequential"):
            return
        body(modules={i + 1: c for i, c in enumerate(m.modules)})
    elif isinstance(m, nn.Linear):
        if not header("nn.Linear"):
            return
        weight = np.asarray(params["weight"], np.float32)
        body(weight=weight, bias=np.asarray(params["bias"], np.float32)
             if "bias" in params else None,
             gradWeight=np.zeros_like(weight),
             gradBias=_grad_like(params, "bias"))
    elif isinstance(m, nn.SpatialDilatedConvolution):
        if not header("nn.SpatialDilatedConvolution"):
            return
        w4 = np.asarray(params["weight"], np.float32)
        body(nInputPlane=float(m.n_input_plane),
             nOutputPlane=float(m.n_output_plane),
             kW=float(m.kernel_w), kH=float(m.kernel_h),
             dW=float(m.stride_w), dH=float(m.stride_h),
             padW=float(m.pad_w), padH=float(m.pad_h),
             dilationW=float(m.dilation_w), dilationH=float(m.dilation_h),
             weight=w4, gradWeight=np.zeros_like(w4),
             bias=np.asarray(params["bias"], np.float32)
             if "bias" in params else None,
             gradBias=_grad_like(params, "bias"))
    elif isinstance(m, nn.SpatialConvolutionMap):
        if not header("nn.SpatialConvolutionMap"):
            return
        conn = np.asarray(m.conn_table, np.int64)
        dense = np.asarray(params["weight"], np.float32)
        wt = np.stack([dense[o - 1, i - 1] for i, o in conn])  # (nConn,kH,kW)
        body(connTable=conn.astype(np.float32),
             kW=float(m.kernel_w), kH=float(m.kernel_h),
             dW=float(m.stride_w), dH=float(m.stride_h),
             padW=float(m.pad_w), padH=float(m.pad_h),
             nInputPlane=float(m.n_input_plane),
             nOutputPlane=float(m.n_output_plane),
             weight=wt, gradWeight=np.zeros_like(wt),
             bias=np.asarray(params["bias"], np.float32),
             gradBias=_grad_like(params, "bias"))
    elif isinstance(m, nn.SpatialFullConvolution):
        if not header("nn.SpatialFullConvolution"):
            return
        w4 = np.asarray(params["weight"], np.float32)  # (I, O/g, kH, kW)
        body(nInputPlane=float(m.n_input_plane),
             nOutputPlane=float(m.n_output_plane),
             kW=float(m.kernel_w), kH=float(m.kernel_h),
             dW=float(m.stride_w), dH=float(m.stride_h),
             padW=float(m.pad_w), padH=float(m.pad_h),
             adjW=float(m.adj_w), adjH=float(m.adj_h),
             nGroup=float(m.n_group),
             weight=w4, gradWeight=np.zeros_like(w4),
             bias=np.asarray(params["bias"], np.float32)
             if "bias" in params else None,
             gradBias=_grad_like(params, "bias"))
    elif isinstance(m, nn.SpatialConvolution):
        if m.n_group != 1:
            # standard Torch7 has no grouped SpatialConvolutionMM: emit
            # the classic decomposition instead — Concat over groups of
            # (Narrow the input channels -> per-group conv) — which any
            # Torch-era loader (and our importer) reads as plain modules
            # with identical forward semantics
            write_module(w, _grouped_conv_as_concat(m, params))
            return
        if not header("nn.SpatialConvolutionMM"):
            return
        w4 = np.asarray(params["weight"], np.float32)
        w2 = w4.reshape(m.n_output_plane, -1)  # MM layout (out, in*kh*kw)
        body(nInputPlane=float(m.n_input_plane),
             nOutputPlane=float(m.n_output_plane),
             kW=float(m.kernel_w), kH=float(m.kernel_h),
             dW=float(m.stride_w), dH=float(m.stride_h),
             padW=float(m.pad_w), padH=float(m.pad_h),
             weight=w2, gradWeight=np.zeros_like(w2),
             bias=np.asarray(params["bias"], np.float32)
             if "bias" in params else None,
             gradBias=_grad_like(params, "bias"))
    elif isinstance(m, nn.SpatialMaxPooling):
        if not header("nn.SpatialMaxPooling"):
            return
        body(kW=float(m.kernel_w), kH=float(m.kernel_h),
             dW=float(m.stride_w), dH=float(m.stride_h),
             padW=float(m.pad_w), padH=float(m.pad_h),
             ceil_mode=bool(m.ceil_mode))
    elif isinstance(m, nn.ReLU):
        if not header("nn.ReLU"):
            return
        body(inplace=bool(m.ip), threshold=0.0, val=0.0)
    elif isinstance(m, nn.Threshold):
        if not header("nn.Threshold"):
            return
        body(threshold=float(m.th), val=float(m.v), inplace=bool(m.ip))
    elif isinstance(m, nn.Dropout):
        if not header("nn.Dropout"):
            return
        body(p=float(m.p), inplace=bool(m.inplace), v2=True)
    elif isinstance(m, nn.View):
        if not header("nn.View"):
            return
        size = np.asarray(m.sizes, np.int64)
        body(size=size, numElements=float(int(np.prod(m.sizes))))
    elif isinstance(m, nn.Reshape):
        if not header("nn.Reshape"):
            return
        size = np.asarray(m.size, np.int64)
        body(size=size, nelement=float(int(np.prod(m.size))),
             batchMode=m.batch_mode)
    elif isinstance(m, nn.LogSoftMax):
        if not header("nn.LogSoftMax"):
            return
        body()
    elif isinstance(m, nn.Tanh):
        if not header("nn.Tanh"):
            return
        body()
    elif isinstance(m, nn.Sigmoid):
        if not header("nn.Sigmoid"):
            return
        body()
    elif isinstance(m, (nn.BatchNormalization,)):
        cls = ("nn.SpatialBatchNormalization"
               if isinstance(m, nn.SpatialBatchNormalization)
               else "nn.BatchNormalization")
        if not header(cls):
            return
        buf = m.buffers or m.init_buffers()
        body(running_mean=np.asarray(buf["running_mean"], np.float32),
             running_var=np.asarray(buf["running_var"], np.float32),
             weight=np.asarray(params["weight"], np.float32)
             if "weight" in params else None,
             bias=np.asarray(params["bias"], np.float32)
             if "bias" in params else None,
             eps=float(m.eps), momentum=float(m.momentum),
             affine=bool(m.affine))
    elif isinstance(m, nn.SpatialAveragePooling):
        if not header("nn.SpatialAveragePooling"):
            return
        body(kW=float(m.kernel_w), kH=float(m.kernel_h),
             dW=float(m.stride_w), dH=float(m.stride_h),
             padW=float(m.pad_w), padH=float(m.pad_h),
             ceil_mode=bool(m.ceil_mode),
             count_include_pad=bool(m.count_include_pad),
             divide=bool(m.divide))
    elif isinstance(m, nn.ConcatTable):
        if not header("nn.ConcatTable"):
            return
        body(modules={i + 1: c for i, c in enumerate(m.modules)})
    elif isinstance(m, nn.ParallelTable):
        if not header("nn.ParallelTable"):
            return
        body(modules={i + 1: c for i, c in enumerate(m.modules)})
    elif isinstance(m, nn.CAddTable):
        if not header("nn.CAddTable"):
            return
        body(inplace=bool(getattr(m, "inplace", False)))
    elif isinstance(m, nn.SpatialCrossMapLRN):
        if not header("nn.SpatialCrossMapLRN"):
            return
        body(size=float(m.size), alpha=float(m.alpha), beta=float(m.beta),
             k=float(m.k))
    elif isinstance(m, nn.LookupTable):
        if not header("nn.LookupTable"):
            return
        wt_ = np.asarray(params["weight"], np.float32)
        body(weight=wt_, gradWeight=np.zeros_like(wt_),
             paddingValue=float(m.padding_value),
             maxNorm=(float(m.max_norm)
                      if m.max_norm != float("inf") else None),
             normType=float(m.norm_type))
    elif isinstance(m, nn.PReLU):
        if not header("nn.PReLU"):
            return
        wt_ = np.asarray(params["weight"], np.float32)
        body(weight=wt_, gradWeight=np.zeros_like(wt_),
             nOutputPlane=float(m.n_output_plane))
    elif isinstance(m, nn.Euclidean):
        if not header("nn.Euclidean"):
            return
        wt_ = np.asarray(params["weight"], np.float32).T  # (in, out) torch layout
        body(weight=wt_, gradWeight=np.zeros_like(wt_))
    elif isinstance(m, nn.Mul):
        if not header("nn.Mul"):
            return
        wt_ = np.asarray(params["weight"], np.float32)
        body(weight=wt_, gradWeight=np.zeros_like(wt_))
    elif isinstance(m, nn.Add):
        if not header("nn.Add"):
            return
        b = np.asarray(params["bias"], np.float32)
        body(bias=b, gradBias=np.zeros_like(b))
    elif isinstance(m, nn.CMul):
        if not header("nn.CMul"):
            return
        wt_ = np.asarray(params["weight"], np.float32)
        body(weight=wt_, gradWeight=np.zeros_like(wt_),
             size=np.asarray(m.size, np.int64))
    elif isinstance(m, nn.CAdd):
        if not header("nn.CAdd"):
            return
        b = np.asarray(params["bias"], np.float32)
        body(bias=b, gradBias=np.zeros_like(b),
             size=np.asarray(m.size, np.int64))
    elif isinstance(m, nn.LeakyReLU):
        if not header("nn.LeakyReLU"):
            return
        body(negval=float(m.negval))
    elif isinstance(m, nn.ELU):
        if not header("nn.ELU"):
            return
        body(alpha=float(m.alpha))
    elif isinstance(m, nn.SoftPlus):
        if not header("nn.SoftPlus"):
            return
        body(beta=float(m.beta))
    elif isinstance(m, nn.Clamp):
        if not header("nn.HardTanh"):
            return
        body(min_val=float(m.min_value), max_val=float(m.max_value))
    elif isinstance(m, nn.HardTanh):
        if not header("nn.HardTanh"):
            return
        body(min_val=float(m.min_value), max_val=float(m.max_value))
    elif isinstance(m, nn.Power):
        if not header("nn.Power"):
            return
        body(pow=float(m.power), scale=float(m.scale), shift=float(m.shift))
    elif isinstance(m, nn.MulConstant):
        if not header("nn.MulConstant"):
            return
        body(constant_scalar=float(m.scalar))
    elif isinstance(m, nn.AddConstant):
        if not header("nn.AddConstant"):
            return
        body(constant_scalar=float(m.constant_scalar))
    elif isinstance(m, nn.Mean):
        if not header("nn.Mean"):
            return
        body(dimension=float(m.dimension), nInputDims=float(m.n_input_dims))
    elif isinstance(m, nn.Sum):
        if not header("nn.Sum"):
            return
        body(dimension=float(m.dimension), nInputDims=float(m.n_input_dims),
             sizeAverage=bool(m.size_average))
    elif isinstance(m, nn.Max):
        if not header("nn.Max"):
            return
        body(dim=float(m.dim), numInputDims=float(m.num_input_dims))
    elif isinstance(m, nn.Min):
        if not header("nn.Min"):
            return
        body(dim=float(m.dim), numInputDims=float(m.num_input_dims))
    elif isinstance(m, nn.Select):
        if not header("nn.Select"):
            return
        body(dimension=float(m.dimension), index=float(m.index))
    elif isinstance(m, nn.Narrow):
        if not header("nn.Narrow"):
            return
        body(dimension=float(m.dimension), index=float(m.offset),
             length=float(m.length))
    elif isinstance(m, nn.Replicate):
        if not header("nn.Replicate"):
            return
        body(nfeatures=float(m.n_features), dim=float(m.dim),
             ndim=float(m.n_dim))
    elif isinstance(m, nn.Transpose):
        if not header("nn.Transpose"):
            return
        body(permutations={i + 1: {1: float(a), 2: float(b)}
                           for i, (a, b) in enumerate(m.permutations)})
    elif isinstance(m, nn.Squeeze):
        if not header("nn.Squeeze"):
            return
        body(dim=(float(m.dim) if m.dim is not None else None),
             numInputDims=float(m.num_input_dims))
    elif isinstance(m, nn.Unsqueeze):
        if not header("nn.Unsqueeze"):
            return
        body(pos=float(m.pos), numInputDims=float(m.num_input_dims))
    elif isinstance(m, nn.Padding):
        if not header("nn.Padding"):
            return
        body(dim=float(m.dim), pad=float(m.pad),
             nInputDim=float(m.n_input_dim), value=float(m.value),
             index=float(m.n_index))
    elif isinstance(m, nn.JoinTable):
        if not header("nn.JoinTable"):
            return
        body(dimension=float(m.dimension), nInputDims=float(m.n_input_dims))
    elif isinstance(m, nn.SplitTable):
        if not header("nn.SplitTable"):
            return
        body(dimension=float(m.dimension), nInputDims=float(m.n_input_dims))
    elif isinstance(m, nn.Normalize):
        if not header("nn.Normalize"):
            return
        body(p=float(m.p), eps=float(m.eps))
    elif isinstance(m, nn.SpatialZeroPadding):
        if not header("nn.SpatialZeroPadding"):
            return
        body(pad_l=float(m.pad_left), pad_r=float(m.pad_right),
             pad_t=float(m.pad_top), pad_b=float(m.pad_bottom))
    elif (not params and not getattr(m, "modules", None)
          and type(m).__init__ is nn.Module.__init__):
        # parameter-free, hyperparameter-free leaf: export by class name,
        # the mirror of the reflection-based import fallback (ref
        # TorchFile.scala:163-177).  Classes with their OWN __init__ carry
        # constructor hyperparameters this fallback would silently drop
        # (e.g. GradientReversal.the_lambda) — those need an explicit
        # handler above and refuse loudly here.
        if not header(f"nn.{type(m).__name__}"):
            return
        body()
    else:
        raise NotImplementedError(f"t7 export of {type(m).__name__}")
