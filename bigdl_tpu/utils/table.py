"""Torch-style Table: a heterogeneous int/str-keyed map, registered as a pytree.

Plays the role of the reference's ``utils/Table.scala:34-316`` (the ``T(...)``
builder): optimizer state, multi-input/multi-output activities, and
name->tensor parameter tables.  Unlike the Scala original it is a JAX pytree,
so a Table of arrays can flow straight through ``jax.jit`` / ``jax.grad`` /
collectives.

Integer keys are 1-based, matching Torch/BigDL semantics.
"""
from __future__ import annotations

from typing import Any, Iterator

import jax


class Table:
    """Heterogeneous map with 1-based integer append semantics."""

    def __init__(self, *args: Any, **kwargs: Any):
        self._state: dict[Any, Any] = {}
        for v in args:
            self.insert(v)
        for k, v in kwargs.items():
            self._state[k] = v

    # -- dict-ish interface ------------------------------------------------
    def __getitem__(self, key: Any) -> Any:
        return self._state[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self._state[key] = value

    def __delitem__(self, key: Any) -> None:
        del self._state[key]

    def __contains__(self, key: Any) -> bool:
        return key in self._state

    def get(self, key: Any, default: Any = None) -> Any:
        return self._state.get(key, default)

    def get_or_update(self, key: Any, default: Any) -> Any:
        if key not in self._state:
            self._state[key] = default
        return self._state[key]

    def keys(self):
        return self._state.keys()

    def values(self):
        return self._state.values()

    def items(self):
        return self._state.items()

    def __len__(self) -> int:
        return len(self._state)

    def __iter__(self) -> Iterator[Any]:
        return iter(self._state)

    # -- Torch array-part semantics ---------------------------------------
    def length(self) -> int:
        """Length of the contiguous 1-based integer 'array part'."""
        n = 0
        while (n + 1) in self._state:
            n += 1
        return n

    def insert(self, *args: Any) -> "Table":
        """insert(value) appends at length+1; insert(index, value) inserts,
        shifting the array part right (Torch ``table.insert`` semantics)."""
        if len(args) == 1:
            self._state[self.length() + 1] = args[0]
        else:
            index, value = args
            i = self.length()
            while i >= index:
                self._state[i + 1] = self._state[i]
                i -= 1
            self._state[index] = value
        return self

    def remove(self, index: int | None = None) -> Any:
        n = self.length()
        if index is None:
            index = n
        if n == 0:
            return None
        value = self._state.get(index)
        for i in range(index, n):
            self._state[i] = self._state[i + 1]
        if n in self._state:
            del self._state[n]
        return value

    def to_seq(self) -> list[Any]:
        return [self._state[i + 1] for i in range(self.length())]

    # -- misc --------------------------------------------------------------
    def clone(self) -> "Table":
        t = Table()
        t._state = dict(self._state)
        return t

    def update(self, other) -> "Table":
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self._state[k] = v
        return self

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Table):
            return self._state == other._state
        if isinstance(other, dict):
            return self._state == other
        return NotImplemented

    def __hash__(self):
        return id(self)

    def __repr__(self) -> str:
        inner = ", ".join(f"{k!r}: {v!r}" for k, v in self._state.items())
        return f"T({{{inner}}})"


def T(*args: Any, **kwargs: Any) -> Table:
    """Builder mirroring the reference's ``T(...)`` (utils/Table.scala)."""
    return Table(*args, **kwargs)


def _table_flatten(t: Table):
    keys = sorted(t._state.keys(), key=lambda k: (0, k) if isinstance(k, int) else (1, str(k)))
    return [t._state[k] for k in keys], tuple(keys)


def _table_unflatten(keys, values):
    t = Table()
    t._state = dict(zip(keys, values))
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
