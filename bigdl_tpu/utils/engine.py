"""Engine: topology discovery and runtime configuration.

TPU-native rebuild of the reference's ``utils/Engine.scala`` (84-445).  The
reference derives (nodeNumber, coresPerNode) from the Spark conf and runs
``coresPerNode`` thread-replicas per executor, each pinned to one MKL thread.
On TPU the mapping is:

    one Spark executor ("node")      -> one JAX process (host)
    one core-thread model replica    -> one TPU chip (one mesh slot)
    Engine.init / checkSingleton     -> jax.distributed.initialize + device
                                        enumeration (one process owns the
                                        host's chips)
    Engine.default / Engine.model    -> host thread pool for the input
                                        pipeline; on-device parallelism is
                                        XLA's job.

There are no thread-replica semantics to reproduce on device: XLA batches
natively, so ``core_number`` counts *local devices*, not threads.
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Callable, Optional, Sequence


class ThreadPool:
    """Host-side task pool (ref utils/ThreadPool.scala:92-168).

    Used by the data pipeline for threaded prefetch/decode, the role
    ``Engine.default`` played for the reference's coarse host tasks.  The
    straggler-timeout variant ``invoke_and_wait2`` is kept for API parity,
    though under SPMD lockstep on TPU it only gates *host* work.
    """

    def __init__(self, size: int):
        self._size = size
        self._pool = ThreadPoolExecutor(max_workers=size, thread_name_prefix="bigdl-tpu")

    @property
    def size(self) -> int:
        return self._size

    def invoke(self, tasks: Sequence[Callable]) -> list[Future]:
        return [self._pool.submit(t) for t in tasks]

    def invoke_and_wait(self, tasks: Sequence[Callable]) -> list:
        return [f.result() for f in self.invoke(tasks)]

    def invoke_and_wait2(self, tasks: Sequence[Callable], timeout: Optional[float] = None) -> list[Future]:
        """Submit all tasks, wait up to ``timeout`` seconds; returns futures
        (some possibly unfinished — the caller decides what to drop).

        Only *timeouts* are swallowed (that is the straggler-drop
        semantic); a task that raised re-raises here after every other
        task has been waited on — a worker dying with a real error is a
        bug, not a straggler (the reference distinguishes the two the
        same way: invokeAll returns, then Future.get rethrows)."""
        futures = self.invoke(tasks)
        first_error: Optional[Exception] = None
        for f in futures:
            try:
                f.result(timeout=timeout)
            except FuturesTimeoutError:
                pass  # straggler: caller inspects f.done() and drops it
            except Exception as e:  # task failure (KeyboardInterrupt et al.
                # propagate immediately — don't hold Ctrl-C hostage)
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return futures

    def sync(self, futures: Sequence[Future]) -> None:
        for f in futures:
            f.result()

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class _EngineState:
    def __init__(self):
        self.initialized = False
        self.node_number = 1
        self.core_number = 1
        self.default_pool: Optional[ThreadPool] = None
        self.model_pool: Optional[ThreadPool] = None
        self.lock = threading.Lock()
        self.singleton_claimed = False


_state = _EngineState()


def ensure_virtual_devices(n: int):
    """Return >= ``n`` devices: already-initialised real accelerator
    devices when the process has enough of them, else a virtual CPU pool
    (the analog of the reference's simulated-multinode trick:
    DistriOptimizerSpec runs 4 "nodes" as 4 partitions in one local[1]
    JVM, optim/DistriOptimizerSpec.scala:39-43).  This function never
    initialises an accelerator backend itself — on a fresh process it
    selects the cpu platform, so an absent/unreachable TPU cannot hang
    the bootstrap.

    ``--xla_force_host_platform_device_count`` only takes effect if set
    before the first backend initialisation in the process, hence the env
    mutation before any ``jax.devices()`` call.  Used by the driver's
    ``dryrun_multichip`` and the perf scaling sweep."""
    import re

    want = max(8, n)
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None or int(m.group(1)) < want:
        if m is not None:
            flags = flags.replace(m.group(0), "")
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={want}").strip()
    import jax

    try:
        from jax._src import xla_bridge as _xb
        initialized = _xb.backends_are_initialized()
    except Exception:
        initialized = False

    if initialized:
        # backends already live in this process: reuse real accelerator
        # devices when the host actually has enough of them (no new
        # backend is dialed — jax.devices() is a cache read here).
        try:
            devices = list(jax.devices())
            if len(devices) >= n:
                return devices[:n]
        except RuntimeError:
            pass
    elif str(jax.config.jax_platforms or "") != "cpu":
        # First backend use in the process: select the cpu platform
        # outright.  jax.config wins over the JAX_PLATFORMS env var (site
        # customisations may pin that to an accelerator), and never
        # initialising the accelerator also means a slow or unreachable
        # tunneled TPU cannot hang or fail this bootstrap — the exact
        # failure mode that turned round 1's multichip check red.  Must
        # be exactly "cpu": a list like "axon,cpu" still initialises the
        # accelerator backend on the first jax.devices() call.  The pin
        # is process-global; release_virtual_devices() undoes it for
        # callers that later want the real accelerator in this process.
        global _pin_active, _pinned_prior_platforms
        _pin_active = True
        _pinned_prior_platforms = jax.config.jax_platforms
        jax.config.update("jax_platforms", "cpu")

    try:
        devices = list(jax.devices("cpu"))
    except RuntimeError as e:
        raise RuntimeError(
            f"need {n} devices and the cpu fallback backend is "
            f"unavailable — a jax backend was initialised before this "
            f"call, so XLA_FLAGS was set too late; restart and request "
            f"the virtual devices before any other jax use.") from e
    if len(devices) < n:
        raise RuntimeError(
            f"need {n} devices; have {len(devices)} CPU virtual devices. "
            f"If a jax backend was initialised before this call, XLA_FLAGS "
            f"was set too late — restart and request the virtual devices "
            f"before any other jax use.")
    return devices[:n]


_pin_active = False
_pinned_prior_platforms = None


def select_platform(platform: Optional[str] = None, *,
                    honor_jax_platforms: bool = False) -> Optional[str]:
    """Pin the JAX platform before the first backend touch and return
    the effective choice (or None for "leave it to jax").

    Resolution order: explicit arg > ``BIGDL_TPU_PLATFORM`` > (with
    ``honor_jax_platforms``) ``JAX_PLATFORMS``.  The env's
    sitecustomize imports jax at interpreter start with JAX_PLATFORMS
    already consumed, so a plain env var is IGNORED for CLIs —
    ``jax.config.update`` before first backend use is the supported
    escape hatch, and this helper is its single home (Engine.init,
    bench.py --serve and serving all route through it).  JAX_PLATFORMS
    is opt-in because library callers (Engine.init under tests) must
    not let a sitecustomize-exported accelerator value override an
    already-pinned cpu platform.  Once a backend is initialized the
    pin is too late; the attempt is swallowed and the live platform
    wins.
    """
    import jax

    platform = (platform
                or os.environ.get("BIGDL_TPU_PLATFORM")
                or (os.environ.get("JAX_PLATFORMS")
                    if honor_jax_platforms else None))
    if platform and jax.config.jax_platforms != platform:
        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            pass  # backend already initialized; too late to switch
    return platform or None


def release_virtual_devices() -> None:
    """Undo ``ensure_virtual_devices``' process-global cpu-platform pin:
    restore the prior ``jax_platforms`` setting and clear the cached
    backend set, so the next ``jax.devices()`` re-reads it and real
    accelerators become visible again.  Arrays created on the virtual
    pool keep referencing their (now un-cached) cpu client and stay
    readable — the same contract the jax ``clear_backends`` API gives.
    No-op when nothing was pinned."""
    global _pin_active, _pinned_prior_platforms
    if not _pin_active:
        return
    import jax
    from jax.extend.backend import clear_backends

    jax.config.update("jax_platforms", _pinned_prior_platforms)
    _pin_active = False
    _pinned_prior_platforms = None
    clear_backends()


class Engine:
    """Singleton runtime facade (ref utils/Engine.scala:84-99,142-146)."""

    @staticmethod
    def init(node_number: Optional[int] = None, core_number: Optional[int] = None,
             platform: Optional[str] = None) -> None:
        """Discover topology.  With no args: local mode uses the current
        process's devices (ref Engine.init no-arg, utils/Engine.scala:84-99);
        in a multi-host job call ``jax.distributed.initialize`` first (the
        analog of launching on Spark) and Engine picks up process/device
        counts from JAX.

        ``platform`` (or the ``BIGDL_TPU_PLATFORM`` env var — Engine owns
        env bootstrap like the reference's BIGDL_LOCAL_MODE/DL_CORE_NUMBER
        contract, utils/Engine.scala:103-157) pins the JAX platform (e.g.
        "cpu") before the first backend touch; useful when a sitecustomize
        preselected an accelerator this process shouldn't use.
        """
        import jax

        select_platform(platform)
        # resilience hook: simulate the classic failure where the
        # tunneled backend never answers the first jax.devices() touch
        from bigdl_tpu.resilience.faults import fault_point
        fault_point("engine.init")

        with _state.lock:
            if node_number is None:
                node_number = jax.process_count()
            if core_number is None:
                if os.environ.get("DL_CORE_NUMBER"):
                    core_number = int(os.environ["DL_CORE_NUMBER"])
                else:
                    core_number = jax.local_device_count()
            _state.node_number = node_number
            _state.core_number = core_number
            host_threads = int(os.environ.get("BIGDL_TPU_DEFAULT_POOL_SIZE", str(max(os.cpu_count() or 4, 4))))
            if _state.default_pool is None:
                _state.default_pool = ThreadPool(host_threads)
            if _state.model_pool is None:
                _state.model_pool = ThreadPool(core_number)
            _state.initialized = True

    @staticmethod
    def node_number() -> int:
        Engine._require_init()
        return _state.node_number

    @staticmethod
    def core_number() -> int:
        Engine._require_init()
        return _state.core_number

    @staticmethod
    def default() -> ThreadPool:
        Engine._require_init()
        return _state.default_pool  # type: ignore[return-value]

    @staticmethod
    def default_or_create(size: Optional[int] = None) -> ThreadPool:
        """The shared host pool, created lazily if Engine.init has not
        run yet.  Serving and other host-side consumers reuse ONE pool
        per process instead of each spinning a private executor; a
        later Engine.init adopts the same pool (init only fills the
        slot when empty)."""
        with _state.lock:
            if _state.default_pool is None:
                host_threads = size or int(os.environ.get(
                    "BIGDL_TPU_DEFAULT_POOL_SIZE",
                    str(max(os.cpu_count() or 4, 4))))
                _state.default_pool = ThreadPool(host_threads)
            return _state.default_pool

    @staticmethod
    def model() -> ThreadPool:
        Engine._require_init()
        return _state.model_pool  # type: ignore[return-value]

    @staticmethod
    def check_singleton() -> bool:
        """Atomic guard: only one Engine owner per process (ref
        utils/Engine.scala:164-174 — one BigDL task per executor JVM; here,
        one trainer per process, since the process owns the host's TPUs)."""
        if os.environ.get("BIGDL_TPU_CHECK_SINGLETON", "1") in ("0", "false"):
            return True
        with _state.lock:
            if _state.singleton_claimed:
                return False
            _state.singleton_claimed = True
            return True

    @staticmethod
    def diagnose_tpu() -> str:
        """Report processes that look like stale TPU holders — the wedge
        where a dead trainer keeps the chip claimed and every new backend
        init hangs or returns UNAVAILABLE until the holder is reaped
        (the single-chip analog of the reference's checkSingleton guard:
        utils/Engine.scala:164-174 prevents two tasks sharing an
        executor; here two processes sharing a chip).  Pure /proc scan —
        never touches the jax backend, so it is safe to call while the
        chip is wedged."""
        notes = []
        lockfile = "/tmp/libtpu_lockfile"
        if os.path.exists(lockfile):
            notes.append(f"{lockfile} exists")
        me = os.getpid()
        try:
            for pid in os.listdir("/proc"):
                if not pid.isdigit() or int(pid) == me:
                    continue
                try:
                    with open(f"/proc/{pid}/cmdline", "rb") as f:
                        cmd = f.read().replace(b"\0", b" ").decode(
                            errors="replace")
                    with open(f"/proc/{pid}/maps", "r",
                              errors="replace") as f:
                        maps = f.read()
                except OSError:
                    continue
                if cmd and ("libtpu" in maps or "accel" in maps):
                    notes.append(f"pid {pid} holds libtpu: {cmd[:120]}")
        except OSError:
            pass
        notes.extend(Engine._diagnose_tunnel())
        notes.extend(Engine._diagnose_memory())
        return "; ".join(notes) if notes else "no stale TPU holder found"

    @staticmethod
    def _diagnose_memory() -> list:
        """Memory-ledger capacity state for stall/flight dumps.  Reads
        only the ledger's host-side totals and its LAST reconcile
        verdict — never the jax backend (this report must stay safe to
        produce while the chip is wedged)."""
        try:
            from bigdl_tpu.obs.ledger import get_ledger
            s = get_ledger().summary()
        except Exception:
            return []
        if not s["entries"] and not s["executables"]:
            return []   # nothing registered: keep the report terse
        last = s.get("last_reconcile") or {}
        drift = last.get("drift_bytes")
        verdict = last.get("verdict", "never_run")
        return [f"memory: ledger={s['ledger_bytes']}B across "
                f"{s['subsystems']} subsystems, "
                f"{s['executables']} executables, "
                f"drift={drift if drift is not None else 'n/a'} "
                f"({verdict})"]

    @staticmethod
    def _diagnose_tunnel() -> list:
        """Probe the tunneled-backend control plane.  When the backend
        proxies to a remote pool (PALLAS_AXON_POOL_IPS / a
        *_POOL_SVC_OVERRIDE host), client init dials the pool service and
        terminal ports on that host and, if nothing listens, retries with
        backoff forever — from the outside indistinguishable from a slow
        compile.  A 1s TCP probe per port names the difference: refused
        means the relay/terminal process is gone (infra, not us); a
        listener that accepts means the hang is past connect (claim or
        compile)."""
        host = None
        for var in ("AXON_POOL_SVC_OVERRIDE", "PALLAS_AXON_POOL_IPS"):
            v = os.environ.get(var)
            if v:
                host = v.split(",")[0].strip()
                break
        if not host:
            return []
        import socket
        targets = [(8080, "pool-svc"), (8083, "terminal")]
        if host.startswith("["):  # bracketed IPv6, maybe [::1]:8080
            inner, _, rest = host[1:].partition("]")
            host = inner
            if rest.startswith(":"):
                try:
                    targets = [(int(rest[1:]), "pool-svc")]
                except ValueError:
                    return []  # unparseable — better silent than misleading
        elif host.count(":") == 1:  # host:port form (bare IPv6 has >1)
            host, _, explicit = host.partition(":")
            try:
                targets = [(int(explicit), "pool-svc")]
            except ValueError:
                return []
        notes = []
        for port, what in targets:
            try:
                with socket.create_connection((host, port), timeout=1.0):
                    notes.append(f"{what} {host}:{port} accepts connections")
            except OSError as e:
                notes.append(
                    f"{what} {host}:{port} unreachable ({e.strerror or e}) "
                    "- backend init will retry forever; the tunnel relay "
                    "appears to be down")
        return notes

    @staticmethod
    def reset() -> None:
        """Test hook: clear init + singleton state."""
        with _state.lock:
            _state.initialized = False
            _state.singleton_claimed = False
            _state.node_number = 1
            _state.core_number = 1

    @staticmethod
    def is_initialized() -> bool:
        return _state.initialized

    @staticmethod
    def _require_init() -> None:
        if not _state.initialized:
            raise RuntimeError(
                "Engine.init() must be called before use. In a multi-host job, "
                "call jax.distributed.initialize() first."
            )
