"""Import PyTorch checkpoints into bigdl_tpu models.

The modern analog of the reference's pretrained-model import path
(ref example/loadmodel/ModelValidator.scala drives Torch/Caffe imports;
utils/CaffeLoader.scala:61-75 copies blobs by position into the
matching modules): today's pretrained checkpoints are PyTorch state
dicts, so "switch from the source framework and keep your weights"
means mapping a ``model.state_dict()`` onto a bigdl_tpu module tree.

Mapping model: both frameworks enumerate parameterized modules in
definition order — a torch ``nn.Module``'s ``state_dict()`` preserves
registration order, and a bigdl_tpu container walks its children in
forward order — so the i-th torch parameter GROUP (all entries sharing
a key prefix: ``layer1.0.conv1.{weight,bias}``) corresponds to the
i-th parameterized bigdl_tpu leaf.  Weight layouts already agree by
construction (bigdl_tpu keeps Torch conventions for import parity:
Linear ``(out, in)``, conv ``OIHW``, transposed conv ``(in, out, kh,
kw)`` — see nn/linear.py, nn/conv.py), so the copy is shape-checked
but transformation-free; BatchNorm running statistics land in the
buffer tree.

The positional contract requires the torch twin to declare its modules
in forward order (true for torchvision-style models).  A count or
shape mismatch raises with both sides' inventories — the same contract
``CaffeLoader.load(match_all=true)`` enforces.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import logging

import numpy as np
import jax.numpy as jnp

log = logging.getLogger("bigdl_tpu.torch_import")


#: state-dict entries that carry no weight data
_IGNORED_SUFFIXES = ("num_batches_tracked",)
#: suffixes that land in the buffer tree instead of params
_BUFFER_SUFFIXES = ("running_mean", "running_var")


def _to_numpy(v) -> np.ndarray:
    """Accept torch tensors, numpy arrays, or anything array-like —
    the importer itself must not require torch."""
    if hasattr(v, "detach"):  # torch.Tensor without importing torch
        v = v.detach().cpu()
        try:
            v = v.numpy()
        except TypeError:
            # dtypes numpy can't hold (bf16 checkpoints are common):
            # widen to f32 — the copy is cast to the model leaf's dtype
            # at assignment anyway
            v = v.float().numpy()
    return np.asarray(v)


def chunked_device_array(a, dtype=None, limit_bytes=32 << 20,
                         force=False):
    """Device array from host data in <=32 MB leading-axis slices, one
    in flight at a time — the tunneled TPU relay dies on large single
    host->device transfers (~154 MB killed round 4's; NOTES_r4.md), and
    GPT-2-scale embeddings/projections are exactly that size.  Same
    pattern as bench.py's chunked input upload.  Single-shot for small
    arrays and on CPU."""
    import jax
    a = np.asarray(a, dtype) if dtype is not None else np.asarray(a)
    if not force and (a.ndim == 0 or a.nbytes <= limit_bytes
                      or jax.devices()[0].platform == "cpu"):
        return jnp.asarray(a)
    rows = max(1, limit_bytes // max(a[0:1].nbytes, 1))
    parts = []
    for i in range(0, a.shape[0], rows):
        p = jnp.asarray(a[i:i + rows])
        p.block_until_ready()  # one in-flight slice at a time
        parts.append(p)
    out = jnp.concatenate(parts, axis=0)
    out.block_until_ready()
    return out


def read_torch_checkpoint(path):
    """``torch.load`` a checkpoint file and unwrap the common trainer
    wrapper keys ('state_dict', 'model') down to the flat state dict."""
    import torch
    obj = torch.load(path, map_location="cpu", weights_only=True)
    for key in ("state_dict", "model"):
        if isinstance(obj, dict) and key in obj and not hasattr(obj[key], "shape"):
            inner = obj[key]
            if isinstance(inner, dict):
                obj = inner
                break
    return obj


def group_state_dict(state_dict) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """Group flat ``{key: tensor}`` entries by module prefix, in order of
    first appearance: ``layer1.0.conv1.weight`` -> prefix
    ``layer1.0.conv1``, leaf ``weight``."""
    groups: List[Tuple[str, Dict[str, np.ndarray]]] = []
    index: Dict[str, Dict[str, np.ndarray]] = {}
    for key, value in state_dict.items():
        prefix, _, leaf = key.rpartition(".")
        if leaf in _IGNORED_SUFFIXES:
            continue
        if prefix not in index:
            index[prefix] = {}
            groups.append((prefix, index[prefix]))
        index[prefix][leaf] = _to_numpy(value)
    return groups


def _walk_leaves(module, params, buffers, path, proto=None):
    """Yield (path, module, param_dict, buffer_dict, param_proto) for
    every parameterized or buffer-holding LEAF module, in forward order.
    The yielded dicts are the live sub-dicts of the params/buffers
    trees, so assignment into them updates the trees; ``param_proto``
    is the leaf's definition-order key structure when a nested descent
    already computed it (None = compute lazily if needed)."""
    children = getattr(module, "modules", None)
    if children:
        # containers key children "0", "1", ... (Container.init);
        # wrapper modules (TimeDistributed, Recurrent, BiRecurrent) use
        # named keys — resolve by matching the child into the param tree
        keys = _child_keys(module)
        for key, child in zip(keys, children):
            yield from _walk_leaves(
                child,
                (params or {}).get(key, {}),
                (buffers or {}).get(key, {}),
                f"{path}.{key}" if path else key)
        return
    if params and all(isinstance(v, dict) for v in params.values()):
        # nested leaf params (Scale's {cmul: {...}, cadd: {...}}): each
        # sub-dict is its own positional group, matching both a
        # structure-mirroring torch twin and this module's own export.
        # Iterate in DEFINITION order (module.init insertion order) —
        # the params tree loses it to jax pytree key sorting the first
        # time it passes through tree_map
        ptree = proto if proto is not None else _init_proto(module)
        for k in _ordered_keys(params, ptree, module, "nested param group"):
            sub = ptree.get(k) if isinstance(ptree, dict) else None
            yield from _walk_leaves(module, params[k],
                                    (buffers or {}).get(k, {}),
                                    f"{path}.{k}" if path else k,
                                    proto=sub)
        return
    if params or buffers:
        yield path, module, params, buffers, proto


def _init_proto(module):
    """The definition-order key structure of ``module.init``, from a
    DIRECT init call.  The live params tree cannot supply this: a tree
    that has passed through any jax pytree op (``tree_map``,
    ``eval_shape``, jit boundaries) comes back with ALPHABETICALLY
    sorted dict keys — jax canonicalizes pytree dicts, which is exactly
    why ``jax.eval_shape(module.init, ...)`` cannot be used here even
    though it would skip computing the values.  A direct call returns
    the dict exactly as init constructed it, insertion order intact;
    the redundant weight materialization is accepted (export is a rare
    interop operation).  None when init fails out of context."""
    import jax
    try:
        return module.init(jax.random.PRNGKey(0))
    except Exception:
        return None


def _ordered_keys(keys, proto, module, what) -> List[str]:
    """``keys`` in proto's definition order; alphabetical fallback is
    LOUD — silent alphabetical ordering is exactly the weight/bias swap
    hazard this machinery exists to prevent."""
    if proto is None:
        log.warning(
            "definition order unavailable for %s (init failed out of "
            "context): exporting its %s in alphabetical order — verify "
            "any positional rename onto a torch module by shape",
            type(module).__name__, what)
        return sorted(keys)
    order = {k: i for i, k in enumerate(proto)}
    return sorted(keys, key=lambda k: (order.get(k, len(order)), k))


def _child_keys(module) -> List[str]:
    """Param-tree keys for a composite's children, in child order."""
    from bigdl_tpu import nn
    if isinstance(module, nn.TimeDistributed):
        return ["module"]
    if isinstance(module, nn.Recurrent):
        return ["cell"]
    if isinstance(module, nn.BiRecurrent):
        return ["fwd", "bwd"]
    return [str(i) for i in range(len(module.modules))]


def load_torch_state_dict(model, state_dict, *, strict: bool = True):
    """Copy a PyTorch ``state_dict`` into ``model``'s params/buffers.

    ``model`` must be built (``model.build(seed)``); returns the model
    with ``model.params`` / ``model.buffers`` holding the imported
    values (the trees are rebuilt, not mutated in place).  With
    ``strict`` (default, = the reference's ``match_all``) the group
    count must match exactly; otherwise the common prefix is copied.
    """
    params = model._built()
    buffers = model.buffers if model.buffers else model.init_buffers()
    # deep-copy into mutable numpy trees so assignment is local
    params = _copy_tree(params)
    buffers = _copy_tree(buffers)

    ours = list(_walk_leaves(model, params, buffers, ""))
    theirs = group_state_dict(state_dict)
    if len(ours) != len(theirs):
        if strict:
            raise ValueError(
                f"module count mismatch: model has {len(ours)} "
                f"parameterized leaves, state_dict has {len(theirs)} "
                f"groups\n{_inventory(ours, theirs)}")
        # strict=False truncates to the common positional prefix — say
        # exactly what fell off each side, because a count mismatch
        # usually means the alignment SHIFTED somewhere earlier and the
        # "matched" prefix is silently importing wrong weights
        n = min(len(ours), len(theirs))
        unmatched_ours = [
            f"{path or '<root>'} ({type(m).__name__}"
            f"{sorted(p) + sorted(b)})"
            for path, m, p, b, _pr in ours[n:]]
        unmatched_theirs = [f"{prefix} ({sorted(g)})"
                            for prefix, g in theirs[n:]]
        log.warning(
            "strict=False: copying the first %d positional groups; "
            "%d model leaves left unmatched: %s; %d state-dict groups "
            "left unmatched: %s — verify the matched prefix is really "
            "aligned (a skipped module shifts every later group)",
            n, len(unmatched_ours), unmatched_ours or "none",
            len(unmatched_theirs), unmatched_theirs or "none")
    for (path, mod, p_leaf, b_leaf, _proto), (prefix, group) in zip(ours, theirs):
        group = _adapt_torch_rnn_group(mod, p_leaf, group, prefix, path)
        for leaf_name, value in group.items():
            target = b_leaf if leaf_name in _BUFFER_SUFFIXES else p_leaf
            if leaf_name not in target:
                raise ValueError(
                    f"{prefix}.{leaf_name}: {type(mod).__name__} at "
                    f"'{path}' has no matching slot "
                    f"(has {sorted(target)})")
            have = target[leaf_name]
            if tuple(np.shape(have)) != tuple(value.shape):
                raise ValueError(
                    f"{prefix}.{leaf_name} -> {type(mod).__name__} at "
                    f"'{path}': shape {tuple(value.shape)} vs expected "
                    f"{tuple(np.shape(have))}")
            target[leaf_name] = chunked_device_array(
                value.astype(np.asarray(have).dtype, copy=False))
    model.params = params
    model.buffers = buffers
    return model


def _adapt_torch_rnn_group(mod, p_leaf, group, prefix, path):
    """Convert a torch ``nn.RNN/LSTM/GRU`` (or ``*Cell``) parameter
    group onto our recurrent-cell layout: torch stores
    ``weight_ih (gH, in)`` / ``weight_hh (gH, H)`` and TWO bias vectors
    where we store transposed ``w_ih (in, gH)`` / ``w_hh (H, gH)`` and
    one fused ``bias`` (= bias_ih + bias_hh; both frameworks add them
    to the same pre-activation, and the gate orders already agree:
    i|f|g|o for LSTM, r|z|n for GRU — for GRU torch's n-gate applies
    ``bias_hh`` inside the reset product, so a nonzero ``bias_hh_l*``
    n-slice cannot be represented exactly and is rejected)."""
    suffixes = {k.rsplit("_l", 1)[0] if "_l" in k else k: k
                for k in group}
    if not {"weight_ih", "weight_hh"} <= set(suffixes) or "w_ih" not in p_leaf:
        return group
    # reject multi-layer/bidirectional modules FIRST: their colliding
    # l0/l1/_reverse suffixes would otherwise trip the bias check below
    # with a misleading diagnostic
    extra = set(group) - {suffixes[s] for s in
                          ("weight_ih", "weight_hh", "bias_ih", "bias_hh")
                          if s in suffixes}
    if extra:
        raise ValueError(f"{prefix}: unsupported torch RNN entries "
                         f"{sorted(extra)} (multi-layer/bidirectional "
                         f"torch RNN modules import layer-by-layer)")
    H = np.shape(p_leaf["w_hh"])[0]
    w_ih = group[suffixes["weight_ih"]].T
    w_hh = group[suffixes["weight_hh"]].T
    zeros = np.zeros(w_ih.shape[1], np.float32)
    # bias=False checkpoints carry no bias entries: the exact mapping is
    # a ZERO fused bias — leaving the model's random init would be a
    # silent wrong-output import
    b_ih = group.get(suffixes.get("bias_ih", ""), zeros)
    b_hh = group.get(suffixes.get("bias_hh", ""), zeros)
    if w_ih.shape[1] == 3 * H and np.any(b_hh[2 * H:]):
        raise ValueError(
            f"{prefix} -> {type(mod).__name__} at '{path}': torch GRU "
            f"applies bias_hh's n-gate slice inside the reset "
            f"product; a nonzero slice cannot map onto the fused "
            f"bias layout — retrain with bias_hh=0 or import "
            f"manually")
    return {"w_ih": w_ih, "w_hh": w_hh, "bias": b_ih + b_hh}


def load_torch_checkpoint(model, path: str, *, strict: bool = True):
    """Load a ``torch.save``d checkpoint file (a state dict, or a dict
    holding one under 'state_dict'/'model') into ``model``."""
    return load_torch_state_dict(model, read_torch_checkpoint(path),
                                 strict=strict)


def export_torch_state_dict(model) -> "dict":
    """The reverse direction: a built model's params/buffers as a flat
    PyTorch-convention state dict (numpy values; pass through
    ``torch.from_numpy`` tree-wise to feed ``torch_model.load_state_dict``).
    Keys are the model's own tree paths (``0.weight``, ``3.running_mean``
    ...), which round-trip through :func:`load_torch_state_dict`'s
    positional contract (nested leaf params like Scale's export as
    ``i.cmul.weight`` and pair back as their own groups); loading into
    an actual torch module whose prefixes differ only needs a key
    rename, since the ORDER matches by the same definition-order
    contract."""
    if model.params is None:
        # the import direction may build lazily (imported values
        # overwrite the init), but silently exporting fresh random
        # init as if it were trained weights is a wrong-output hazard
        raise ValueError("model has no params to export — call "
                         "model.build(seed) (or train it) first")
    buffers = model.buffers if model.buffers else model.init_buffers()
    out = {}
    for path, mod, p_leaf, b_leaf, proto in _walk_leaves(
            model, model.params, buffers, ""):
        # _walk_leaves descends into nested leaf dicts, so values here
        # are always arrays.  Emit params in DEFINITION order (weight
        # before bias, w_ih before w_hh before bias, ...): the live
        # tree's key order is alphabetical after any tree_map, and a
        # positional rename onto a torch twin depends on this order
        if len(p_leaf) > 1 and proto is None:
            proto = _init_proto(mod)
        names = (list(p_leaf) if len(p_leaf) < 2
                 else _ordered_keys(p_leaf, proto, mod, "params"))
        for name in names:
            out[f"{path}.{name}" if path else name] = np.asarray(p_leaf[name])
        bproto = None
        if len(b_leaf) > 1:
            try:  # direct call: eval_shape would sort the keys (above)
                bproto = mod.init_buffers()
            except Exception:
                bproto = None
        bnames = (list(b_leaf) if len(b_leaf) < 2
                  else _ordered_keys(b_leaf, bproto, mod, "buffers"))
        for name in bnames:
            out[f"{path}.{name}" if path else name] = np.asarray(b_leaf[name])
    return out


def _copy_tree(t):
    if isinstance(t, dict):
        return {k: _copy_tree(v) for k, v in t.items()}
    return t


def _inventory(ours, theirs) -> str:
    left = [f"  model[{i}] {path or '<root>'}: {type(m).__name__}"
            f"{sorted(p) + sorted(b)}"
            for i, (path, m, p, b, _pr) in enumerate(ours)]
    right = [f"  torch[{i}] {prefix}: {sorted(g)}"
             for i, (prefix, g) in enumerate(theirs)]
    return "\n".join(left + right)
