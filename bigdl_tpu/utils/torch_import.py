"""Import PyTorch checkpoints into bigdl_tpu models.

The modern analog of the reference's pretrained-model import path
(ref example/loadmodel/ModelValidator.scala drives Torch/Caffe imports;
utils/CaffeLoader.scala:61-75 copies blobs by position into the
matching modules): today's pretrained checkpoints are PyTorch state
dicts, so "switch from the source framework and keep your weights"
means mapping a ``model.state_dict()`` onto a bigdl_tpu module tree.

Mapping model: both frameworks enumerate parameterized modules in
definition order — a torch ``nn.Module``'s ``state_dict()`` preserves
registration order, and a bigdl_tpu container walks its children in
forward order — so the i-th torch parameter GROUP (all entries sharing
a key prefix: ``layer1.0.conv1.{weight,bias}``) corresponds to the
i-th parameterized bigdl_tpu leaf.  Weight layouts already agree by
construction (bigdl_tpu keeps Torch conventions for import parity:
Linear ``(out, in)``, conv ``OIHW``, transposed conv ``(in, out, kh,
kw)`` — see nn/linear.py, nn/conv.py), so the copy is shape-checked
but transformation-free; BatchNorm running statistics land in the
buffer tree.

The positional contract requires the torch twin to declare its modules
in forward order (true for torchvision-style models).  A count or
shape mismatch raises with both sides' inventories — the same contract
``CaffeLoader.load(match_all=true)`` enforces.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np
import jax.numpy as jnp


#: state-dict entries that carry no weight data
_IGNORED_SUFFIXES = ("num_batches_tracked",)
#: suffixes that land in the buffer tree instead of params
_BUFFER_SUFFIXES = ("running_mean", "running_var")


def _to_numpy(v) -> np.ndarray:
    """Accept torch tensors, numpy arrays, or anything array-like —
    the importer itself must not require torch."""
    if hasattr(v, "detach"):  # torch.Tensor without importing torch
        v = v.detach().cpu()
        try:
            v = v.numpy()
        except TypeError:
            # dtypes numpy can't hold (bf16 checkpoints are common):
            # widen to f32 — the copy is cast to the model leaf's dtype
            # at assignment anyway
            v = v.float().numpy()
    return np.asarray(v)


def group_state_dict(state_dict) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """Group flat ``{key: tensor}`` entries by module prefix, in order of
    first appearance: ``layer1.0.conv1.weight`` -> prefix
    ``layer1.0.conv1``, leaf ``weight``."""
    groups: List[Tuple[str, Dict[str, np.ndarray]]] = []
    index: Dict[str, Dict[str, np.ndarray]] = {}
    for key, value in state_dict.items():
        prefix, _, leaf = key.rpartition(".")
        if leaf in _IGNORED_SUFFIXES:
            continue
        if prefix not in index:
            index[prefix] = {}
            groups.append((prefix, index[prefix]))
        index[prefix][leaf] = _to_numpy(value)
    return groups


def _walk_leaves(module, params, buffers, path):
    """Yield (path, module, param_dict, buffer_dict) for every
    parameterized or buffer-holding LEAF module, in forward order.
    The yielded dicts are the live sub-dicts of the params/buffers
    trees, so assignment into them updates the trees."""
    children = getattr(module, "modules", None)
    if children:
        # containers key children "0", "1", ... (Container.init);
        # wrapper modules (TimeDistributed, Recurrent, BiRecurrent) use
        # named keys — resolve by matching the child into the param tree
        keys = _child_keys(module)
        for key, child in zip(keys, children):
            yield from _walk_leaves(
                child,
                (params or {}).get(key, {}),
                (buffers or {}).get(key, {}),
                f"{path}.{key}" if path else key)
        return
    if params or buffers:
        yield path, module, params, buffers


def _child_keys(module) -> List[str]:
    """Param-tree keys for a composite's children, in child order."""
    from bigdl_tpu import nn
    if isinstance(module, nn.TimeDistributed):
        return ["module"]
    if isinstance(module, nn.Recurrent):
        return ["cell"]
    if isinstance(module, nn.BiRecurrent):
        return ["fwd", "bwd"]
    return [str(i) for i in range(len(module.modules))]


def load_torch_state_dict(model, state_dict, *, strict: bool = True):
    """Copy a PyTorch ``state_dict`` into ``model``'s params/buffers.

    ``model`` must be built (``model.build(seed)``); returns the model
    with ``model.params`` / ``model.buffers`` holding the imported
    values (the trees are rebuilt, not mutated in place).  With
    ``strict`` (default, = the reference's ``match_all``) the group
    count must match exactly; otherwise the common prefix is copied.
    """
    params = model._built()
    buffers = model.buffers if model.buffers else model.init_buffers()
    # deep-copy into mutable numpy trees so assignment is local
    params = _copy_tree(params)
    buffers = _copy_tree(buffers)

    ours = list(_walk_leaves(model, params, buffers, ""))
    theirs = group_state_dict(state_dict)
    if len(ours) != len(theirs) and strict:
        raise ValueError(
            f"module count mismatch: model has {len(ours)} "
            f"parameterized leaves, state_dict has {len(theirs)} "
            f"groups\n{_inventory(ours, theirs)}")
    for (path, mod, p_leaf, b_leaf), (prefix, group) in zip(ours, theirs):
        for leaf_name, value in group.items():
            target = b_leaf if leaf_name in _BUFFER_SUFFIXES else p_leaf
            if leaf_name not in target:
                raise ValueError(
                    f"{prefix}.{leaf_name}: {type(mod).__name__} at "
                    f"'{path}' has no matching slot "
                    f"(has {sorted(target)})")
            have = target[leaf_name]
            if tuple(np.shape(have)) != tuple(value.shape):
                raise ValueError(
                    f"{prefix}.{leaf_name} -> {type(mod).__name__} at "
                    f"'{path}': shape {tuple(value.shape)} vs expected "
                    f"{tuple(np.shape(have))}")
            target[leaf_name] = jnp.asarray(
                value.astype(np.asarray(have).dtype, copy=False))
    model.params = params
    model.buffers = buffers
    return model


def load_torch_checkpoint(model, path: str, *, strict: bool = True):
    """Load a ``torch.save``d checkpoint file (a state dict, or a dict
    holding one under 'state_dict'/'model') into ``model``."""
    import torch
    obj = torch.load(path, map_location="cpu", weights_only=True)
    for key in ("state_dict", "model"):
        if isinstance(obj, dict) and key in obj and not hasattr(obj[key], "shape"):
            inner = obj[key]
            if isinstance(inner, dict):
                obj = inner
                break
    return load_torch_state_dict(model, obj, strict=strict)


def _copy_tree(t):
    if isinstance(t, dict):
        return {k: _copy_tree(v) for k, v in t.items()}
    return t


def _inventory(ours, theirs) -> str:
    left = [f"  model[{i}] {path or '<root>'}: {type(m).__name__}"
            f"{sorted(p) + sorted(b)}"
            for i, (path, m, p, b) in enumerate(ours)]
    right = [f"  torch[{i}] {prefix}: {sorted(g)}"
             for i, (prefix, g) in enumerate(theirs)]
    return "\n".join(left + right)
