"""Pluggable filesystem layer: scheme-dispatched IO for checkpoints and
model files (ref utils/File.scala:62-122, whose save/load transparently
handle ``hdfs:`` URIs — the TPU-cloud equivalents are ``gs://`` object
stores, reached here through fsspec).

Built-ins:
  - local paths (no scheme or ``file://``)
  - ``memory://`` — an in-process store, the mock remote FS for tests
  - any other scheme (``gs://``, ``hdfs://``, ``s3://``) falls through to
    fsspec when installed; ``register_filesystem`` overrides per scheme.

Real pod training cannot checkpoint to a worker's local disk — every
checkpoint path in bigdl_tpu flows through this module so a ``gs://``
destination works end-to-end.
"""
from __future__ import annotations

import io
import os
import threading
from typing import BinaryIO, Optional


def _split_scheme(path: str) -> tuple[str, str]:
    """('gs', 'bucket/dir/f') for 'gs://bucket/dir/f'; ('', path) for local.
    Windows drive letters ('C:/x') are not treated as schemes."""
    idx = path.find("://")
    if idx <= 1:  # no scheme, or single-letter drive
        return "", path
    return path[:idx], path[idx + 3:]


class FileSystem:
    """Minimal interface the framework needs: streams + a few queries."""

    def open(self, path: str, mode: str = "rb") -> BinaryIO:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Replace dst with src (atomic where the backend supports it)."""
        raise NotImplementedError

    def listdir(self, path: str) -> list:
        """Entry names directly under ``path`` (no scheme, no parents)."""
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, path: str, mode: str = "rb") -> BinaryIO:
        if "w" in mode or "a" in mode:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
        return open(path, mode)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        os.remove(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def listdir(self, path: str) -> list:
        return os.listdir(path)


class MemoryFileSystem(FileSystem):
    """In-process blob store keyed by full path — the mocked remote
    filesystem used by tests (and handy as a scratch store)."""

    def __init__(self):
        self._blobs: dict[str, bytes] = {}
        self._lock = threading.Lock()

    class _Writer(io.BytesIO):
        def __init__(self, fs: "MemoryFileSystem", path: str):
            super().__init__()
            self._fs = fs
            self._path = path

        def close(self):
            with self._fs._lock:
                self._fs._blobs[self._path] = self.getvalue()
            super().close()

    def open(self, path: str, mode: str = "rb") -> BinaryIO:
        if "w" in mode:
            return MemoryFileSystem._Writer(self, path)
        with self._lock:
            if path not in self._blobs:
                raise FileNotFoundError(f"memory://{path}")
            return io.BytesIO(self._blobs[path])

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._blobs

    def makedirs(self, path: str) -> None:
        pass  # flat keyspace, like object stores

    def remove(self, path: str) -> None:
        with self._lock:
            del self._blobs[path]

    def rename(self, src: str, dst: str) -> None:
        with self._lock:
            self._blobs[dst] = self._blobs.pop(src)

    def listdir(self, path: str) -> list:
        prefix = path.rstrip("/") + "/"
        with self._lock:
            return sorted({k[len(prefix):].split("/")[0]
                           for k in self._blobs if k.startswith(prefix)})


class FsspecFileSystem(FileSystem):
    """Adapter for any fsspec-supported scheme (gs, s3, hdfs, ...)."""

    def __init__(self, scheme: str):
        import fsspec

        self._scheme = scheme
        self._fs = fsspec.filesystem(scheme)

    def open(self, path: str, mode: str = "rb") -> BinaryIO:
        return self._fs.open(f"{self._scheme}://{path}", mode)

    def exists(self, path: str) -> bool:
        return self._fs.exists(f"{self._scheme}://{path}")

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(f"{self._scheme}://{path}", exist_ok=True)

    def remove(self, path: str) -> None:
        self._fs.rm(f"{self._scheme}://{path}")

    def rename(self, src: str, dst: str) -> None:
        self._fs.mv(f"{self._scheme}://{src}", f"{self._scheme}://{dst}")

    def listdir(self, path: str) -> list:
        entries = self._fs.ls(f"{self._scheme}://{path}", detail=False)
        return sorted({e.rstrip("/").rsplit("/", 1)[-1] for e in entries})


_local = LocalFileSystem()
_registry: dict[str, FileSystem] = {
    "": _local,
    "file": _local,
    "memory": MemoryFileSystem(),
}


def register_filesystem(scheme: str, fs: FileSystem) -> None:
    """Install (or override) the filesystem serving ``scheme://`` paths."""
    _registry[scheme] = fs


def get_filesystem(path: str) -> tuple[FileSystem, str]:
    """Resolve a path to (filesystem, scheme-stripped path); adapters that
    need the scheme (fsspec) re-attach it themselves."""
    scheme, rest = _split_scheme(path)
    if scheme in _registry:
        return _registry[scheme], rest
    try:
        fs = FsspecFileSystem(scheme)
    except Exception as e:  # fsspec missing or scheme unknown
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(register one with bigdl_tpu.utils.fs.register_filesystem)") from e
    _registry[scheme] = fs
    return fs, rest


def open_file(path: str, mode: str = "rb") -> BinaryIO:
    fs, p = get_filesystem(path)
    return fs.open(p, mode)


def exists(path: str) -> bool:
    fs, p = get_filesystem(path)
    return fs.exists(p)


def makedirs(path: str) -> None:
    fs, p = get_filesystem(path)
    fs.makedirs(p)


def remove(path: str) -> None:
    fs, p = get_filesystem(path)
    fs.remove(p)


def listdir(path: str) -> list:
    fs, p = get_filesystem(path)
    return fs.listdir(p)


def join(base: str, *parts: str) -> str:
    """Path join that preserves URI schemes ('gs://b/dir' + 'f')."""
    scheme, rest = _split_scheme(base)
    joined = "/".join([rest.rstrip("/")] + [p.strip("/") for p in parts])
    return f"{scheme}://{joined}" if scheme else os.path.join(base, *parts)


# object stores where a single put is already atomic per key — a tmp +
# rename there costs an extra copy for no safety
_ATOMIC_PUT_SCHEMES = {"gs", "gcs", "s3", "s3a", "az", "abfs"}


def atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename by default (a killed writer must never leave a
    truncated file at the final path — e.g. hdfs:// writes are not
    atomic); plain write only on object stores with atomic puts."""
    fs, p = get_filesystem(path)
    if isinstance(fs, FsspecFileSystem) and fs._scheme in _ATOMIC_PUT_SCHEMES:
        with fs.open(p, "wb") as f:
            f.write(data)
        return
    tmp = p + ".tmp"
    with fs.open(tmp, "wb") as f:
        f.write(data)
    fs.rename(tmp, p)
