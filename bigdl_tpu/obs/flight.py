"""Incident flight recorder: one correlated bundle per incident.

When something goes wrong in the serving stack — a watchdog stall, a
classified backend-lost, a fault-injector fire, a shed burst, the
memory ledger crossing its OOM watermark (``mem_pressure``) — the
evidence today is scattered: a log line here, a counter there, a trace
ring that will be overwritten in minutes.  The flight recorder freezes
all of it at the moment of the incident into one atomically-written
``FLIGHT_<ts>.json`` bundle:

- the last N trace spans (the request timeline leading into the
  incident) and the active request ids;
- the time-series window from the process sampler (the time axis
  around the incident), when one is installed;
- ``Engine.diagnose_tpu()`` — the port-level tunnel state, safe to
  read while wedged;
- registered state providers (BlockPool/placement/spec stats,
  ReplicaSet circuit states, …) — engines register themselves at
  init, latest owner wins, and a provider that raises contributes its
  error string instead of killing the dump;
- a pointer row appended into ``TUNNEL_INCIDENTS.json`` through
  ``traffic.incidents`` so the incident ledger and the bundle
  cross-reference each other.

Recording is OFF by default (``BIGDL_TPU_FLIGHT=1`` or
``configure(enabled=True)`` arms it); bundles land under ``flight/``
(``BIGDL_TPU_FLIGHT_DIR`` moves them) and rotate at dump time — the
oldest past ``BIGDL_TPU_FLIGHT_MAX`` (default 64) are pruned, so an
incident-heavy soak can never grow the directory without bound.
"Exactly one bundle per distinct incident": bundles dedup on
``(kind, key)`` within ``dedup_window_s`` — a shed burst or a
fault-matrix sweep collapses to its first bundle per site instead of a
bundle per occurrence.

CLI (what ``chip_opportunist.sh`` calls on a probe/stage death)::

    python -m bigdl_tpu.obs.flight dump <stage> <rc> [--dir DIR]

dumps a bundle from fresh process state AND appends the incident row
with its ``flight`` pointer, replacing the bare
``traffic.incidents append`` call.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from bigdl_tpu.obs.registry import get_registry
from bigdl_tpu.obs.tracer import get_tracer
from bigdl_tpu.obs.timeseries import get_sampler

log = logging.getLogger("bigdl_tpu.obs.flight")

__all__ = ["FlightRecorder", "get_flight_recorder", "configure",
           "register_state", "register_requests", "note_shed"]


def _env_enabled() -> bool:
    return os.environ.get("BIGDL_TPU_FLIGHT", "0").lower() \
        in ("1", "true", "on")


class FlightRecorder:
    """Correlated incident-bundle dumper with per-incident dedup."""

    #: incident kinds the serving stack wires up (detail carries the
    #: specifics); ad-hoc kinds are allowed — the schema only pins shape
    KINDS = ("stall", "backend_lost", "fault_injected", "shed_burst",
             "probe_death", "stage_death", "mem_pressure")

    def __init__(self, *, enabled: Optional[bool] = None,
                 out_dir: Optional[str] = None,
                 incidents_path: Optional[str] = None,
                 max_spans: int = 512,
                 dedup_window_s: float = 30.0,
                 shed_burst_threshold: int = 32,
                 shed_burst_window_s: float = 5.0,
                 max_bundles: Optional[int] = None):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        # new bundles land under flight/ (not the repo root — dozens of
        # stale FLIGHT_*.json at top level was the round-16 mess);
        # incident-ledger pointers carry the subdir
        self.out_dir = (out_dir
                        or os.environ.get("BIGDL_TPU_FLIGHT_DIR")
                        or os.path.join(os.getcwd(), "flight"))
        if max_bundles is None:
            try:
                max_bundles = int(os.environ.get(
                    "BIGDL_TPU_FLIGHT_MAX", "64"))
            except ValueError:
                max_bundles = 64
        #: rotation bound: at dump time the oldest FLIGHT_*.json past
        #: this count are pruned from out_dir (<= 0 disables)
        self.max_bundles = int(max_bundles)
        #: None -> traffic.incidents.DEFAULT_PATH, resolved at dump time
        self.incidents_path = incidents_path
        self.max_spans = int(max_spans)
        self.dedup_window_s = float(dedup_window_s)
        self.shed_burst_threshold = int(shed_burst_threshold)
        self.shed_burst_window_s = float(shed_burst_window_s)
        self._lock = threading.Lock()
        self._last_by_key: Dict[tuple, float] = {}
        self._state_providers: Dict[str, Callable[[], object]] = {}
        self._request_providers: Dict[str, Callable[[], list]] = {}
        self._shed_times: deque = deque(maxlen=4096)
        self._seq = 0
        self.bundles_written = 0
        self.last_bundle_path: Optional[str] = None

    # -- provider registration ------------------------------------------ #
    def register_state(self, key: str,
                       fn: Callable[[], object]) -> None:
        """Bind a state snapshot callable (BlockPool stats, placement,
        spec, circuit states...) under ``key``; latest owner wins, the
        FnGauge idiom."""
        with self._lock:
            self._state_providers[key] = fn

    def register_requests(self, key: str,
                          fn: Callable[[], list]) -> None:
        """Bind an active-request-id provider (engine slots + queue)."""
        with self._lock:
            self._request_providers[key] = fn

    def unregister(self, key: str) -> None:
        with self._lock:
            self._state_providers.pop(key, None)
            self._request_providers.pop(key, None)

    # -- triggers ------------------------------------------------------- #
    def note_shed(self) -> Optional[str]:
        """Called per shed (queue-full rejection); records ONE bundle
        when sheds exceed the burst threshold within the window, then
        the dedup window re-arms it."""
        if not self.enabled:
            return None
        now = time.time()
        with self._lock:
            self._shed_times.append(now)
            cutoff = now - self.shed_burst_window_s
            recent = sum(1 for t in self._shed_times if t >= cutoff)
        if recent < self.shed_burst_threshold:
            return None
        return self.record("shed_burst",
                           {"sheds_in_window": recent,
                            "window_s": self.shed_burst_window_s},
                           key="shed")

    def record(self, kind: str, detail: Optional[dict] = None, *,
               key: Optional[str] = None) -> Optional[str]:
        """Dump one bundle for this incident; returns its path, or
        ``None`` when disabled or deduplicated.  ``key`` scopes the
        dedup — two different fault sites are distinct incidents, two
        fires of the same site inside ``dedup_window_s`` are one."""
        if not self.enabled:
            return None
        now = time.time()
        dkey = (kind, key)
        with self._lock:
            last = self._last_by_key.get(dkey)
            if last is not None and now - last < self.dedup_window_s:
                return None
            self._last_by_key[dkey] = now
            self._seq += 1
            seq = self._seq
        try:
            return self._dump(kind, detail or {}, now, seq)
        except Exception:
            log.exception("flight recorder failed dumping %r", kind)
            return None

    # -- bundle assembly ------------------------------------------------ #
    def _dump(self, kind: str, detail: dict, now: float, seq: int) -> str:
        tracer = get_tracer()
        spans = tracer.events()[-self.max_spans:]
        sampler = get_sampler()
        window = sampler.window() if sampler is not None else []
        with self._lock:
            state_providers = dict(self._state_providers)
            request_providers = dict(self._request_providers)
        state = {}
        for pkey, fn in state_providers.items():
            try:
                state[pkey] = fn()
            except Exception as e:
                state[pkey] = f"capture failed: {e}"
        active: dict = {}
        for pkey, fn in request_providers.items():
            try:
                active[pkey] = list(fn())
            except Exception as e:
                active[pkey] = [f"capture failed: {e}"]
        try:
            from bigdl_tpu.utils.engine import Engine
            diagnose = Engine.diagnose_tpu()
        except Exception as e:  # pragma: no cover - diagnose is /proc-only
            diagnose = f"capture failed: {e}"
        bundle = {
            "flight": kind,
            "ts_unix": round(now, 3),
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now)),
            "detail": detail,
            "spans": spans,
            "active_requests": active,
            "timeseries": window,
            "state": state,
            "registry": get_registry().snapshot(),
            "diagnose_tpu": diagnose,
            "complete": True,
        }
        stamp = time.strftime("%Y%m%d_%H%M%S", time.localtime(now))
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir,
                            f"FLIGHT_{stamp}_{os.getpid()}_{seq}.json")
        from bigdl_tpu.utils.artifacts import write_artifact
        write_artifact(path, bundle)
        with self._lock:
            self.bundles_written += 1
            self.last_bundle_path = path
        self._rotate()
        self._append_incident_pointer(kind, detail, path)
        log.warning("flight recorder: %s -> %s", kind, path)
        return path

    def _rotate(self) -> None:
        """Prune the oldest bundles past ``max_bundles``
        (``BIGDL_TPU_FLIGHT_MAX``) — the stamp-named files sort
        chronologically, so name order IS age order."""
        if self.max_bundles <= 0:
            return
        try:
            names = sorted(n for n in os.listdir(self.out_dir)
                           if n.startswith("FLIGHT_")
                           and n.endswith(".json"))
            for name in names[:-self.max_bundles]:
                os.remove(os.path.join(self.out_dir, name))
        except OSError:
            log.exception("flight bundle rotation failed in %s",
                          self.out_dir)

    def _append_incident_pointer(self, kind: str, detail: dict,
                                 path: str) -> None:
        try:
            from bigdl_tpu.traffic import incidents
            # a CLI dump carries the opportunist's stage/rc verbatim so
            # the ledger row looks exactly like the old bare append
            # (plus the pointer); in-process triggers self-name
            stage = f"flight/{kind}"
            rc = 0
            if isinstance(detail, dict):
                stage = str(detail.get("stage", stage))
                try:
                    rc = int(detail.get("rc", 0))
                except (TypeError, ValueError):
                    rc = 0
            try:
                # pointer keeps the flight/ prefix so the ledger row
                # resolves from the repo root
                pointer = os.path.relpath(path, os.getcwd())
                if pointer.startswith(".."):
                    pointer = path
            except ValueError:
                pointer = os.path.basename(path)
            incidents.append_incident(
                stage=stage, rc=rc,
                path=self.incidents_path or incidents.DEFAULT_PATH,
                flight=pointer)
        except Exception:
            log.exception("flight recorder: incident pointer append "
                          "failed for %s", path)


#: process-wide recorder — triggers all over the stack (watchdog,
#: replicaset, fault injector, batcher sheds) report into this one
_GLOBAL = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _GLOBAL


def configure(**kw) -> FlightRecorder:
    """Rebind the process-wide recorder (``configure(enabled=True,
    out_dir=...)``); providers registered on the old one carry over."""
    global _GLOBAL
    old = _GLOBAL
    rec = FlightRecorder(**kw)
    with old._lock:
        rec._state_providers.update(old._state_providers)
        rec._request_providers.update(old._request_providers)
    _GLOBAL = rec
    return rec


# module-level conveniences for the hot-path call sites
def register_state(key: str, fn: Callable[[], object]) -> None:
    _GLOBAL.register_state(key, fn)


def register_requests(key: str, fn: Callable[[], list]) -> None:
    _GLOBAL.register_requests(key, fn)


def note_shed() -> Optional[str]:
    return _GLOBAL.note_shed()


def _main(argv) -> int:
    """``python -m bigdl_tpu.obs.flight dump <stage> <rc> [--dir D]``"""
    if len(argv) < 3 or argv[0] != "dump":
        print(__doc__.strip().splitlines()[0], file=sys.stderr)
        print("usage: python -m bigdl_tpu.obs.flight dump <stage> <rc> "
              "[--dir DIR]", file=sys.stderr)
        return 2
    stage, rc = argv[1], int(argv[2])
    out_dir = None
    if "--dir" in argv:
        out_dir = argv[argv.index("--dir") + 1]
    kind = "probe_death" if stage == "probe" else "stage_death"
    rec = FlightRecorder(enabled=True, out_dir=out_dir,
                         dedup_window_s=0.0)
    path = rec.record(kind, {"stage": stage, "rc": rc})
    if path is None:
        return 1
    print(json.dumps({"flight": kind, "stage": stage, "rc": rc,
                      "path": path}))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised by the shell
    sys.exit(_main(sys.argv[1:]))
