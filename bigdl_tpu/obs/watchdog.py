"""StallWatchdog: turn a silently hung step into a diagnosed event.

The repo's recurring operational failure is the tunneled TPU backend
wedging mid-step: the process looks merely "slow" (ESTABLISHED TCP to
the relay, blocked in tcp_recvmsg, ~1s CPU — NOTES_r4.md) while a
measurement window burns.  The watchdog watches the *step cadence*: the
instrumented loop brackets each step (``with wd.step(): ...``), a
daemon thread tracks the rolling median of completed durations, and a
step exceeding ``k`` x median (or an absolute ``deadline_s``) fires ONE
diagnostics capture:

- ``Engine.diagnose_tpu()`` — the /proc + relay-port scan that names a
  stale chip holder or a dead tunnel without touching the jax backend
  (safe while wedged);
- all-thread stack dumps (``sys._current_frames``) — where the step is
  actually blocked;
- an instant event into the trace spine plus a structured log record.

Firing is once per stall: the flag re-arms when the step completes, so
a genuinely slow-but-alive loop logs one event per incident, not one
per poll.  Env knobs (read by the instrumented call sites):
``BIGDL_TPU_WATCHDOG`` (default on; ``0`` disables),
``BIGDL_TPU_WATCHDOG_K`` (median multiplier, default 10),
``BIGDL_TPU_WATCHDOG_DEADLINE_S`` (absolute ceiling, default none).
"""
from __future__ import annotations

import logging
import os
import statistics
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Optional

from bigdl_tpu.obs.tracer import get_tracer

log = logging.getLogger("bigdl_tpu.obs")


def env_watchdog_enabled() -> bool:
    return os.environ.get("BIGDL_TPU_WATCHDOG", "1").lower() \
        not in ("0", "false", "off")


def env_watchdog_kwargs() -> dict:
    """k/deadline knobs from the environment (shared by every
    instrumented loop so the knobs are spelled once)."""
    kw = {}
    try:
        kw["k"] = float(os.environ.get("BIGDL_TPU_WATCHDOG_K", "10"))
    except ValueError:
        pass
    dl = os.environ.get("BIGDL_TPU_WATCHDOG_DEADLINE_S")
    if dl:
        try:
            kw["deadline_s"] = float(dl)
        except ValueError:
            pass
    return kw


def thread_stacks(limit_per_thread: int = 40) -> dict:
    """{thread name: formatted stack} for every live thread — where a
    wedged process is actually blocked."""
    names = {t.ident: t.name for t in threading.enumerate()
             if t.ident is not None}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = names.get(ident, f"thread-{ident}")
        stacks[label] = "".join(
            traceback.format_stack(frame, limit=limit_per_thread))
    return stacks


class _StepCtx:
    __slots__ = ("_wd",)

    def __init__(self, wd: "StallWatchdog"):
        self._wd = wd

    def __enter__(self):
        self._wd.step_started()
        return self

    def __exit__(self, *exc):
        self._wd.step_finished()
        return False


class StallWatchdog:
    """Rolling-median stall detector for a step/dispatch loop.

    Args:
        name: label for trace events and logs ("train_step", "serve").
        k: fire when the in-flight step exceeds ``k`` x rolling median.
        deadline_s: absolute in-flight ceiling (fires regardless of the
            median; the only trigger before ``min_samples`` completed
            steps exist, so a first-step compile cannot false-fire the
            median rule).
        window: completed-duration history length for the median.
        min_samples: completed steps required before the median rule
            arms (the first steps of a run include compiles).
        poll_s: watcher thread check interval.
        on_stall: optional callback receiving the diagnostics event
            dict (after it is logged and traced).
        capture: extra named capture callables; each result lands under
            its key in the event (defaults to ``Engine.diagnose_tpu``).
    """

    def __init__(self, name: str = "step", *, k: float = 10.0,
                 deadline_s: Optional[float] = None, window: int = 64,
                 min_samples: int = 5, poll_s: float = 0.5,
                 tracer=None, on_stall: Optional[Callable] = None,
                 capture: Optional[dict] = None):
        self.name = name
        self.k = float(k)
        self.deadline_s = deadline_s
        self.min_samples = int(min_samples)
        self.poll_s = float(poll_s)
        self.on_stall = on_stall
        self._capture = capture
        self._tracer = tracer if tracer is not None else get_tracer()
        self._durations: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()
        self._inflight_since: Optional[float] = None
        self._fired_inflight = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0
        self.last_event: Optional[dict] = None

    # -- step bracketing ------------------------------------------------ #
    def step(self) -> _StepCtx:
        return _StepCtx(self)

    def reset(self, **overrides) -> "StallWatchdog":
        """Re-arm for a new loop: drop the duration history (a new model
        has a new step time) and apply fresh ``k``/``deadline_s``
        overrides.  How a shared process-wide watchdog is handed from
        one training run to the next."""
        with self._lock:
            self._durations.clear()
            self._inflight_since = None
            self._fired_inflight = False
        if "k" in overrides:
            self.k = float(overrides["k"])
        if "deadline_s" in overrides:
            self.deadline_s = overrides["deadline_s"]
        return self

    def step_started(self) -> None:
        with self._lock:
            self._inflight_since = time.perf_counter()
            self._fired_inflight = False
        self._ensure_thread()

    def step_finished(self) -> None:
        with self._lock:
            if self._inflight_since is not None:
                self._durations.append(
                    time.perf_counter() - self._inflight_since)
            self._inflight_since = None
            self._fired_inflight = False

    def median(self) -> Optional[float]:
        with self._lock:
            if not self._durations:
                return None
            return statistics.median(self._durations)

    # -- detection ------------------------------------------------------ #
    def _threshold(self) -> Optional[float]:
        """Current fire threshold in seconds, None when unarmed."""
        with self._lock:
            n = len(self._durations)
            med = statistics.median(self._durations) if n else None
        bounds = []
        if med is not None and n >= self.min_samples:
            bounds.append(self.k * med)
        if self.deadline_s is not None:
            bounds.append(self.deadline_s)
        return min(bounds) if bounds else None

    def check_now(self) -> Optional[dict]:
        """Synchronous probe (what the watcher thread runs each poll):
        fires and returns the diagnostics event when the in-flight step
        is past threshold, else None."""
        with self._lock:
            since = self._inflight_since
            fired = self._fired_inflight
        if since is None or fired:
            return None
        inflight = time.perf_counter() - since
        threshold = self._threshold()
        if threshold is None or inflight < threshold:
            return None
        with self._lock:
            if self._fired_inflight:  # lost the race to another poller
                return None
            self._fired_inflight = True
        return self._fire(inflight, threshold)

    def _fire(self, inflight_s: float, threshold_s: float) -> dict:
        event = {
            "kind": "stall", "watchdog": self.name,
            "inflight_s": round(inflight_s, 3),
            "threshold_s": round(threshold_s, 3),
            "median_s": self.median(),
            "steps_observed": len(self._durations),
        }
        captures = self._capture
        if captures is None:
            captures = {"diagnose_tpu": _default_diagnose}
        for key, fn in captures.items():
            try:
                event[key] = fn()
            except Exception as e:  # diagnostics must never kill the loop
                event[key] = f"capture failed: {e}"
        event["thread_stacks"] = thread_stacks()
        self.stall_count += 1
        self.last_event = event
        log.error(
            "watchdog %s: step in flight %.1fs exceeds threshold %.1fs "
            "(median %s); diagnose_tpu: %s", self.name, inflight_s,
            threshold_s, event["median_s"], event.get("diagnose_tpu"))
        tr = self._tracer
        # instant event regardless of prior state: a stall is exactly
        # when a trace must exist, so firing force-enables the buffer
        # for this event if tracing was off
        was = tr.enabled
        tr.enabled = True
        try:
            tr.instant(f"stall:{self.name}", cat="watchdog", **{
                k: v for k, v in event.items() if k != "thread_stacks"})
        finally:
            tr.enabled = was
        if self.on_stall is not None:
            try:
                self.on_stall(event)
            except Exception:
                log.exception("watchdog on_stall callback failed")
        # a stall is a first-class incident: dump the correlated bundle
        # (last spans + time-series window + the diagnostics captured
        # above) if the process flight recorder is armed
        try:
            from bigdl_tpu.obs import flight
            flight.get_flight_recorder().record(
                "stall",
                {k: v for k, v in event.items() if k != "thread_stacks"},
                key=self.name)
        except Exception:
            log.exception("watchdog flight-recorder dump failed")
        return event

    # -- watcher thread ------------------------------------------------- #
    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name=f"bigdl-tpu-watchdog-{self.name}")
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_now()
            except Exception:  # never let the watcher die silently
                log.exception("watchdog poll failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.poll_s + 1.0)
        self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def _default_diagnose() -> str:
    from bigdl_tpu.utils.engine import Engine
    return Engine.diagnose_tpu()


_SHARED: dict = {}
_shared_lock = threading.Lock()


def shared_watchdog(name: str) -> StallWatchdog:
    """Process-wide watchdog per loop name, created on first use with
    the env knobs.  Long-lived on purpose: the poll thread is one
    daemon per loop kind, and successive training runs re-arm it with
    ``reset()`` instead of spawning/joining threads per run."""
    with _shared_lock:
        wd = _SHARED.get(name)
        if wd is None:
            wd = StallWatchdog(name, **env_watchdog_kwargs())
            _SHARED[name] = wd
        return wd
