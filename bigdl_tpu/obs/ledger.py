"""MemoryLedger: process-wide HBM byte attribution + executable costs.

The serving stack consumes device memory from half a dozen subsystems
— staged param shards, paged KV arenas (plus int8 scale arenas), the
spec drafter's dense arena, compile-cache executables, kvtier
promotion traffic — and until now the only capacity signal was an
ad-hoc ``kvcache_headroom()`` check in one bench hook.  The reference
BigDL never had this problem: Spark's UnifiedMemoryManager accounts
every cached block and shuffle buffer under one evictable ledger
(arXiv 1804.05839).  This module is that ledger rebuilt for HBM:

- every long-lived device allocation registers ``(subsystem, name,
  nbytes, shape/dtype)`` — as a static byte count, a computed
  provider (the FnGauge idiom), or a live array held by weakref so
  the ledger never pins what it accounts;
- :class:`~bigdl_tpu.serving.compile_cache.CompileCache` (and the
  engines' directly-lowered decode/verify/insert programs) record
  each executable's ``memory_analysis()`` (temp/argument/output/code
  bytes) and ``cost_analysis()`` (flops, bytes accessed) at AOT-lower
  time — a per-executable roofline estimate
  (``flops / bytes_accessed``) captured for free, the TensorFlow
  per-op cost-model surface (arXiv 1605.08695) at executable
  granularity;
- totals reconcile against ``device.memory_stats()['bytes_in_use']``
  where the backend supports it (TPU/GPU; CPU returns ``None`` and
  the verdict degrades gracefully), exposing ``drift_bytes`` — the
  bytes the ledger cannot attribute;
- ``headroom(device)`` is the one capacity API: fraction of the
  device byte budget still free.  Budget resolution order: an
  explicit ``budget_bytes`` (tests), the backend's ``bytes_limit``,
  then ``BIGDL_TPU_MEM_BUDGET``.  Unknown budget -> ``None``
  (permissive: callers must not invent pressure they cannot see);
- crossing the low-headroom watermark (``BIGDL_TPU_MEM_WATERMARK``,
  default 0.9 used fraction) fires ONE ``mem_pressure`` flight bundle
  carrying the full attribution table — predictive OOM forensics
  dumped *before* RESOURCE_EXHAUSTED kills the process, when the
  post-mortem can no longer run.

Gauges land in the metric registry under ``obs/ledger/*`` (totals,
per-subsystem bytes, drift, headroom) and ``obs/xcost/*`` (executable
count, flops/bytes-accessed/code/temp totals); the full per-entry and
per-executable tables ride flight bundles (state provider
``memledger``) and ``bench.py --memprofile``'s ``PROFILE_MEM.json``.

The process-wide instance (:func:`get_ledger`) is what the engines
register into; :func:`set_ledger` swaps it (test injection — a fake
ledger is how the SLO scale-up refusal is unit-tested without filling
real memory).
"""
from __future__ import annotations

import logging
import os
import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from bigdl_tpu.obs.registry import FnGauge, MetricRegistry, get_registry

log = logging.getLogger("bigdl_tpu.obs.ledger")

__all__ = ["MemoryLedger", "get_ledger", "set_ledger", "env_watermark"]

#: used-fraction threshold past which the ledger reports pressure
DEFAULT_WATERMARK = 0.9


def env_watermark() -> float:
    try:
        v = float(os.environ.get("BIGDL_TPU_MEM_WATERMARK",
                                 DEFAULT_WATERMARK))
    except ValueError:
        return DEFAULT_WATERMARK
    return v if 0.0 < v <= 1.0 else DEFAULT_WATERMARK


def _env_budget() -> Optional[int]:
    v = os.environ.get("BIGDL_TPU_MEM_BUDGET")
    if not v:
        return None
    try:
        return int(float(v))
    except ValueError:
        return None


class _Entry:
    """One registered allocation; ``provider`` is a weakref to a live
    array, a callable returning bytes, or a static int."""

    __slots__ = ("subsystem", "name", "provider", "shape", "dtype",
                 "device", "note")

    def __init__(self, subsystem: str, name: str, provider,
                 shape, dtype, device, note):
        self.subsystem = subsystem
        self.name = name
        self.provider = provider
        self.shape = shape
        self.dtype = dtype
        self.device = device
        self.note = note


class MemoryLedger:
    """Byte-attribution plane + executable cost observatory.

    Args:
        registry: metric registry to publish ``obs/ledger/*`` /
            ``obs/xcost/*`` gauges into (default: the process-wide
            one).  All gauges register with ``replace=True`` — the
            latest ledger owns the names.
        watermark: used-fraction pressure threshold (default
            ``BIGDL_TPU_MEM_WATERMARK`` or 0.9).
        budget_bytes: explicit device byte budget, overriding the
            backend's ``bytes_limit`` and ``BIGDL_TPU_MEM_BUDGET``
            (tests inject tiny budgets this way).
    """

    def __init__(self, *, registry: Optional[MetricRegistry] = None,
                 watermark: Optional[float] = None,
                 budget_bytes: Optional[int] = None):
        self.watermark = (env_watermark() if watermark is None
                          else float(watermark))
        self.budget_bytes = (int(budget_bytes)
                             if budget_bytes is not None else None)
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], _Entry] = {}
        self._xcost: Dict[Tuple[str, str], dict] = {}
        self._last_reconcile: Optional[dict] = None
        self._registry = registry if registry is not None else get_registry()
        self._published: set = set()
        self._publish_base()
        self._register_flight_provider()

    # -- registration --------------------------------------------------- #
    def register(self, subsystem: str, name: str, provider, *,
                 shape=None, dtype=None, device: Optional[str] = None,
                 note: str = "") -> Tuple[str, str]:
        """Attribute one long-lived allocation to ``(subsystem, name)``
        (re-registering replaces — the latest owner wins, like the
        registry's ``replace=True``).  ``provider`` is a static byte
        count, a zero-arg callable returning bytes (``None`` -> stale),
        or a live array (``nbytes``/``shape``/``dtype`` captured, the
        array held by weakref so the ledger never extends its life).
        Returns the entry key for :meth:`release`."""
        if hasattr(provider, "nbytes") and not callable(provider):
            if shape is None:
                shape = tuple(getattr(provider, "shape", ()) or ())
            if dtype is None:
                dtype = str(getattr(provider, "dtype", "") or "")
            try:
                provider = weakref.ref(provider)
            except TypeError:
                # not weakref-able (slots-only wrappers): fall back to
                # a static count — safer than pinning the buffer alive
                provider = int(provider.nbytes)
        entry = _Entry(str(subsystem), str(name), provider,
                       tuple(shape) if shape is not None else None,
                       str(dtype) if dtype is not None else None,
                       device, note)
        key = (entry.subsystem, entry.name)
        with self._lock:
            self._entries[key] = entry
        self._publish_subsystem(entry.subsystem)
        return key

    def release(self, subsystem: str, name: str) -> bool:
        """Drop one attribution; True if it existed."""
        with self._lock:
            return self._entries.pop((str(subsystem), str(name)),
                                     None) is not None

    @staticmethod
    def _resolve(entry: _Entry) -> Optional[int]:
        p = entry.provider
        try:
            if isinstance(p, weakref.ref):
                obj = p()
                return None if obj is None else int(obj.nbytes)
            if callable(p):
                v = p()
                return None if v is None else int(v)
            return int(p)
        except Exception:
            return None

    # -- executable cost rows ------------------------------------------- #
    @staticmethod
    def analyze_compiled(compiled) -> Tuple[Optional[dict],
                                            Optional[dict]]:
        """Extract ``(memory, cost)`` dicts from a jax ``Compiled``;
        either half degrades to ``None`` when the backend does not
        report it.  ``cost_analysis()`` returns a list of dicts on
        this jaxlib — both shapes are handled."""
        memory = None
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                memory = {
                    "temp_bytes": int(
                        getattr(ma, "temp_size_in_bytes", 0) or 0),
                    "argument_bytes": int(
                        getattr(ma, "argument_size_in_bytes", 0) or 0),
                    "output_bytes": int(
                        getattr(ma, "output_size_in_bytes", 0) or 0),
                    "alias_bytes": int(
                        getattr(ma, "alias_size_in_bytes", 0) or 0),
                    "code_bytes": int(
                        getattr(ma, "generated_code_size_in_bytes", 0)
                        or 0),
                }
        except Exception:
            memory = None
        cost = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                flops = float(ca.get("flops", 0.0) or 0.0)
                touched = float(ca.get("bytes accessed", 0.0) or 0.0)
                cost = {"flops": flops, "bytes_accessed": touched,
                        "flops_per_byte": (flops / touched
                                           if touched > 0 else None)}
        except Exception:
            cost = None
        return memory, cost

    def record_compiled(self, tag: str, key: str, compiled) -> dict:
        """Analyze one freshly-compiled executable and file its row
        under ``(tag, key)`` — the one-call hook every AOT-lower site
        uses."""
        memory, cost = self.analyze_compiled(compiled)
        return self.record_executable(tag, key, memory=memory, cost=cost)

    def record_executable(self, tag: str, key: str, *,
                          memory: Optional[dict] = None,
                          cost: Optional[dict] = None) -> dict:
        row = {"tag": str(tag), "key": str(key),
               "memory": memory, "cost": cost}
        with self._lock:
            self._xcost[(row["tag"], row["key"])] = row
        return row

    def release_executable(self, tag: str, key: str) -> bool:
        with self._lock:
            return self._xcost.pop((str(tag), str(key)), None) is not None

    def executables(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._xcost.values()]

    def _xcost_totals(self) -> dict:
        with self._lock:
            rows = list(self._xcost.values())
        tot = {"executables": len(rows), "flops": 0.0,
               "bytes_accessed": 0.0, "code_bytes": 0,
               "temp_bytes": 0, "output_bytes": 0}
        for r in rows:
            c, m = r.get("cost"), r.get("memory")
            if c:
                tot["flops"] += c.get("flops") or 0.0
                tot["bytes_accessed"] += c.get("bytes_accessed") or 0.0
            if m:
                tot["code_bytes"] += m.get("code_bytes") or 0
                tot["temp_bytes"] += m.get("temp_bytes") or 0
                tot["output_bytes"] += m.get("output_bytes") or 0
        return tot

    # -- attribution ----------------------------------------------------- #
    def entries(self) -> List[dict]:
        """The attribution table: one row per registration, stale
        providers (dead weakrefs, raising callables) reported at 0
        bytes with ``stale: true`` instead of silently vanishing."""
        with self._lock:
            items = list(self._entries.values())
        rows = []
        for e in items:
            n = self._resolve(e)
            row = {"subsystem": e.subsystem, "name": e.name,
                   "nbytes": int(n) if n is not None else 0,
                   "stale": n is None}
            if e.shape is not None:
                row["shape"] = list(e.shape)
            if e.dtype:
                row["dtype"] = e.dtype
            if e.device is not None:
                row["device"] = e.device
            if e.note:
                row["note"] = e.note
            rows.append(row)
        rows.sort(key=lambda r: (r["subsystem"], r["name"]))
        return rows

    def attribution(self) -> Dict[str, int]:
        """Bytes per subsystem; executables contribute their resident
        generated-code bytes as the synthetic ``executables``
        subsystem (temp/argument bytes are transient per call, not a
        standing claim)."""
        out: Dict[str, int] = {}
        for row in self.entries():
            out[row["subsystem"]] = (out.get(row["subsystem"], 0)
                                     + row["nbytes"])
        code = self._xcost_totals()["code_bytes"]
        if code:
            out["executables"] = out.get("executables", 0) + int(code)
        return out

    def total_bytes(self) -> int:
        return sum(self.attribution().values())

    # -- reconciliation / capacity --------------------------------------- #
    @staticmethod
    def backend_stats(device=None) -> Optional[dict]:
        """``device.memory_stats()`` (default device when none given);
        ``None`` where the backend does not support it — the CPU
        degrade path."""
        try:
            if device is None:
                import jax
                device = jax.devices()[0]
            stats = device.memory_stats()
        except Exception:
            return None
        return stats if isinstance(stats, dict) else None

    def reconcile(self, device=None) -> dict:
        """Ledger-vs-backend verdict.  ``reconciled``: the backend
        reports ``bytes_in_use`` and ``drift_bytes`` is the
        unattributed remainder.  ``degraded``: the backend cannot be
        read (CPU) — drift is pinned at 0 by definition (no observable
        to drift from), the verdict says so."""
        ledger = self.total_bytes()
        stats = self.backend_stats(device)
        in_use = stats.get("bytes_in_use") if stats else None
        if in_use is not None:
            out = {"ledger_bytes": ledger,
                   "backend_bytes_in_use": int(in_use),
                   "drift_bytes": int(in_use) - ledger,
                   "verdict": "reconciled"}
        else:
            out = {"ledger_bytes": ledger,
                   "backend_bytes_in_use": None,
                   "drift_bytes": 0,
                   "verdict": "degraded"}
        with self._lock:
            self._last_reconcile = out
        return out

    def drift_bytes(self, device=None) -> int:
        return self.reconcile(device)["drift_bytes"]

    def capacity_bytes(self, device=None) -> Optional[int]:
        if self.budget_bytes is not None:
            return self.budget_bytes
        stats = self.backend_stats(device)
        if stats:
            for key in ("bytes_limit", "bytes_reservable_limit"):
                if stats.get(key):
                    return int(stats[key])
        return _env_budget()

    def used_fraction(self, device=None) -> Optional[float]:
        """Used bytes over the byte budget; ``None`` when no budget is
        known (CPU with neither ``BIGDL_TPU_MEM_BUDGET`` nor an
        injected one) — callers treat unknown as permissive."""
        cap = self.capacity_bytes(device)
        if not cap or cap <= 0:
            return None
        stats = self.backend_stats(device)
        used = stats.get("bytes_in_use") if stats else None
        if used is None:
            used = self.total_bytes()
        return float(used) / float(cap)

    def headroom(self, device=None) -> Optional[float]:
        """Fraction of the device byte budget still free — THE
        capacity API (the SLO scale-up gate and admission deferral
        read this, replacing per-subsystem ad-hoc checks)."""
        uf = self.used_fraction(device)
        return None if uf is None else max(0.0, 1.0 - uf)

    def over_watermark(self, device=None) -> bool:
        uf = self.used_fraction(device)
        return uf is not None and uf >= self.watermark

    # -- pressure -> flight ---------------------------------------------- #
    def check_pressure(self, device=None, *,
                       context: Optional[dict] = None) -> Optional[str]:
        """Fire ONE ``mem_pressure`` flight bundle when usage crosses
        the watermark (the recorder's ``(kind, key)`` dedup collapses
        repeated checks of the same condition); returns the bundle
        path, or ``None`` when under the watermark, disabled, or
        deduplicated.  The detail carries the full attribution table —
        the forensics RESOURCE_EXHAUSTED would otherwise destroy."""
        uf = self.used_fraction(device)
        if uf is None or uf < self.watermark:
            return None
        detail = {
            "used_fraction": round(uf, 6),
            "watermark": self.watermark,
            "headroom": round(max(0.0, 1.0 - uf), 6),
            "capacity_bytes": self.capacity_bytes(device),
            "ledger_bytes": self.total_bytes(),
            "attribution": self.attribution(),
            "table": self.entries(),
        }
        if isinstance(context, str):
            # pressure checks must never crash a serving path over a
            # sloppy caller; fold a bare-string context into the detail
            context = {"context": context}
        if context:
            detail.update(context)
        try:
            from bigdl_tpu.obs import flight
            return flight.get_flight_recorder().record(
                "mem_pressure", detail, key="memledger")
        except Exception:
            log.exception("mem_pressure flight dump failed")
            return None

    # -- snapshots -------------------------------------------------------- #
    def summary(self) -> dict:
        """Backend-free totals (safe while the chip is wedged —
        ``diagnose_tpu`` embeds this): ledger bytes, subsystem count,
        and the LAST reconcile verdict rather than a fresh backend
        read."""
        attr = self.attribution()
        with self._lock:
            last = dict(self._last_reconcile) if self._last_reconcile \
                else None
        return {"ledger_bytes": sum(attr.values()),
                "subsystems": len(attr),
                "entries": len(self._entries),
                "executables": len(self._xcost),
                "watermark": self.watermark,
                "last_reconcile": last}

    def stats(self) -> dict:
        return {"attribution": self.attribution(),
                "total_bytes": self.total_bytes(),
                "xcost": self._xcost_totals(),
                "watermark": self.watermark,
                "headroom": self.headroom(),
                "reconcile": (self._last_reconcile
                              or {"verdict": "never_run"})}

    # -- gauge publication ------------------------------------------------ #
    def _publish_base(self) -> None:
        reg = self._registry
        try:
            reg.register("obs/ledger/total_bytes",
                         FnGauge(lambda: float(self.total_bytes())),
                         replace=True)
            reg.register("obs/ledger/drift_bytes",
                         FnGauge(lambda: float(self.drift_bytes())),
                         replace=True)
            reg.register("obs/ledger/headroom",
                         FnGauge(self.headroom), replace=True)
            reg.register("obs/ledger/watermark",
                         FnGauge(lambda: self.watermark), replace=True)
            for key in ("executables", "flops", "bytes_accessed",
                        "code_bytes", "temp_bytes"):
                reg.register(
                    f"obs/xcost/{key}",
                    FnGauge(lambda k=key: float(
                        self._xcost_totals()[k])),
                    replace=True)
        except Exception:
            log.exception("ledger gauge publication failed")

    def _publish_subsystem(self, subsystem: str) -> None:
        with self._lock:
            if subsystem in self._published:
                return
            self._published.add(subsystem)
        try:
            self._registry.register(
                f"obs/ledger/{subsystem}_bytes",
                FnGauge(lambda s=subsystem: float(
                    self.attribution().get(s, 0))),
                replace=True)
        except Exception:
            log.exception("ledger subsystem gauge failed: %s", subsystem)

    def _register_flight_provider(self) -> None:
        # every flight bundle (any kind) carries the attribution table
        # + executable rows; weakref'd so a replaced ledger is
        # collectable
        try:
            from bigdl_tpu.obs import flight
            ref = weakref.ref(self)

            def _state():
                led = ref()
                if led is None:
                    return None
                out = led.stats()
                out["table"] = led.entries()
                out["executable_rows"] = led.executables()
                return out

            flight.register_state("memledger", _state)
        except Exception:
            log.exception("ledger flight-state registration failed")


#: process-wide ledger, created lazily so env knobs are read at first
#: use, not import
_GLOBAL: Optional[MemoryLedger] = None
_GLOBAL_LOCK = threading.Lock()


def get_ledger() -> MemoryLedger:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MemoryLedger()
        return _GLOBAL


def set_ledger(ledger: Optional[MemoryLedger]) -> Optional[MemoryLedger]:
    """Swap the process-wide ledger (test injection); returns the old
    one.  ``None`` resets to lazy re-creation."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        old = _GLOBAL
        _GLOBAL = ledger
        return old
