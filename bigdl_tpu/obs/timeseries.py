"""Telemetry time-series: a background sampler over the MetricRegistry.

``MetricRegistry.snapshot()`` is a point-in-time read — good for a
summary line, useless for "what happened in the 30 seconds before the
stall".  :class:`TimeSeriesSampler` closes that gap: a daemon thread
snapshots the registry at a fixed interval into a bounded ring, turning
the lifetime metrics every subsystem already publishes into an actual
time axis:

- gauges (and ``FnGauge``/``Counter`` values) record their value;
- counters additionally record the **delta** since the previous tick,
  so a rate is one subtraction away;
- histograms record *windowed* p50/p99 over just the interval — the
  same ``counts()``-delta idiom ``traffic.SLOController`` uses — plus
  the interval's observation count.

Consumers: the flight recorder embeds ``window()`` in every incident
bundle (the time axis around the incident), ``bench.py`` can record a
load test's trajectory instead of one end-state snapshot, and
post-mortems read the ring directly.  The ring is bounded
(``capacity`` rows), so a week-long serving process pays a fixed
memory cost.

Threading mirrors ``SLOController``: a pure ``sample_now()`` core the
tests (and the flight recorder, on demand) call deterministically, and
``start()``/``stop()`` wrapping it in a daemon loop.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from bigdl_tpu.obs.registry import (MetricRegistry, get_registry,
                                    percentile_from_counts)

__all__ = ["TimeSeriesSampler", "get_sampler", "set_sampler"]


class TimeSeriesSampler:
    """Fixed-interval MetricRegistry sampler into a bounded ring.

    Each row::

        {"t_unix": ..., "t_perf": ..., "metrics": {
            "serving/requests":  {"value": 41.0, "delta": 3.0},
            "serving/lm/ttft":   {"count": 17, "count_delta": 2,
                                  "p50_s": ..., "p99_s": ...},
            "some/gauge":        {"value": 0.62},
        }}

    ``p50_s``/``p99_s`` in histogram entries are *windowed* (over the
    interval's observations only); ``None`` when the interval saw none.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 interval_s: float = 1.0, capacity: int = 300):
        self.registry = registry if registry is not None else get_registry()
        self.interval_s = max(float(interval_s), 0.01)
        self._rows: deque = deque(maxlen=max(int(capacity), 2))
        self._lock = threading.Lock()
        # previous tick's counter values / histogram bucket counts,
        # keyed by metric name — the windowed-delta state
        self._prev_values: dict = {}
        self._prev_counts: dict = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.ticks = 0

    # -- core (pure, deterministic) ------------------------------------- #
    def sample_now(self) -> dict:
        """Take one sample row now and append it to the ring."""
        reg = self.registry
        row_metrics: dict = {}
        # metric objects first: counters/histograms need object access
        # for deltas; names() + get() is the registry's supported read
        for name in reg.names():
            m = reg.get(name)
            if m is None:
                continue
            try:
                entry = self._sample_metric(name, m)
            except Exception as e:  # a broken FnGauge must not kill the tick
                entry = {"error": f"{type(e).__name__}: {e}"}
            if entry is not None:
                row_metrics[name] = entry
        row_metrics["obs/registry_cardinality"] = {
            "value": float(reg.cardinality())}
        row = {"t_unix": time.time(), "t_perf": time.perf_counter(),
               "metrics": row_metrics}
        with self._lock:
            self._rows.append(row)
            self.ticks += 1
        return row

    def _sample_metric(self, name: str, m) -> Optional[dict]:
        counts_fn = getattr(m, "counts", None)
        if callable(counts_fn):  # histogram-shaped: windowed percentiles
            counts = counts_fn()
            prev = self._prev_counts.get(name)
            self._prev_counts[name] = counts
            if prev is not None and len(prev) == len(counts):
                delta = [max(0, c - p) for c, p in zip(counts, prev)]
            else:
                delta = counts
            n = sum(delta)
            return {"count": int(sum(counts)), "count_delta": int(n),
                    "p50_s": percentile_from_counts(delta, 50.0),
                    "p99_s": percentile_from_counts(delta, 99.0)}
        snap = m.snapshot()
        if not isinstance(snap, dict):
            return None
        if "value" in snap:
            v = snap["value"]
            entry = {"value": v}
            get_fn = getattr(m, "get", None)
            if callable(get_fn) and isinstance(v, (int, float)):
                # Counter: value + windowed delta
                prev = self._prev_values.get(name)
                self._prev_values[name] = v
                if prev is not None:
                    entry["delta"] = v - prev
            return entry
        # registered histogram-like object without counts(): keep its
        # lifetime snapshot fields as-is
        return {k: snap[k] for k in ("count", "p50_s", "p99_s")
                if k in snap}

    # -- reading -------------------------------------------------------- #
    def window(self, last_s: Optional[float] = None) -> list:
        """Ring rows (oldest first); ``last_s`` trims to the trailing
        wall-clock window — how the flight recorder asks for "the
        minute around the incident"."""
        with self._lock:
            rows = list(self._rows)
        if last_s is not None and rows:
            cutoff = rows[-1]["t_unix"] - float(last_s)
            rows = [r for r in rows if r["t_unix"] >= cutoff]
        return rows

    def series(self, name: str, field: str = "value") -> list:
        """One metric's ``(t_unix, field)`` pairs across the ring —
        the plot-me accessor for bench summaries and post-mortems."""
        out = []
        for r in self.window():
            entry = r["metrics"].get(name)
            if isinstance(entry, dict) and field in entry:
                out.append((r["t_unix"], entry[field]))
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    # -- threading (SLOController pattern) ------------------------------ #
    def start(self) -> "TimeSeriesSampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="obs-timeseries")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_now()
            except Exception:  # pragma: no cover - belt and braces
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "TimeSeriesSampler":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


#: process-wide sampler slot — None until something (an engine opting
#: in, bench.py, the flight recorder CLI) installs one; the flight
#: recorder embeds its window when present and degrades to [] when not
_GLOBAL: Optional[TimeSeriesSampler] = None
_global_lock = threading.Lock()


def get_sampler() -> Optional[TimeSeriesSampler]:
    return _GLOBAL


def set_sampler(sampler: Optional[TimeSeriesSampler]
                ) -> Optional[TimeSeriesSampler]:
    """Install (or clear, with None) the process-wide sampler; returns
    the previous one so callers can restore it."""
    global _GLOBAL
    with _global_lock:
        prev = _GLOBAL
        _GLOBAL = sampler
    return prev
