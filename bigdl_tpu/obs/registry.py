"""Process-wide metric registry: counters, gauges, histograms, one
snapshot/export path.

The repo grew three disconnected metric stores (``optim.Metrics``
phase counters, ``serving.metrics`` latency histograms,
``utils.profiling`` roofline rows) with three export idioms.  The
registry is the single namespace they all publish into:
``snapshot()`` flattens everything to one dict, and
``export_to_summary`` writes it through the existing ``visualization``
tfevents writers, so training and serving dashboards share a spine.

The log-bucket :class:`Histogram` here is the former
``serving.metrics.LatencyHistogram`` verbatim (serving re-exports it
under the old name for compatibility); its snapshot keys
(``count``/``mean_s``/``p50_s``/``p99_s``/``max_s``) are unchanged.

Registration is get-or-create by name.  Live metric *objects* can also
be registered (``register(..., replace=True)``) — that is how a
``ServingMetrics`` or ``optim.Metrics`` instance exposes its private
counters process-wide without copying: the registry holds the same
object the hot path mutates.
"""
from __future__ import annotations

import bisect
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

log = logging.getLogger("bigdl_tpu.obs.registry")


def _log_edges() -> List[float]:
    # 10us .. ~100s, ~7% geometric steps: fine enough for p99 on a
    # millisecond-scale serving path, small enough to snapshot cheaply
    edges = []
    v = 1e-5
    while v < 100.0:
        edges.append(v)
        v *= 1.07
    return edges


_EDGES = _log_edges()


#: what an overflow-bucket rank reports: the next geometric edge past
#: the instrumented range (~100s) — finite and JSON-safe, but strictly
#: greater than every in-range answer, so overflow mass can never make
#: a window look *healthier* than the instrumented buckets would
OVERFLOW_EDGE = _EDGES[-1] * 1.07


def percentile_from_counts(counts, p: float,
                           overflow: Optional[float] = None
                           ) -> Optional[float]:
    """Percentile over a raw bucket-count vector shaped like
    ``Histogram.counts()`` (upper bucket edge, same conservative
    estimate as ``Histogram.percentile``).  The windowed-p99 primitive:
    subtracting two ``counts()`` snapshots gives the histogram of just
    the interval between them — how the SLO controller reads a sliding
    p99 out of the lifetime histograms the engines publish.

    Edge cases, pinned by tests: an empty window is ``None`` (never
    0.0); negative entries — a torn counts delta under concurrent
    ``observe`` — are clamped to zero instead of corrupting the rank;
    and a rank landing in the *overflow* bucket (observations past the
    last edge) reports ``overflow`` (default :data:`OVERFLOW_EDGE`,
    > every real edge) rather than the old quietly-too-small last
    edge, which could read a stalled window as within SLO."""
    counts = [c if c > 0 else 0 for c in counts]
    total = sum(counts)
    if not total:
        return None
    if overflow is None:
        overflow = OVERFLOW_EDGE
    rank = max(1, int(round(total * p / 100.0)))
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            return _EDGES[i] if i < len(_EDGES) else overflow
    return overflow


class Counter:
    """Monotonic-ish accumulator with the reference Metrics' (value,
    parallel-count) pair (optim/Metrics.scala's AtomicDouble + parallel
    counters) and a unit tag the summary printer respects."""

    __slots__ = ("value", "n", "unit", "_lock")

    def __init__(self, unit: str = ""):
        self.value = 0.0
        self.n = 1
        self.unit = unit
        self._lock = threading.Lock()

    def add(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += float(v)

    def set(self, v: float, n: int = 1) -> None:
        with self._lock:
            self.value = float(v)
            self.n = int(n)

    def get(self) -> tuple:
        with self._lock:
            return self.value, self.n

    def snapshot(self) -> dict:
        with self._lock:
            d = {"value": self.value, "n": self.n}
            if self.unit:
                d["unit"] = self.unit
            return d


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value", "unit", "_lock")

    def __init__(self, unit: str = ""):
        self.value: Optional[float] = None
        self.unit = unit
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def snapshot(self) -> dict:
        with self._lock:
            d = {"value": self.value}
            if self.unit:
                d["unit"] = self.unit
            return d


class FnGauge:
    """Computed gauge: reads a callable at snapshot time.  How
    ``ServingMetrics`` exposes its plain-int counters to the registry
    without double bookkeeping in the hot path."""

    __slots__ = ("fn",)

    def __init__(self, fn: Callable[[], Optional[float]]):
        self.fn = fn

    def snapshot(self) -> dict:
        try:
            v = self.fn()
        except Exception:
            v = None
        return {"value": v}


class Histogram:
    """Fixed log-bucket histogram over seconds, with percentile
    estimation (upper bucket edge — a conservative answer for a p99
    SLO check).  Formerly ``serving.metrics.LatencyHistogram``."""

    def __init__(self):
        self._counts = [0] * (len(_EDGES) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        self._counts[bisect.bisect_left(_EDGES, seconds)] += 1
        self.count += 1
        self.sum += seconds
        if seconds > self.max:
            self.max = seconds

    def counts(self) -> List[int]:
        """Copy of the raw bucket counts (pair with a later copy and
        ``percentile_from_counts`` for windowed percentiles)."""
        return list(self._counts)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None when empty."""
        if not self.count:
            return None
        rank = max(1, int(round(self.count * p / 100.0)))
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                return _EDGES[i] if i < len(_EDGES) else self.max
        return self.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_s": (self.sum / self.count) if self.count else None,
            "p50_s": self.percentile(50),
            "p99_s": self.percentile(99),
            "max_s": self.max if self.count else None,
        }


class MetricRegistry:
    """Name -> metric map with get-or-create accessors.

    Anything with a ``snapshot() -> dict`` method can be registered, so
    live ``Histogram``s owned by a serving engine and ``Counter``s owned
    by an optimizer coexist under one namespace.

    Cardinality is bounded: dynamic name families (per-quant-path
    gauges, anything keyed per request or per slot) would otherwise
    grow the map for the life of the process.  Past ``max_metrics``
    (env ``BIGDL_TPU_REGISTRY_MAX``) a *new* name gets a live but
    detached metric — the caller's hot path keeps working, the map
    stops growing — and the drop is self-reporting: every ``snapshot``
    carries synthetic ``obs/registry_cardinality`` /
    ``obs/registry_overflow_total`` gauges (synthetic so they never
    perturb ``names()`` or collide with user names).
    """

    DEFAULT_MAX_METRICS = 4096

    def __init__(self, max_metrics: Optional[int] = None):
        if max_metrics is None:
            try:
                max_metrics = int(os.environ.get(
                    "BIGDL_TPU_REGISTRY_MAX", self.DEFAULT_MAX_METRICS))
            except ValueError:
                max_metrics = self.DEFAULT_MAX_METRICS
        self.max_metrics = max(int(max_metrics), 8)
        self._metrics: Dict[str, object] = {}
        self._overflow = 0
        self._warned_overflow = False
        self._lock = threading.Lock()

    def _overflowed(self, name: str) -> None:
        # caller holds self._lock
        self._overflow += 1
        if not self._warned_overflow:
            self._warned_overflow = True
            log.warning(
                "metric registry at cardinality cap (%d): %r and "
                "subsequent new names get detached metrics; see "
                "obs/registry_overflow_total", self.max_metrics, name)

    def _get_or_create(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(**kw)
                if len(self._metrics) >= self.max_metrics:
                    self._overflowed(name)
                else:
                    self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, unit: str = "") -> Counter:
        return self._get_or_create(name, Counter, unit=unit)

    def gauge(self, name: str, unit: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, unit=unit)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def register(self, name: str, metric, replace: bool = False):
        """Bind a live metric object.  ``replace=True`` is the
        latest-owner-wins idiom: a fresh engine/optimizer rebinds the
        process-wide names to its own counters."""
        if not hasattr(metric, "snapshot"):
            raise TypeError(f"metric {name!r} has no snapshot() method")
        with self._lock:
            if not replace and name in self._metrics:
                raise ValueError(f"metric {name!r} already registered")
            if name not in self._metrics \
                    and len(self._metrics) >= self.max_metrics:
                self._overflowed(name)
            else:
                self._metrics[name] = metric
        return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list:
        with self._lock:
            return sorted(self._metrics)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._overflow = 0
            self._warned_overflow = False

    def cardinality(self) -> int:
        with self._lock:
            return len(self._metrics)

    def overflow_total(self) -> int:
        """Metric creations refused (detached) by the cardinality cap."""
        with self._lock:
            return self._overflow

    def snapshot(self) -> dict:
        """{name: metric.snapshot()} for every registered metric, plus
        the synthetic self-reporting gauges ``obs/registry_cardinality``
        and ``obs/registry_overflow_total``."""
        with self._lock:
            items = list(self._metrics.items())
            card, over = len(self._metrics), self._overflow
        snap = {name: m.snapshot() for name, m in items}
        snap["obs/registry_cardinality"] = {"value": float(card)}
        snap["obs/registry_overflow_total"] = {"value": float(over)}
        return snap

    def export_to_summary(self, summary, step: int,
                          prefix: str = "Obs/") -> int:
        """Write every scalar-valued field of the snapshot through a
        ``visualization.Summary`` (tfevents) writer; histograms export
        their p50/p99/mean/count.  Returns the scalar count written."""
        wrote = 0
        for name, snap in self.snapshot().items():
            if "value" in snap:
                if snap["value"] is not None:
                    summary.add_scalar(prefix + name, float(snap["value"]),
                                       step)
                    wrote += 1
                continue
            for key in ("p50_s", "p99_s", "mean_s", "count"):
                v = snap.get(key)
                if v is not None:
                    summary.add_scalar(f"{prefix}{name}/{key}", float(v),
                                       step)
                    wrote += 1
        summary.flush()
        return wrote


#: process-wide registry — the "one snapshot path" every subsystem
#: publishes into
_GLOBAL = MetricRegistry()


def get_registry() -> MetricRegistry:
    return _GLOBAL
