"""bigdl_tpu.obs — unified observability: tracing, telemetry, forensics.

Six pieces, one spine:

- :mod:`~bigdl_tpu.obs.tracer` — thread-safe span API (context manager
  + decorator) over a ring buffer, exported as Chrome trace-event JSON
  (Perfetto-loadable) or a structured JSONL log.  Request-scoped:
  every serving submission is minted a ``request_id``
  (:func:`mint_request_id`), propagated through batch assembly,
  prefill, decode/verify rounds, and failover re-dispatch, and
  assembled back into a per-request span tree
  (:meth:`Tracer.span_tree` / :meth:`Tracer.export_request`).
  Enabled via ``BIGDL_TPU_TRACE=1``; sampled per request via
  ``BIGDL_TPU_TRACE_SAMPLE``; near-zero overhead when off.
- :mod:`~bigdl_tpu.obs.registry` — process-wide MetricRegistry of
  counters/gauges/histograms (cardinality-capped;
  ``BIGDL_TPU_REGISTRY_MAX``); ``optim.Metrics`` and
  ``serving.ServingMetrics`` publish into it, and one
  ``export_to_summary`` path writes everything through the
  ``visualization`` tfevents writers.
- :mod:`~bigdl_tpu.obs.timeseries` — TimeSeriesSampler: a background
  thread snapshotting the registry at a fixed interval into bounded
  rings — gauge values, counter deltas, windowed histogram p50/p99 —
  the time axis the SLO controller, bench.py, and post-mortems read.
- :mod:`~bigdl_tpu.obs.flight` — FlightRecorder: on a watchdog stall,
  a classified backend-lost, a fault-injector fire, or a shed burst,
  atomically dump ONE correlated bundle (last spans + time-series
  window + ``Engine.diagnose_tpu()`` + serving state + active request
  ids) to ``FLIGHT_<ts>.json`` and append a pointer into
  ``TUNNEL_INCIDENTS.json``.  Armed via ``BIGDL_TPU_FLIGHT=1``.
- :mod:`~bigdl_tpu.obs.watchdog` — StallWatchdog: rolling-median step
  cadence; a hung step captures ``Engine.diagnose_tpu()`` + all-thread
  stacks into the trace before the process looks merely "slow".
- :mod:`~bigdl_tpu.obs.ledger` — MemoryLedger: process-wide HBM byte
  attribution (params / KV arenas / drafter / kvtier / executables),
  per-executable roofline costs captured at AOT-lower time,
  ``headroom(device)`` + reconciliation drift vs
  ``device.memory_stats()``, and a ``mem_pressure`` flight trigger at
  the ``BIGDL_TPU_MEM_WATERMARK`` used-fraction watermark.

Quickstart::

    import os; os.environ["BIGDL_TPU_TRACE"] = "1"   # before import
    from bigdl_tpu import obs

    tr = obs.get_tracer()
    with tr.span("my_phase", cat="app", rows=1024):
        ...
    tr.export_chrome("TRACE_app.json")               # open in Perfetto

    reg = obs.get_registry()
    reg.counter("app/requests").add(1)
    print(reg.snapshot())
"""
from bigdl_tpu.obs.ledger import MemoryLedger, get_ledger, set_ledger
from bigdl_tpu.obs.registry import (Counter, FnGauge, Gauge, Histogram,
                                    MetricRegistry, get_registry,
                                    percentile_from_counts)
from bigdl_tpu.obs.timeseries import (TimeSeriesSampler, get_sampler,
                                      set_sampler)
from bigdl_tpu.obs.tracer import (Tracer, get_tracer, mint_request_id,
                                  set_request_context,
                                  get_request_context,
                                  clear_request_context)
from bigdl_tpu.obs.watchdog import (StallWatchdog, env_watchdog_enabled,
                                    env_watchdog_kwargs, shared_watchdog,
                                    thread_stacks)

# Flight names resolve lazily (PEP 562): an eager `from ...flight
# import` here would put bigdl_tpu.obs.flight in sys.modules before
# runpy executes it, so every `python -m bigdl_tpu.obs.flight dump`
# (chip_opportunist's incident recorder) logged a RuntimeWarning about
# the double import.  Everything else in the tree already imports
# flight lazily; the package facade now does too.
_FLIGHT_NAMES = ("FlightRecorder", "get_flight_recorder", "note_shed")


def __getattr__(name):
    if name in _FLIGHT_NAMES:
        from bigdl_tpu.obs import flight
        return getattr(flight, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Tracer", "get_tracer", "mint_request_id",
    "set_request_context", "get_request_context", "clear_request_context",
    "Counter", "Gauge", "FnGauge", "Histogram", "MetricRegistry",
    "get_registry", "percentile_from_counts",
    "TimeSeriesSampler", "get_sampler", "set_sampler",
    "FlightRecorder", "get_flight_recorder", "note_shed",
    "MemoryLedger", "get_ledger", "set_ledger",
    "StallWatchdog", "env_watchdog_enabled", "env_watchdog_kwargs",
    "shared_watchdog", "thread_stacks",
]
