"""bigdl_tpu.obs — unified observability: tracing, metrics, watchdog.

Three pieces, one spine:

- :mod:`~bigdl_tpu.obs.tracer` — thread-safe span API (context manager
  + decorator) over a ring buffer, exported as Chrome trace-event JSON
  (Perfetto-loadable) or a structured JSONL log.  Enabled via
  ``BIGDL_TPU_TRACE=1``; near-zero overhead when off.
- :mod:`~bigdl_tpu.obs.registry` — process-wide MetricRegistry of
  counters/gauges/histograms; ``optim.Metrics`` and
  ``serving.ServingMetrics`` publish into it, and one
  ``export_to_summary`` path writes everything through the
  ``visualization`` tfevents writers.
- :mod:`~bigdl_tpu.obs.watchdog` — StallWatchdog: rolling-median step
  cadence; a hung step captures ``Engine.diagnose_tpu()`` + all-thread
  stacks into the trace before the process looks merely "slow".

Quickstart::

    import os; os.environ["BIGDL_TPU_TRACE"] = "1"   # before import
    from bigdl_tpu import obs

    tr = obs.get_tracer()
    with tr.span("my_phase", cat="app", rows=1024):
        ...
    tr.export_chrome("TRACE_app.json")               # open in Perfetto

    reg = obs.get_registry()
    reg.counter("app/requests").add(1)
    print(reg.snapshot())
"""
from bigdl_tpu.obs.registry import (Counter, FnGauge, Gauge, Histogram,
                                    MetricRegistry, get_registry,
                                    percentile_from_counts)
from bigdl_tpu.obs.tracer import Tracer, get_tracer
from bigdl_tpu.obs.watchdog import (StallWatchdog, env_watchdog_enabled,
                                    env_watchdog_kwargs, shared_watchdog,
                                    thread_stacks)

__all__ = [
    "Tracer", "get_tracer",
    "Counter", "Gauge", "FnGauge", "Histogram", "MetricRegistry",
    "get_registry", "percentile_from_counts",
    "StallWatchdog", "env_watchdog_enabled", "env_watchdog_kwargs",
    "shared_watchdog", "thread_stacks",
]
