"""Span tracing: one trace spine for training steps and serving requests.

The reference's observability is per-phase wall-clock counters summed on
the Spark driver (optim/Metrics.scala); a counter tells you the *mean*
cost of a phase, never which iteration or which request was slow.  This
module is the missing timeline: a thread-safe span API whose events
export as Chrome trace-event JSON (loadable in Perfetto / chrome://
tracing) and as a structured JSONL log.

Design constraints, in order:

1. near-zero overhead when disabled — every instrumented hot path
   (batcher dispatch, per-chunk uploads, the training loop) calls
   ``span()`` unconditionally, so the disabled path must be one
   attribute check returning a shared no-op context manager;
2. thread-safe and allocation-bounded — events land in a ring buffer
   (``collections.deque`` with ``maxlen``), so a week-long serving
   process can keep tracing without growing;
3. retroactive spans — the batcher learns a request's queue wait only
   at dispatch time, so ``add_complete`` accepts an explicit start
   timestamp instead of requiring a context manager around the wait.

Toggled by the ``BIGDL_TPU_TRACE`` env var (read at import for the
process-wide tracer; ``enable()``/``disable()`` flip it at runtime).
Timestamps are ``time.perf_counter`` microseconds relative to the
tracer's epoch — monotonic, immune to NTP steps, and exactly what the
Chrome ``ts``/``dur`` fields want.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from functools import wraps
from typing import Optional


def _env_enabled() -> bool:
    return os.environ.get("BIGDL_TPU_TRACE", "0").lower() in ("1", "true", "on")


class _NullSpan:
    """Shared no-op context manager for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records a Chrome 'X' (complete) event on exit."""
    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if exc_type is not None:
            self.args = dict(self.args)
            self.args["error"] = f"{exc_type.__name__}: {exc}"
        self._tracer.add_complete(self.name, self._t0, t1 - self._t0,
                                  cat=self.cat, args=self.args)
        return False


class Tracer:
    """Ring-buffered trace-event collector.

    One process normally uses the module-level tracer (``get_tracer()``);
    private instances exist for tests and for tools that want an
    isolated buffer.
    """

    def __init__(self, capacity: int = 65536,
                 enabled: Optional[bool] = None):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self._events: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        # perf_counter epoch; the unix pair stamps exports with wall time
        self._epoch_perf = time.perf_counter()
        self._epoch_unix = time.time()
        self._pid = os.getpid()

    # -- control -------------------------------------------------------- #
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- recording ------------------------------------------------------ #
    def span(self, name: str, cat: str = "obs", **args):
        """Context manager timing a section.  Disabled: a shared no-op."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def traced(self, name: Optional[str] = None, cat: str = "obs"):
        """Decorator form of ``span`` (span name defaults to the
        function's qualified name)."""
        def deco(fn):
            label = name or fn.__qualname__

            @wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                with self.span(label, cat=cat):
                    return fn(*a, **kw)
            return wrapper
        return deco

    def _ts_us(self, t_perf: float) -> float:
        return (t_perf - self._epoch_perf) * 1e6

    def add_complete(self, name: str, t0_perf: float, dur_s: float,
                     cat: str = "obs", args: Optional[dict] = None,
                     tid: Optional[int] = None) -> None:
        """Record a finished span retroactively (``t0_perf`` from
        ``time.perf_counter``) — how the batcher reports a request's
        queue wait it only knows at dispatch time."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": self._ts_us(t0_perf), "dur": max(dur_s, 0.0) * 1e6,
              "pid": self._pid,
              "tid": tid if tid is not None else threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, cat: str = "obs", **args) -> None:
        """Point-in-time event (Chrome ph='i', thread scope)."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._ts_us(time.perf_counter()),
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    # -- reading / export ---------------------------------------------- #
    def events(self) -> list:
        with self._lock:
            return [dict(e) for e in self._events]

    def _thread_metadata(self, events: list) -> list:
        """Chrome 'M' thread_name rows so Perfetto shows thread names
        instead of bare idents."""
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        rows = []
        for tid in sorted({e["tid"] for e in events}):
            rows.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                         "tid": tid,
                         "args": {"name": names.get(tid, f"thread-{tid}")}})
        return rows

    def export_chrome(self, path: Optional[str] = None) -> dict:
        """The buffered events as a Chrome trace-event document
        (``{"traceEvents": [...]}``); written to ``path`` when given.
        Loadable as-is in Perfetto / chrome://tracing."""
        events = self.events()
        doc = {
            "traceEvents": self._thread_metadata(events) + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "bigdl_tpu.obs",
                "epoch_unix": self._epoch_unix,
            },
        }
        if path:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        return doc

    def export_jsonl(self, path: str) -> int:
        """Structured event log: one JSON object per line (the grep/jq
        side of the same buffer); returns the row count."""
        events = self.events()
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        os.replace(tmp, path)
        return len(events)


#: process-wide tracer — instrumented modules bind this once at import
_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL
