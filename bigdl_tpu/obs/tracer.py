"""Span tracing: one trace spine for training steps and serving requests.

The reference's observability is per-phase wall-clock counters summed on
the Spark driver (optim/Metrics.scala); a counter tells you the *mean*
cost of a phase, never which iteration or which request was slow.  This
module is the missing timeline: a thread-safe span API whose events
export as Chrome trace-event JSON (loadable in Perfetto / chrome://
tracing) and as a structured JSONL log.

Design constraints, in order:

1. near-zero overhead when disabled — every instrumented hot path
   (batcher dispatch, per-chunk uploads, the training loop) calls
   ``span()`` unconditionally, so the disabled path must be one
   attribute check returning a shared no-op context manager;
2. thread-safe and allocation-bounded — events land in a ring buffer
   (``collections.deque`` with ``maxlen``), so a week-long serving
   process can keep tracing without growing;
3. retroactive spans — the batcher learns a request's queue wait only
   at dispatch time, so ``add_complete`` accepts an explicit start
   timestamp instead of requiring a context manager around the wait.

Toggled by the ``BIGDL_TPU_TRACE`` env var (read at import for the
process-wide tracer; ``enable()``/``disable()`` flip it at runtime).
Timestamps are ``time.perf_counter`` microseconds relative to the
tracer's epoch — monotonic, immune to NTP steps, and exactly what the
Chrome ``ts``/``dur`` fields want.

Request-scoped tracing rides the same buffer: serving entry points mint
an id with :func:`mint_request_id`, stamp it into span ``args``
(``request_id`` for per-request events, ``request_ids`` for batch-level
events that cover several), and :meth:`Tracer.span_tree` /
:meth:`Tracer.export_request` reassemble one request's timeline from
the ring.  ``BIGDL_TPU_TRACE_SAMPLE`` (0..1, default 1) decides — by a
deterministic hash of the id, so every layer agrees without passing a
flag — which requests record their per-round events, keeping tracing
cheap at high QPS.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from functools import wraps
from typing import Optional


def _env_enabled() -> bool:
    return os.environ.get("BIGDL_TPU_TRACE", "0").lower() in ("1", "true", "on")


def _env_sample_rate() -> float:
    try:
        rate = float(os.environ.get("BIGDL_TPU_TRACE_SAMPLE", "1"))
    except ValueError:
        return 1.0
    return min(max(rate, 0.0), 1.0)


#: process-wide request-id sequence; ids stay unique across engines and
#: batchers inside one process, and the pid prefix disambiguates merged
#: multi-process traces
_REQ_SEQ = itertools.count(1)


def mint_request_id() -> str:
    """A fresh request id (``r<pid>-<seq>``).  Always cheap, always
    minted — the flight recorder lists active ids even when tracing is
    off; sampling only gates what the *tracer* records for the id."""
    return "r%d-%d" % (os.getpid(), next(_REQ_SEQ))


# -- request context ---------------------------------------------------- #
# The batcher knows which requests are in the batch it is dispatching;
# the layers below it (ReplicaSet failover, engine run_batch) only see a
# padded array.  A thread-local carries the ids across that call so the
# failover hop can stamp them without widening every run_batch signature.
_REQCTX = threading.local()


def set_request_context(request_ids) -> None:
    """Bind the given request ids to the current thread (the dispatch
    thread) until cleared; tuple-copied so callers can reuse the list."""
    _REQCTX.rids = tuple(request_ids)


def get_request_context() -> tuple:
    """Request ids bound to the current thread (empty when none)."""
    return getattr(_REQCTX, "rids", ())


def clear_request_context() -> None:
    _REQCTX.rids = ()


class _NullSpan:
    """Shared no-op context manager for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records a Chrome 'X' (complete) event on exit."""
    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if exc_type is not None:
            self.args = dict(self.args)
            self.args["error"] = f"{exc_type.__name__}: {exc}"
        self._tracer.add_complete(self.name, self._t0, t1 - self._t0,
                                  cat=self.cat, args=self.args)
        return False


class Tracer:
    """Ring-buffered trace-event collector.

    One process normally uses the module-level tracer (``get_tracer()``);
    private instances exist for tests and for tools that want an
    isolated buffer.
    """

    def __init__(self, capacity: int = 65536,
                 enabled: Optional[bool] = None,
                 sample_rate: Optional[float] = None):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.sample_rate = (_env_sample_rate() if sample_rate is None
                            else min(max(float(sample_rate), 0.0), 1.0))
        self._events: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        # perf_counter epoch; the unix pair stamps exports with wall time
        self._epoch_perf = time.perf_counter()
        self._epoch_unix = time.time()
        self._pid = os.getpid()

    # -- control -------------------------------------------------------- #
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def set_sample_rate(self, rate: float) -> None:
        self.sample_rate = min(max(float(rate), 0.0), 1.0)

    def sampled(self, request_id: Optional[str]) -> bool:
        """Whether per-round events should be recorded for this request.

        Deterministic on the id (crc32 fraction vs ``sample_rate``), so
        admission, prefill, decode and failover all make the same call
        without coordinating — a sampled request traces end to end, an
        unsampled one costs nothing anywhere."""
        if not self.enabled or not request_id:
            return False
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        frac = (zlib.crc32(request_id.encode()) & 0xFFFFFFFF) / 2.0 ** 32
        return frac < self.sample_rate

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- recording ------------------------------------------------------ #
    def span(self, name: str, cat: str = "obs", **args):
        """Context manager timing a section.  Disabled: a shared no-op."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def traced(self, name: Optional[str] = None, cat: str = "obs"):
        """Decorator form of ``span`` (span name defaults to the
        function's qualified name)."""
        def deco(fn):
            label = name or fn.__qualname__

            @wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                with self.span(label, cat=cat):
                    return fn(*a, **kw)
            return wrapper
        return deco

    def _ts_us(self, t_perf: float) -> float:
        return (t_perf - self._epoch_perf) * 1e6

    def add_complete(self, name: str, t0_perf: float, dur_s: float,
                     cat: str = "obs", args: Optional[dict] = None,
                     tid: Optional[int] = None) -> None:
        """Record a finished span retroactively (``t0_perf`` from
        ``time.perf_counter``) — how the batcher reports a request's
        queue wait it only knows at dispatch time."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": self._ts_us(t0_perf), "dur": max(dur_s, 0.0) * 1e6,
              "pid": self._pid,
              "tid": tid if tid is not None else threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, cat: str = "obs", **args) -> None:
        """Point-in-time event (Chrome ph='i', thread scope)."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._ts_us(time.perf_counter()),
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    # -- reading / export ---------------------------------------------- #
    def events(self) -> list:
        """A snapshot of the ring, ordered by start timestamp.

        Events land in the ring at *completion* time, so under
        concurrent writers the raw append order interleaves
        arbitrarily; sorting by ``ts`` (stable, so equal-ts events keep
        completion order) gives every reader — exports, the flight
        recorder, tests — one canonical ordering.  Each event dict is
        copied under the lock, so a reader never sees a span another
        thread is still assembling."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        evs.sort(key=lambda e: e.get("ts", 0.0))
        return evs

    @staticmethod
    def _matches_request(ev: dict, request_id: str) -> bool:
        args = ev.get("args")
        if not isinstance(args, dict):
            return False
        if args.get("request_id") == request_id:
            return True
        rids = args.get("request_ids")
        return isinstance(rids, (list, tuple)) and request_id in rids

    def request_events(self, request_id: str) -> list:
        """Every buffered event stamped with this request id — directly
        (``args.request_id``) or as a member of a batch-level event's
        ``args.request_ids`` list."""
        return [e for e in self.events()
                if self._matches_request(e, request_id)]

    def span_tree(self, request_id: str) -> dict:
        """One request's events assembled into a phase tree.

        Spans nest by interval containment (a span whose ``[ts,
        ts+dur]`` lies inside another's is its child), which
        reconstructs the request's lifecycle — queue wait, prefill
        chunks, per-round decode/verify, failover hops — from the flat
        ring without the recorders ever coordinating.  Instants join as
        zero-duration leaves.  Returns ``{"request_id", "span_count",
        "spans": [...]}`` where each span is ``{"name", "cat", "ph",
        "ts", "dur", "args", "children"}``."""
        nodes = []
        for e in sorted(self.request_events(request_id),
                        key=lambda e: (e.get("ts", 0.0),
                                       -e.get("dur", 0.0))):
            nodes.append({"name": e.get("name"), "cat": e.get("cat"),
                          "ph": e.get("ph"), "ts": e.get("ts", 0.0),
                          "dur": e.get("dur", 0.0),
                          "args": e.get("args", {}), "children": []})
        roots: list = []
        stack: list = []
        for n in nodes:
            end = n["ts"] + n["dur"]
            while stack and not (n["ts"] >= stack[-1]["ts"]
                                 and end <= stack[-1]["ts"]
                                 + stack[-1]["dur"]):
                stack.pop()
            (stack[-1]["children"] if stack else roots).append(n)
            if n["ph"] == "X":
                stack.append(n)
        return {"request_id": request_id, "span_count": len(nodes),
                "spans": roots}

    def export_request(self, request_id: str,
                       path: Optional[str] = None) -> dict:
        """One request's events as a Chrome trace-event document —
        the same format ``export_chrome`` writes, filtered to the
        request — written atomically to ``path`` when given."""
        events = self.request_events(request_id)
        doc = {
            "traceEvents": self._thread_metadata(events) + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "bigdl_tpu.obs",
                "epoch_unix": self._epoch_unix,
                "request_id": request_id,
            },
        }
        if path:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        return doc

    def _thread_metadata(self, events: list) -> list:
        """Chrome 'M' thread_name rows so Perfetto shows thread names
        instead of bare idents."""
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        rows = []
        for tid in sorted({e["tid"] for e in events}):
            rows.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                         "tid": tid,
                         "args": {"name": names.get(tid, f"thread-{tid}")}})
        return rows

    def export_chrome(self, path: Optional[str] = None) -> dict:
        """The buffered events as a Chrome trace-event document
        (``{"traceEvents": [...]}``); written to ``path`` when given.
        Loadable as-is in Perfetto / chrome://tracing."""
        events = self.events()
        doc = {
            "traceEvents": self._thread_metadata(events) + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "bigdl_tpu.obs",
                "epoch_unix": self._epoch_unix,
            },
        }
        if path:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        return doc

    def export_jsonl(self, path: str) -> int:
        """Structured event log: one JSON object per line (the grep/jq
        side of the same buffer); returns the row count."""
        events = self.events()
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        os.replace(tmp, path)
        return len(events)


#: process-wide tracer — instrumented modules bind this once at import
_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL
