"""bigdl_tpu: a TPU-native distributed deep-learning framework.

A ground-up JAX/XLA rebuild of the capabilities of BigDL v0.1 (Intel's
Torch-style distributed DL library for Apache Spark; reference surveyed in
/root/repo/SURVEY.md).  The compute path is jax.numpy / lax under jax.jit
(XLA plays the role MKL played on Xeon); distribution is expressed as
shardings over a `jax.sharding.Mesh` with XLA collectives over ICI/DCN
(playing the role of the reference's FP16 all-reduce over Spark's
BlockManager, reference parameters/AllReduceParameter.scala:53-228).

Top-level layout (mirrors the reference's layer map, SURVEY.md SS1):

- ``bigdl_tpu.tensor``   -- dtype seam + Torch-verb array helpers  (ref tensor/)
- ``bigdl_tpu.nn``       -- module system, layer zoo, criterions   (ref nn/)
- ``bigdl_tpu.optim``    -- optim methods, local/distributed loops (ref optim/)
- ``bigdl_tpu.parallel`` -- mesh, collectives, sharded parameters  (ref parameters/)
- ``bigdl_tpu.dataset``  -- DataSet/Transformer input pipeline     (ref dataset/)
- ``bigdl_tpu.models``   -- model zoo + train/test CLIs            (ref models/)
- ``bigdl_tpu.utils``    -- Engine, Table, RNG, File, Summary      (ref utils/)
"""

__version__ = "0.1.0"

from bigdl_tpu.utils.table import Table, T  # noqa: F401
from bigdl_tpu.utils.engine import Engine  # noqa: F401
