"""Triggers: predicates over the training state (ref optim/Trigger.scala:22-70)."""
from __future__ import annotations

from typing import Callable


class Trigger:
    def __init__(self, fn: Callable[[dict], bool], name: str = "trigger"):
        self._fn = fn
        self.name = name

    def __call__(self, state: dict) -> bool:
        return self._fn(state)

    # -- factories (same four as the reference) -------------------------- #
    @staticmethod
    def every_epoch() -> "Trigger":
        """Fires when the epoch number just advanced (the optimizer sets
        'epoch_finished' at epoch rollover)."""
        return Trigger(lambda s: s.get("epoch_finished", False), "every_epoch")

    @staticmethod
    def several_iteration(interval: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] % interval == 0, f"several_iteration({interval})")

    @staticmethod
    def max_epoch(maximum: int) -> "Trigger":
        return Trigger(lambda s: s["epoch"] > maximum, f"max_epoch({maximum})")

    @staticmethod
    def max_iteration(maximum: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] > maximum, f"max_iteration({maximum})")

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers), "and")

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers), "or")
