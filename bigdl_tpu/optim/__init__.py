"""optim: optimization engine (ref spark/dl/.../optim/, 2,475 LoC)."""
from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adagrad, Adam, AdamW, LBFGS, LearningRateSchedule,
    Default, Poly, Step, EpochStep, EpochDecay, EpochSchedule, Regime,
    ls_wolfe,
)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, AccuracyResult, LossResult,
    PerplexityResult, Top1Accuracy, Top5Accuracy, Loss, Perplexity,
)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optimizer import (
    Optimizer, LocalOptimizer, Validator, LocalValidator,
)
