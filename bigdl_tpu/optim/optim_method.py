"""Optimization methods (ref optim/OptimMethod.scala:37-65, SGD.scala,
Adagrad.scala, LBFGS.scala + LineSearch.scala).

First-order methods are pure ``update`` functions over pytrees, designed to
live inside one jitted train step (hyper-parameter schedules are traced
functions of an iteration counter carried in the optimizer state, so one
XLA program covers the whole run — no per-iteration recompile).

LBFGS is host-driven over the flattened parameter vector with a strong-
Wolfe line search, like the reference; each feval is still one jitted
device computation.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


# --------------------------------------------------------------------- #
# learning-rate schedules (ref optim/SGD.scala:127-208)                 #
# --------------------------------------------------------------------- #
class LearningRateSchedule:
    def rate(self, base_lr, iteration, epoch):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + iteration * decay) (Torch SGD default)."""

    def __init__(self, decay: float = 0.0):
        self.decay = decay

    def rate(self, base_lr, iteration, epoch):
        return base_lr / (1.0 + iteration * self.decay)


class Poly(LearningRateSchedule):
    """lr * (1 - iter/max)^power; 0 beyond max (ref SGD.Poly)."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def rate(self, base_lr, iteration, epoch):
        frac = jnp.clip(iteration / self.max_iteration, 0.0, 1.0)
        return base_lr * (1.0 - frac) ** self.power


class Step(LearningRateSchedule):
    """lr * gamma^(floor(iter / step_size)) (ref SGD.Step)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def rate(self, base_lr, iteration, epoch):
        return base_lr * self.gamma ** jnp.floor(iteration / self.step_size)


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor((epoch-1) / step)) (ref SGD.EpochStep)."""

    def __init__(self, step: int, gamma: float):
        self.step = step
        self.gamma = gamma

    def rate(self, base_lr, iteration, epoch):
        return base_lr * self.gamma ** jnp.floor((epoch - 1) / self.step)


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decay(epoch) with a user decay function (ref SGD.EpochDecay).
    The function must be jnp-traceable (epoch arrives as a traced scalar)."""

    def __init__(self, decay_fn: Callable):
        self.decay_fn = decay_fn

    def rate(self, base_lr, iteration, epoch):
        return base_lr * 0.1 ** self.decay_fn(epoch)


class Regime:
    """Epoch range + config (ref SGD.Regime).  ``config`` is a dict with
    "learning_rate" (absolute) or "learning_rate_multiplier" (scales the
    method's base lr — the reference's Train.scala regimes express the
    classic lr, lr/10, lr/100 staircase this way); a bare number is
    shorthand for the multiplier form."""

    def __init__(self, start_epoch: int, end_epoch: int, config):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        if not isinstance(config, dict):
            config = {"learning_rate_multiplier": float(config)}
        self.config = config


class EpochSchedule(LearningRateSchedule):
    """Piecewise-constant lr by epoch regime (ref SGD.EpochSchedule)."""

    def __init__(self, regimes: list[Regime]):
        self.regimes = regimes

    def rate(self, base_lr, iteration, epoch):
        lr = base_lr
        for r in self.regimes:
            in_regime = (epoch >= r.start_epoch) & (epoch <= r.end_epoch)
            if "learning_rate_multiplier" in r.config:
                regime_lr = base_lr * r.config["learning_rate_multiplier"]
            else:
                regime_lr = r.config.get("learning_rate", base_lr)
            lr = jnp.where(in_regime, regime_lr, lr)
        return lr


# --------------------------------------------------------------------- #
# OptimMethod base                                                      #
# --------------------------------------------------------------------- #
class OptimMethod:
    """Functional optimizer: init_state + update (jit-composable), plus a
    host-level ``optimize(feval, x)`` mirroring the reference signature."""

    def init_state(self, params):
        return {"iteration": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, epoch=1):
        """-> (new_params, new_state). Pure; safe inside jit/shard_map."""
        raise NotImplementedError

    def optimize(self, feval: Callable, x, epoch: int = 1):
        """One step given feval: x -> (loss, grad) (ref OptimMethod.optimize).
        Keeps per-method state on the instance like the reference's state
        Table."""
        if not hasattr(self, "_state") or self._state is None:
            self._state = self.init_state(x)
        loss, grad = feval(x)
        x, self._state = self.update(grad, self._state, x, epoch=epoch)
        return x, [loss]

    def clear_history(self) -> None:
        self._state = None

    def get_hyper_parameter(self) -> str:
        return ""


class SGD(OptimMethod):
    """SGD with momentum/nesterov/weight-decay and lr schedules
    (ref optim/SGD.scala:25-127).  Semantics follow Torch optim.sgd:
    v = mu*v + (1-dampening)*g ; g = g + mu*v (nesterov) or v."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, momentum: float = 0.0,
                 dampening: Optional[float] = None, nesterov: bool = False,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        self.learning_rate = learning_rate
        self.weight_decay = weight_decay
        self.momentum = momentum
        # Torch-Lua/BigDL default: dampening = momentum (ref SGD.scala:39),
        # except under nesterov which requires dampening = 0.  Pass
        # dampening=0.0 explicitly for PyTorch-style heavy-ball SGD.
        self.dampening = dampening if dampening is not None else (
            0.0 if nesterov else momentum)
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or self.dampening != 0.0):
            raise ValueError("nesterov requires momentum > 0 and dampening = 0")
        self.schedule = learning_rate_schedule or Default(learning_rate_decay)

    def init_state(self, params):
        state = {"iteration": jnp.zeros((), jnp.int32)}
        if self.momentum > 0:
            state["velocity"] = jax.tree_util.tree_map(jnp.zeros_like, params)
        return state

    def current_rate(self, state, epoch=1):
        return self.schedule.rate(self.learning_rate, state["iteration"], epoch)

    def update(self, grads, state, params, epoch=1):
        lr = self.current_rate(state, epoch)
        damp = self.dampening

        if self.weight_decay > 0:
            grads = jax.tree_util.tree_map(
                lambda g, w: g + self.weight_decay * w, grads, params)
        if self.momentum > 0:
            new_v = jax.tree_util.tree_map(
                lambda v, g: self.momentum * v + (1 - damp) * g,
                state["velocity"], grads)
            if self.nesterov:
                step_dir = jax.tree_util.tree_map(
                    lambda g, v: g + self.momentum * v, grads, new_v)
            else:
                step_dir = new_v
            new_state = {"iteration": state["iteration"] + 1, "velocity": new_v}
        else:
            step_dir = grads
            new_state = {"iteration": state["iteration"] + 1}
        new_params = jax.tree_util.tree_map(lambda w, d: w - lr * d, params, step_dir)
        return new_params, new_state

    def get_hyper_parameter(self) -> str:
        return f"Current learning rate is {self.learning_rate}. "


class Adagrad(OptimMethod):
    """Adagrad (ref optim/Adagrad.scala:25-78)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0, eps: float = 1e-10):
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.eps = eps

    def init_state(self, params):
        return {"iteration": jnp.zeros((), jnp.int32),
                "accum": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def current_rate(self, state, epoch=1):
        return self.learning_rate / (1.0 + state["iteration"] * self.learning_rate_decay)

    def update(self, grads, state, params, epoch=1):
        lr = self.current_rate(state, epoch)
        accum = jax.tree_util.tree_map(lambda a, g: a + g * g, state["accum"], grads)
        new_params = jax.tree_util.tree_map(
            lambda w, g, a: w - lr * g / (jnp.sqrt(a) + self.eps), params, grads, accum)
        return new_params, {"iteration": state["iteration"] + 1, "accum": accum}


class Adam(OptimMethod):
    """Adam with bias correction (post-reference capability: the
    reference's method set is SGD/Adagrad/LBFGS, optim/; the transformer
    family effectively requires an adaptive method, and the state pytree
    shards under the ZeRO-1 cycle exactly like SGD's momentum does).
    Matches the standard formulation (Kingma & Ba 2015) — oracle-tested
    against torch.optim.Adam."""

    def __init__(self, learning_rate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        self.learning_rate = learning_rate
        self.beta1 = beta1
        self.beta2 = beta2
        self.eps = eps
        self.weight_decay = weight_decay
        self.learning_rate_schedule = learning_rate_schedule or Default()

    def init_state(self, params):
        return {"iteration": jnp.zeros((), jnp.int32),
                "m": jax.tree_util.tree_map(jnp.zeros_like, params),
                "v": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def current_rate(self, state, epoch=1):
        return self.learning_rate_schedule.rate(
            self.learning_rate, state["iteration"], epoch)

    def _decayed(self, grads, params):
        if self.weight_decay == 0.0:
            return grads
        # L2-style decay folded into the gradient (torch.optim.Adam
        # semantics; see AdamW for the decoupled variant)
        return jax.tree_util.tree_map(
            lambda g, w: g + self.weight_decay * w, grads, params)

    def update(self, grads, state, params, epoch=1):
        lr = self.current_rate(state, epoch)
        t = state["iteration"] + 1
        tf = t.astype(jnp.float32)
        grads = self._decayed(grads, params)
        m = jax.tree_util.tree_map(
            lambda mm, g: self.beta1 * mm + (1 - self.beta1) * g,
            state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda vv, g: self.beta2 * vv + (1 - self.beta2) * g * g,
            state["v"], grads)
        bc1 = 1 - self.beta1 ** tf
        bc2 = 1 - self.beta2 ** tf
        new_params = jax.tree_util.tree_map(
            lambda w, mm, vv: w - lr * (mm / bc1)
            / (jnp.sqrt(vv / bc2) + self.eps),
            params, m, v)
        new_params = self._post_step(new_params, params, lr)
        return new_params, {"iteration": t, "m": m, "v": v}

    def _post_step(self, new_params, params, lr):
        return new_params


class AdamW(Adam):
    """Adam with decoupled weight decay (Loshchilov & Hutter 2019):
    decay applies directly to the weights, scaled by the current rate,
    instead of riding the gradient through the second-moment estimate."""

    def _decayed(self, grads, params):
        return grads  # decay decoupled: applied in _post_step

    def _post_step(self, new_params, params, lr):
        if self.weight_decay == 0.0:
            return new_params
        return jax.tree_util.tree_map(
            lambda nw, w: nw - lr * self.weight_decay * w,
            new_params, params)


# --------------------------------------------------------------------- #
# LBFGS (ref optim/LBFGS.scala:38-280 + LineSearch.scala lswolfe)       #
# --------------------------------------------------------------------- #
def ls_wolfe(feval, x, t, d, f, g, gtd, c1=1e-4, c2=0.9, tol_x=1e-9,
             max_iter=20):
    """Strong-Wolfe cubic-interpolation line search (ref LineSearch.scala).
    Works on flat jnp vectors; feval returns (f, g)."""
    d_norm = float(jnp.max(jnp.abs(d)))
    g = jnp.asarray(g)
    # bracket phase
    t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
    ls_func_evals = 0
    bracket = None
    for _ in range(max_iter):
        f_new, g_new = feval(x + t * d)
        ls_func_evals += 1
        gtd_new = float(jnp.vdot(g_new, d))
        if f_new > (f + c1 * t * gtd) or (ls_func_evals > 1 and f_new >= f_prev):
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new, gtd_prev, gtd_new)
            break
        if abs(gtd_new) <= -c2 * gtd:
            return f_new, g_new, t, ls_func_evals
        if gtd_new >= 0:
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new, gtd_prev, gtd_new)
            break
        t_prev, f_prev, g_prev, gtd_prev = t, f_new, g_new, gtd_new
        t = min(10.0, t * 2.0)
    if bracket is None:
        return f_new, g_new, t, ls_func_evals
    # zoom phase
    lo_t, hi_t, lo_f, hi_f, lo_g, hi_g, lo_gtd, hi_gtd = bracket
    for _ in range(max_iter):
        if abs(hi_t - lo_t) * d_norm < tol_x:
            break
        t = (lo_t + hi_t) / 2.0
        f_new, g_new = feval(x + t * d)
        ls_func_evals += 1
        gtd_new = float(jnp.vdot(g_new, d))
        if f_new > (f + c1 * t * gtd) or f_new >= lo_f:
            hi_t, hi_f, hi_g, hi_gtd = t, f_new, g_new, gtd_new
        else:
            if abs(gtd_new) <= -c2 * gtd:
                return f_new, g_new, t, ls_func_evals
            if gtd_new * (hi_t - lo_t) >= 0:
                hi_t, hi_f, hi_g, hi_gtd = lo_t, lo_f, lo_g, lo_gtd
            lo_t, lo_f, lo_g, lo_gtd = t, f_new, g_new, gtd_new
    return f_new, g_new, t, ls_func_evals


class LBFGS(OptimMethod):
    """Limited-memory BFGS with optional strong-Wolfe line search
    (ref optim/LBFGS.scala).  Host-driven loop; each feval is one device
    computation on the flattened parameter vector."""

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tol_fun: float = 1e-5, tol_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: bool = False):
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 1.25
        self.tol_fun = tol_fun
        self.tol_x = tol_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search
        self._state: Optional[dict] = None

    def clear_history(self):
        self._state = None

    def optimize(self, feval: Callable, x, epoch: int = 1):
        """Run up to max_iter LBFGS iterations from x (one reference
        `optimize` call = one outer loop).  Returns (x, loss_history)."""
        x = jnp.asarray(x)
        st = self._state if self._state is not None else {
            "old_dirs": [], "old_steps": [], "prev_g": None, "prev_loss": None,
            "d": None, "t": None, "hdiag": 1.0, "func_evals": 0}
        f, g = feval(x)
        f_hist = [float(f)]
        st["func_evals"] += 1
        abs_grad_sum = float(jnp.sum(jnp.abs(g)))
        if abs_grad_sum <= self.tol_fun:
            self._state = st
            return x, f_hist

        for n_iter in range(self.max_iter):
            if st["prev_g"] is None:
                d = -g
                st["hdiag"] = 1.0
            else:
                y = g - st["prev_g"]
                s = st["d"] * st["t"]
                ys = float(jnp.vdot(y, s))
                if ys > 1e-10:
                    if len(st["old_dirs"]) == self.n_correction:
                        st["old_dirs"].pop(0)
                        st["old_steps"].pop(0)
                    st["old_dirs"].append(s)
                    st["old_steps"].append(y)
                    st["hdiag"] = ys / float(jnp.vdot(y, y))
                # two-loop recursion
                k = len(st["old_dirs"])
                ro = [1.0 / float(jnp.vdot(st["old_steps"][i], st["old_dirs"][i]))
                      for i in range(k)]
                al = [0.0] * k
                q = -g
                for i in range(k - 1, -1, -1):
                    al[i] = float(jnp.vdot(st["old_dirs"][i], q)) * ro[i]
                    q = q - al[i] * st["old_steps"][i]
                d = q * st["hdiag"]
                for i in range(k):
                    be = float(jnp.vdot(st["old_steps"][i], d)) * ro[i]
                    d = d + st["old_dirs"][i] * (al[i] - be)
            st["prev_g"] = g
            gtd = float(jnp.vdot(g, d))
            if gtd > -self.tol_x:
                break
            if n_iter == 0 and st["prev_loss"] is None:
                t = min(1.0, 1.0 / max(abs_grad_sum, 1e-12)) * self.learning_rate
            else:
                t = self.learning_rate
            if self.line_search:
                f, g, t, evals = ls_wolfe(feval, x, t, d, float(f), g, gtd)
                x = x + t * d
                st["func_evals"] += evals
            else:
                x = x + t * d
                f, g = feval(x)
                st["func_evals"] += 1
            st["d"], st["t"] = d, t
            f_hist.append(float(f))
            abs_grad_sum = float(jnp.sum(jnp.abs(g)))
            if abs_grad_sum <= self.tol_fun:
                break
            if float(jnp.max(jnp.abs(d * t))) <= self.tol_x:
                break
            if st["prev_loss"] is not None and \
                    abs(f_hist[-1] - f_hist[-2]) < self.tol_fun:
                break
            if st["func_evals"] >= self.max_eval:
                break
        st["prev_loss"] = f_hist[-1]
        self._state = st
        return x, f_hist
