"""Named metric counters (ref optim/Metrics.scala:24-112).

The reference distinguishes local AtomicDouble counters from Spark
accumulators aggregated on the driver; here a metric is local to the
process, and in a multi-host job each host reports its own (cross-host
aggregation of *training* statistics rides the same collectives as
gradients, so there is no separate accumulator RPC to build).
"""
from __future__ import annotations

import threading


class Metrics:
    def __init__(self):
        self._values: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def set(self, name: str, value: float, parallel: int = 1) -> None:
        with self._lock:
            self._values[name] = float(value)
            self._counts[name] = parallel

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0.0) + float(value)
            self._counts.setdefault(name, 1)

    def get(self, name: str) -> tuple[float, int]:
        with self._lock:
            return self._values.get(name, 0.0), self._counts.get(name, 1)

    def summary(self, unit_scale: float = 1.0) -> str:
        """Summary in seconds.  Values here are recorded in seconds already
        (the reference stores nanoseconds and divides by 1e9,
        optim/Metrics.scala:96); pass unit_scale for other units."""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for name, v in self._values.items():
                n = self._counts.get(name, 1)
                lines.append(f"{name} : {v / unit_scale / max(n, 1)} s")
            lines.append("=====================================")
            return "\n".join(lines)
