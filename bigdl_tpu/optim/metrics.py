"""Named metric counters (ref optim/Metrics.scala:24-112).

The reference distinguishes local AtomicDouble counters from Spark
accumulators aggregated on the driver; here a metric is local to the
process, and ``aggregate()`` plays the Spark-accumulator role in a
multi-host job: every process contributes its counters and receives the
cross-process mean (a host-side allgather over DCN — cheap, called at
summary points only, and collective: every process must call it).
"""
from __future__ import annotations

import threading


class Metrics:
    def __init__(self):
        self._values: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def aggregate(self) -> "Metrics":
        """Cross-process mean of every counter (ref Metrics.scala:24-112:
        Spark accumulators summed on the driver; here each process gets
        the fleet view).  COLLECTIVE — in a multi-process job all
        processes must call it together.  No-op single-process."""
        import jax
        if jax.process_count() <= 1:
            return self
        import numpy as np
        from jax.experimental import multihost_utils
        with self._lock:
            names = sorted(self._values)
            local = np.array([self._values[n] for n in names], np.float64)
        gathered = np.asarray(multihost_utils.process_allgather(local))
        mean = gathered.mean(axis=0) if gathered.ndim > 1 else gathered
        out = Metrics()
        with self._lock:
            for i, n in enumerate(names):
                out._values[n] = float(mean[i])
                out._counts[n] = self._counts.get(n, 1)
        return out

    def set(self, name: str, value: float, parallel: int = 1) -> None:
        with self._lock:
            self._values[name] = float(value)
            self._counts[name] = parallel

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0.0) + float(value)
            self._counts.setdefault(name, 1)

    def get(self, name: str) -> tuple[float, int]:
        with self._lock:
            return self._values.get(name, 0.0), self._counts.get(name, 1)

    def summary(self, unit_scale: float = 1.0) -> str:
        """Summary in seconds.  Values here are recorded in seconds already
        (the reference stores nanoseconds and divides by 1e9,
        optim/Metrics.scala:96); pass unit_scale for other units."""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for name, v in self._values.items():
                n = self._counts.get(name, 1)
                lines.append(f"{name} : {v / unit_scale / max(n, 1)} s")
            lines.append("=====================================")
            return "\n".join(lines)
