"""Named metric counters (ref optim/Metrics.scala:24-112).

The reference distinguishes local AtomicDouble counters from Spark
accumulators aggregated on the driver; here a metric is local to the
process, and ``aggregate()`` plays the Spark-accumulator role in a
multi-host job: every process contributes its counters and receives the
cross-process mean (a host-side allgather over DCN — cheap, called at
summary points only, and collective: every process must call it).

Storage is :class:`bigdl_tpu.obs.registry.Counter` objects, so an
optimizer's phase counters can be published into the process-wide
``obs`` registry (``publish_to``) and ride the same snapshot/tfevents
export path as the serving metrics — the reference's "driver
accumulator" view, without a driver.
"""
from __future__ import annotations

import threading

from bigdl_tpu.obs.registry import Counter, MetricRegistry


class Metrics:
    def __init__(self):
        self._counters: dict[str, Counter] = {}
        self._lock = threading.Lock()
        self._published: list[tuple[MetricRegistry, str]] = []

    # -- registry wiring ------------------------------------------------ #
    def publish_to(self, registry: MetricRegistry,
                   prefix: str = "train/") -> "Metrics":
        """Expose every counter (current and future) in ``registry``
        under ``prefix`` — live objects, not copies; latest publisher
        wins the names (replace semantics)."""
        with self._lock:
            self._published.append((registry, prefix))
            for name, c in self._counters.items():
                registry.register(prefix + name, c, replace=True)
        return self

    def _counter(self, name: str, unit: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = Counter(unit=unit)
            self._counters[name] = c
            for registry, prefix in self._published:
                registry.register(prefix + name, c, replace=True)
        return c

    # -- recording ------------------------------------------------------ #
    def set(self, name: str, value: float, parallel: int = 1,
            unit: str = "s") -> None:
        with self._lock:
            self._counter(name, unit).set(float(value), parallel)

    def add(self, name: str, value: float, unit: str = "s") -> None:
        with self._lock:
            self._counter(name, unit).add(float(value))

    def get(self, name: str) -> tuple[float, int]:
        with self._lock:
            c = self._counters.get(name)
            return c.get() if c is not None else (0.0, 1)

    # -- aggregation / reporting ---------------------------------------- #
    def aggregate(self) -> "Metrics":
        """Cross-process mean of every counter (ref Metrics.scala:24-112:
        Spark accumulators summed on the driver; here each process gets
        the fleet view).  COLLECTIVE — in a multi-process job all
        processes must call it together.  No-op single-process."""
        import jax
        if jax.process_count() <= 1:
            return self
        import numpy as np
        from jax.experimental import multihost_utils
        with self._lock:
            names = sorted(self._counters)
            local = np.array([self._counters[n].value for n in names],
                             np.float64)
        gathered = np.asarray(multihost_utils.process_allgather(local))
        mean = gathered.mean(axis=0) if gathered.ndim > 1 else gathered
        out = Metrics()
        with self._lock:
            for i, n in enumerate(names):
                src = self._counters[n]
                out.set(n, float(mean[i]), parallel=src.n, unit=src.unit)
        return out

    def summary(self, unit_scale: float = 1.0) -> str:
        """Per-phase means.  Time counters (``unit="s"``, the default —
        values recorded in seconds; the reference stores nanoseconds and
        divides by 1e9, optim/Metrics.scala:96) are scaled by
        ``unit_scale`` and labeled `` s``; counters recorded with any
        other unit print their raw value — a batch count must not be
        stamped as seconds — with their own unit suffix when one was
        given."""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for name, c in self._counters.items():
                v, n = c.get()
                mean = v / max(n, 1)
                if c.unit == "s":
                    lines.append(f"{name} : {mean / unit_scale} s")
                elif c.unit:
                    lines.append(f"{name} : {mean} {c.unit}")
                else:
                    lines.append(f"{name} : {mean}")
            lines.append("=====================================")
            return "\n".join(lines)
