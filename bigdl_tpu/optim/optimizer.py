"""Optimizer builder + single-chip training loop (ref optim/Optimizer.scala:
29-201, optim/LocalOptimizer.scala:76-173) and standalone validators
(ref optim/Validator.scala, LocalValidator.scala).

The reference's LocalOptimizer clones `coreNumber` thread-replicas that
alias one flattened weight storage and sum gradients slice-parallel.  On a
TPU chip none of that exists: ONE jitted train step (forward, backward,
optimizer update fused into a single XLA program, parameters donated so
updates are in-place in HBM) is the whole hot loop.  The distributed loop
lives in bigdl_tpu.parallel.distri_optimizer.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import LBFGS, OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod

log = logging.getLogger("bigdl_tpu.optim")


_accum_fallback_warned: set = set()  # (batch_desc, dim, accum) already traced


def accumulated_value_and_grad(loss_fn, accum, params, buffers, data,
                               labels, rng, batch_desc="batch"):
    """``(loss, new_buffers), grads`` for one batch, optionally split
    into ``accum`` equal micro-batches scanned inside the step.

    The mean of the micro-batch gradients equals the full-batch
    gradient for mean-reduced criteria, while activation memory is that
    of ONE micro-batch — the scan re-materializes activations per
    micro-step.  Buffers (BN stats, MoE aux) thread through the scan
    carry, i.e. sequential small-batch semantics.  Used by both the
    local and the distributed step builders; inside shard_map the
    parameter all-gather and gradient reduce-scatter still run once per
    EFFECTIVE batch (any collectives the model's own loss carries —
    e.g. the MoE balance-term pmean — do repeat per micro-batch).
    An INDIVISIBLE batch (the ragged tail a drop-last=False batcher
    emits at epoch end) falls back to one unaccumulated step — the
    same true mean gradient, briefly at full-batch activation memory;
    a tail is smaller than the steady batch, so the peak does not grow.
    Misconfiguration (steady batch itself indivisible) is caught
    host-side by the optimize loops before any work runs; ``batch_desc``
    names the axis there (under shard_map the constraint binds the
    per-device shard, not the global batch)."""
    vag = jax.value_and_grad(loss_fn, has_aux=True)
    n = jnp.asarray(data).shape[0]
    if accum <= 1 or n % accum:
        if accum > 1 and (batch_desc, n, accum) not in _accum_fallback_warned:
            # the shape is static under jit, so this fires at TRACE time —
            # once per distinct shape, not per step.  An epoch tail is
            # expected; an irregular batch >= the steady size from a custom
            # pipeline would otherwise silently run at full-batch
            # activation memory.
            _accum_fallback_warned.add((batch_desc, n, accum))
            log.warning(
                "gradient accumulation: %s dim %d is not divisible by "
                "accum=%d — running this shape as ONE unaccumulated step "
                "(full-batch activation memory)", batch_desc, n, accum)
        return vag(params, buffers, data, labels, rng)

    def resh(x):
        x = jnp.asarray(x)
        return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

    data_m, labels_m = resh(data), resh(labels)
    rngs = jax.random.split(rng, accum)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

    def body(carry, xs):
        g_acc, bufs, l_acc = carry
        d, l, r = xs
        (loss, nb), g = vag(params, bufs, d, l, r)
        g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
        return (g_acc, nb, l_acc + loss.astype(jnp.float32)), None

    (g_sum, new_buffers, loss_sum), _ = jax.lax.scan(
        body, (zeros, buffers, jnp.zeros((), jnp.float32)),
        (data_m, labels_m, rngs))
    inv = 1.0 / accum
    grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
    return (loss_sum * inv, new_buffers), grads


class Optimizer:
    """Builder API (ref optim/Optimizer.scala:29-144).  The factory
    dispatches Local vs Distri on the dataset type, like the reference's
    apply (Optimizer.scala:166-201)."""

    def __init__(self, model: Module, dataset: AbstractDataSet, criterion: Criterion):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = Trigger.max_iteration(100)
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Sequence[ValidationMethod] = ()
        self.train_summary = None
        self.validation_summary = None
        self.state: dict = {}
        # phase counters, published live into the process-wide obs
        # registry (one snapshot path with the serving metrics)
        from bigdl_tpu.obs import get_registry
        self.metrics = Metrics().publish_to(get_registry())
        self.compute_dtype = None  # e.g. jnp.bfloat16; None = full f32
        self.grad_accum = 1  # micro-batches per step (set_gradient_accumulation)

    # -- builder methods (reference names, pythonized) ------------------- #
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_gradient_accumulation(self, n_micro: int) -> "Optimizer":
        """Split every batch into ``n_micro`` equal micro-batches inside
        the jitted step (``lax.scan``), accumulating gradients before
        the single optimizer update (and, distributed, the single
        collective cycle).  Activation memory scales with the
        MICRO-batch, so effective batches far beyond HBM fit — a
        capability the reference's executor model has no analog for.
        Losses/gradients match the full-batch step exactly for
        mean-reduced criteria; batch-statistics layers (BatchNorm) see
        micro-batch statistics, matching sequential small-batch
        semantics.  ``n_micro`` must divide the batch each step body
        sees — the full batch locally, the PER-DEVICE shard
        (global batch / devices) under ``DistriOptimizer``."""
        n_micro = int(n_micro)
        if n_micro < 1:
            raise ValueError(f"n_micro must be >= 1, got {n_micro}")
        self.grad_accum = n_micro
        return self

    def set_compute_dtype(self, dtype) -> "Optimizer":
        """Mixed precision: run forward/backward with float params cast to
        ``dtype`` (bf16 feeds the MXU at full rate) while the master
        weights and optimizer state stay f32 — the TPU rendering of the
        reference's fp16-transport / f32-state split
        (parameters/AllReduceParameter.scala).  Gradients arrive f32
        (the cast's own vjp does the up-cast)."""
        self.compute_dtype = dtype
        return self

    def _cast_for_compute(self, params):
        # input batches are deliberately NOT cast alongside the params:
        # the MXU-feeding layers align their input to the weight dtype
        # themselves (nn/_util.py match_compute_dtype) — a blanket
        # float-input cast would silently corrupt float-encoded
        # LookupTable/embedding ids above bf16's exact-integer range
        # (dataset/text.py emits 1-based ids as float32).
        if self.compute_dtype is None:
            return params
        from bigdl_tpu.nn._util import cast_f32_leaves
        return cast_f32_leaves(params, self.compute_dtype)

    def _outputs_to_f32(self, out):
        """Loss inputs in f32 regardless of the compute dtype; identity in
        the pure-f32 path (no traversal added to the traced graph)."""
        if self.compute_dtype is None:
            return out
        return jax.tree_util.tree_map(
            lambda o: jnp.asarray(o).astype(jnp.float32), out)

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float) -> "Optimizer":
        """Clip every gradient element into [min, max] inside the jitted
        step (the elementwise clipping later reference versions pair with
        the norm clip below; jnp.clip fuses into the update)."""
        assert min_value < max_value
        self._clip_const = (float(min_value), float(max_value))
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float) -> "Optimizer":
        """Scale the WHOLE gradient tree so its global L2 norm is at most
        ``clip_norm`` (torch clip_grad_norm_ semantics — one norm across
        all leaves, not per-leaf).  Applied after any constant clip,
        before the optimizer update; in the distributed path it runs on
        each device's reduce-scattered shard with a psum'd global norm."""
        self._clip_l2 = float(clip_norm)
        return self

    def _clip_gradients(self, grads, psum_axis: Optional[str] = None):
        """Pure, jit-composable; ``psum_axis`` makes the L2 norm global
        across a mesh axis when grads are sharded slices."""
        const = getattr(self, "_clip_const", None)
        l2 = getattr(self, "_clip_l2", None)
        if const is not None:
            lo, hi = const
            grads = jax.tree_util.tree_map(
                lambda g: jnp.clip(g, lo, hi), grads)
        if l2 is not None:
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree_util.tree_leaves(grads))
            if psum_axis is not None:
                from jax import lax
                sq = lax.psum(sq, psum_axis)
            norm = jnp.sqrt(sq)
            scale = jnp.minimum(1.0, l2 / jnp.maximum(norm, 1e-12))
            grads = jax.tree_util.tree_map(
                lambda g: (g * scale).astype(g.dtype), grads)
        return grads

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_state(self, state: dict) -> "Optimizer":
        self.state = dict(state)
        return self

    def set_checkpoint(self, path: str, trigger: Trigger) -> "Optimizer":
        """Checkpoint dir may be local or remote (gs://, memory://, ...);
        local dirs are created, remote schemes are flat keyspaces."""
        from bigdl_tpu.utils import fs as _fs
        filesystem, rest = _fs.get_filesystem(path)
        if isinstance(filesystem, _fs.LocalFileSystem):
            if os.path.exists(rest) and not os.path.isdir(rest):
                raise ValueError(f"checkpoint path {path} is not a directory")
            filesystem.makedirs(rest)
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence[ValidationMethod]) -> "Optimizer":
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = methods
        self._validator = None  # rebuilt around the new dataset
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_validation_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    @staticmethod
    def create(model: Module, dataset: AbstractDataSet, criterion: Criterion) -> "Optimizer":
        from bigdl_tpu.dataset.dataset import DistributedDataSet, TransformedDataSet
        src = dataset
        while isinstance(src, TransformedDataSet):
            src = src.source
        if isinstance(src, DistributedDataSet):
            try:
                from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "distributed training requires bigdl_tpu.parallel") from e
            return DistriOptimizer(model, dataset, criterion)
        return LocalOptimizer(model, dataset, criterion)

    # -- shared loop plumbing ------------------------------------------- #
    def _init_driver_state(self):
        self.state.setdefault("epoch", 1)
        self.state.setdefault("neval", 1)
        self.state.setdefault("records_processed", 0)
        self.state["epoch_finished"] = False

    def _record_train_summary(self, loss_val: float, throughput: float,
                              epoch: Optional[int] = None,
                              iteration: Optional[int] = None,
                              record_params: Optional[bool] = None):
        """Write trigger-gated scalars (+ optional Parameters histograms) —
        ref DistriOptimizer.scala:358-388 / utils/Summary.scala:121-146.
        Plain summaries (no triggers attr) get Loss/Throughput every step.
        Callers must publish current weights to self.model.params first.
        ``epoch``/``iteration`` identify the step that actually ran (driver
        state may have rolled over; opt-state iteration may differ from
        neval after a resume).  ``record_params`` lets the caller poll the
        Parameters trigger itself (it must be polled exactly once)."""
        ts = self.train_summary
        if ts is None:
            return
        step = self.state["neval"]
        if epoch is None:
            epoch = self.state["epoch"]
        if iteration is None:
            iteration = step - 1
        gated = hasattr(ts, "should_record")
        if not gated or ts.should_record("Loss", self.state):
            ts.add_scalar("Loss", loss_val, step)
        if not gated or ts.should_record("Throughput", self.state):
            ts.add_scalar("Throughput", throughput, step)
        if gated and ts.should_record("LearningRate", self.state):
            m = self.optim_method
            if hasattr(m, "current_rate"):
                lr = float(m.current_rate({"iteration": iteration}, epoch))
            else:
                lr = float(getattr(m, "learning_rate", 0.0))
            ts.add_scalar("LearningRate", lr, step)
        if record_params is None:
            record_params = gated and ts.should_record("Parameters", self.state)
        if record_params:
            flat = jax.tree_util.tree_flatten_with_path(self.model.params)[0]
            for path, leaf in flat:
                name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                                for p in path)
                ts.add_histogram(name, jax.device_get(leaf), step)

    def _maybe_validate(self):
        if (self.validation_trigger is not None and self.validation_dataset is not None
                and self.validation_trigger(self.state)):
            return self._run_validation()
        return None

    def _run_validation(self):
        results = self._validate()
        for method, result in results:
            log.info("%s is %s", method, result)
            if self.validation_summary is not None:
                value = result.result()[0]
                self.validation_summary.add_scalar(
                    str(method), value, self.state["neval"] - 1)
        return results

    def _validate(self):
        raise NotImplementedError

    def _maybe_checkpoint(self) -> bool:
        if (self.checkpoint_trigger is not None and self.checkpoint_path is not None
                and self.checkpoint_trigger(self.state)):
            self._checkpoint()
            return True
        return False

    def handle_preemption(self, signals=None) -> "Optimizer":
        """Graceful-preemption contract for preemptible/spot TPU pods: on
        SIGTERM (the eviction notice), finish the in-flight iteration,
        write a final checkpoint when a checkpoint path is configured, and
        return from ``optimize`` cleanly so ``--resume`` continues the run
        on the replacement machine.  This is the SPMD rendering of the
        reference's failure-recovery story (Spark task retries,
        SURVEY.md §5.3) — under lockstep SPMD there is no per-task retry,
        so checkpoint-and-restart is the recovery path and the eviction
        signal is the failure detector."""
        import signal as _signal

        if signals is None:
            signals = (_signal.SIGTERM,)
        self._preempted = False

        def _handler(signum, frame):
            self._preempted = True
            log.warning("received signal %s: will checkpoint and stop "
                        "after the current iteration", signum)

        for s in signals:
            _signal.signal(s, _handler)
        return self

    def _check_preemption(self) -> bool:
        """True -> the loop should checkpoint (caller publishes weights
        first where needed) and break."""
        return bool(getattr(self, "_preempted", False))

    def _checkpoint(self):
        """Write model.<neval> + state.<neval> (ref Optimizer.saveModel/
        saveState, DistriOptimizer.scala:334-356).  Paths flow through the
        fs layer, so gs://... checkpoint dirs work from pod workers (the
        reference's hdfs: support, utils/File.scala:62-122)."""
        from bigdl_tpu.utils import file_io, fs
        if jax.process_index() != 0:
            # every process publishes (the gathers above are collective),
            # but only process 0 touches the filesystem — the reference's
            # driver-writes-the-checkpoint contract
            # (DistriOptimizer.scala:334-356) without N hosts racing on
            # one gs:// path
            return
        n = self.state["neval"] - 1
        self.model.save(fs.join(self.checkpoint_path, f"model.{n}"),
                        overwrite=True)
        opt_state = getattr(self.optim_method, "_state", None)
        host_state = dict(self.state)
        file_io.save({"driver_state": host_state,
                      "optim_state": jax.tree_util.tree_map(
                          np.asarray, opt_state) if opt_state is not None else None,
                      # recorded so resume can refuse a mismatched method
                      # (an Adam m/v tree fed to SGD would be silently
                      # dropped; the reverse KeyErrors inside the step)
                      "optim_method": type(self.optim_method).__name__},
                     fs.join(self.checkpoint_path, f"state.{n}"), overwrite=True)
        log.info("checkpoint written at iteration %d", n)

    # -- resilience: emergency checkpoint + resume ---------------------- #
    def resume_from(self, path: str) -> "Optimizer":
        """Auto-resume: load the newest ``model.<n>``/``state.<n>`` pair
        under ``path`` (the directory ``set_checkpoint`` writes to —
        including its emergency checkpoints) into this optimizer, so the
        next ``optimize()`` continues the interrupted run: step/epoch
        counters, optimizer moments, LR-schedule position, and mid-epoch
        data progress (``records_processed``) all restore, losing at
        most the one step that was in flight when the run died.

        A missing/empty directory is a cold start, not an error — one
        code path covers first launch and every restart after."""
        from bigdl_tpu.utils import file_io
        found = file_io.latest_checkpoint(path)
        if not found:
            log.info("resume_from(%s): no checkpoint pair found — "
                     "cold start", path)
            return self
        model_path, state_path, n = found
        from bigdl_tpu.models.utils import restore_optim_state
        loaded = Module.load(model_path)
        self.model._built()
        self.model.params = loaded.params
        self.model.buffers = loaded.buffers
        restore_optim_state(self, self.optim_method, state_path)
        from bigdl_tpu.obs import get_registry
        get_registry().counter("resilience/resumes").add(1)
        log.warning("resumed from %s (iteration %d, epoch %s, %s records "
                    "into the epoch)", path, n, self.state.get("epoch"),
                    self.state.get("records_processed", 0))
        return self

    def _publish_for_checkpoint(self) -> None:
        """Make ``self.model.params``/``optim_method._state`` current
        before an emergency checkpoint.  No-op locally (the loop
        publishes every iteration); DistriOptimizer overrides with its
        guarded device->host gather."""

    def _emergency_checkpoint(self, reason: str = "") -> bool:
        """Best-effort checkpoint of the LAST COMPLETED step, taken on
        the failure path — so a crashed run restarts from
        ``resume_from`` having lost at most the step that was in
        flight.  Never raises: it runs inside exception handlers, and a
        checkpoint failure must not mask the original error."""
        if self.checkpoint_path is None:
            log.warning("cannot write emergency checkpoint (%s): no "
                        "checkpoint path configured — call "
                        "set_checkpoint first", reason)
            return False
        try:
            self._publish_for_checkpoint()
        except Exception:
            log.warning("publish before emergency checkpoint failed "
                        "(backend gone?); writing last published host "
                        "state instead", exc_info=True)
        try:
            self._checkpoint()
        except Exception:
            log.exception("emergency checkpoint failed (%s)", reason)
            return False
        from bigdl_tpu.obs import get_registry
        get_registry().counter("resilience/emergency_checkpoints").add(1)
        log.warning("emergency checkpoint written at iteration %d (%s)",
                    self.state["neval"] - 1, reason)
        return True

    def _arm_stall_checkpoint(self, watchdog) -> None:
        """Escalation chain: when the StallWatchdog fires (a wedged
        device call), request an emergency checkpoint — taken by the
        loop at the next completed iteration, where the published state
        is consistent (the stalled step itself may still be running; a
        checkpoint from the watchdog thread would race it)."""
        self._stall_ckpt_requested = False
        if watchdog is None:
            return

        def _on_stall(event):
            self._stall_ckpt_requested = True

        watchdog.on_stall = _on_stall

    def _maybe_stall_checkpoint(self) -> None:
        if getattr(self, "_stall_ckpt_requested", False):
            self._stall_ckpt_requested = False
            self._emergency_checkpoint(
                "stall watchdog escalation: checkpointing at the next "
                "completed iteration")

    def _fast_forward_data(self, data_iter, records_into_epoch: int,
                           scale: int = 1) -> None:
        """Re-join an interrupted epoch's data order after resume_from:
        replay the rollover shuffles the original run performed (the
        dataset draws permutations from a seeded stream, so replay is
        exact on a freshly constructed dataset), then consume the
        records the interrupted epoch already trained on.  A cold start
        (epoch 1, 0 records in) is a no-op.  ``scale`` converts a local
        batch to its global record count (process count, distributed)."""
        for _ in range(int(self.state.get("epoch", 1)) - 1):
            self.dataset.shuffle()
        skipped = 0
        while skipped < records_into_epoch:
            batch = next(data_iter)
            skipped += int(np.asarray(batch.data).shape[0]) * int(scale)
        if skipped:
            log.info("resume fast-forward: skipped %d already-trained "
                     "records to rejoin the epoch mid-stream", skipped)


class LocalOptimizer(Optimizer):
    """Single-process training loop (ref optim/LocalOptimizer.scala:76-173).

    The dataset must yield MiniBatch (data, labels); one jitted step does
    forward+backward+update with donated params for in-HBM updates.
    """

    def __init__(self, model: Module, dataset: AbstractDataSet, criterion: Criterion):
        super().__init__(model, dataset, criterion)
        self._step_fn = None

    def _build_step(self):
        model, criterion, method = self.model, self.criterion, self.optim_method
        cast = self._cast_for_compute

        def loss_fn(params, buffers, data, labels, rng):
            out, new_buffers = model.apply(cast(params), data, buffers=buffers,
                                           training=True, rng=rng)
            loss = criterion.loss(self._outputs_to_f32(out), labels)
            # reserved buffers key: model-declared differentiable
            # auxiliary terms (e.g. MoE load balancing) join the loss
            # INSIDE the differentiated step, pre-scaled by the model
            if isinstance(new_buffers, dict) and "aux_loss" in new_buffers:
                loss = loss + new_buffers["aux_loss"]
            return loss, new_buffers

        accum = self.grad_accum

        def step(params, buffers, opt_state, data, labels, rng, epoch):
            (loss, new_buffers), grads = accumulated_value_and_grad(
                loss_fn, accum, params, buffers, data, labels, rng)
            grads = self._clip_gradients(grads)
            new_params, new_opt_state = method.update(grads, opt_state, params,
                                                      epoch=epoch)
            return new_params, new_buffers, new_opt_state, loss

        return jax.jit(step, donate_argnums=(0, 2))

    def optimize(self) -> Module:
        self._init_driver_state()
        self.model._built()
        params, buffers = self.model.params, self.model.buffers
        # a restored snapshot (restore_optim_state) takes priority over a
        # fresh init: resume must continue Adam m/v, SGD momentum, and the
        # iteration counter every LR schedule reads — a silent re-init
        # would restart the schedule and re-warm the moments
        restored = getattr(self.optim_method, "_state", None)
        opt_state = restored if restored else \
            self.optim_method.init_state(params)
        if isinstance(self.optim_method, LBFGS):
            return self._optimize_lbfgs()
        self._step_fn = self._build_step()
        rng = jax.random.PRNGKey(self.state.get("seed", 0))
        dataset_size = self.dataset.size()
        self.dataset.shuffle()
        data_iter = self.dataset.data(train=True)

        records_this_epoch = self.state.get("records_processed", 0)
        self._fast_forward_data(data_iter, records_this_epoch)
        wall0 = time.perf_counter()
        # host/device overlap: jit dispatch is async, so the expensive
        # host work for the NEXT batch (decode/augment/stack) runs while
        # the device executes the current step; the loss fetch below is
        # the only sync point.  Without this the loop serializes host
        # and device time (the chip idles during every batch prep).
        overlap = os.environ.get("BIGDL_TPU_PREFETCH_OVERLAP", "1") == "1"
        next_batch = None
        accum_checked = False
        # step-cadence stall detection + escalation: a wedged device
        # call fires diagnostics, and the escalation hook checkpoints
        # at the next completed iteration (see _arm_stall_checkpoint)
        from bigdl_tpu.obs import (env_watchdog_enabled,
                                   env_watchdog_kwargs, shared_watchdog)
        watchdog = None
        if env_watchdog_enabled():
            watchdog = shared_watchdog("train_step")
            watchdog.reset(**env_watchdog_kwargs())
        self._arm_stall_checkpoint(watchdog)
        try:
            self._optimize_loop(params, buffers, opt_state, rng, data_iter,
                                dataset_size, records_this_epoch, overlap,
                                next_batch, accum_checked, watchdog, wall0)
        except Exception as e:
            # crash resilience: persist the last completed step before
            # surfacing the failure, so resume_from loses at most the
            # in-flight step (the JAX rendering of the reference's
            # recompute-from-lineage story — here state is explicit)
            self._emergency_checkpoint(f"training loop failed: {e!r}")
            raise
        finally:
            if watchdog is not None:
                watchdog.on_stall = None
        return self.model

    def _optimize_loop(self, params, buffers, opt_state, rng, data_iter,
                       dataset_size, records_this_epoch, overlap,
                       next_batch, accum_checked, watchdog, wall0):
        while not self.end_when(self.state):
            self.state["epoch_finished"] = False
            batch = next_batch if next_batch is not None else next(data_iter)
            next_batch = None
            if not accum_checked:
                # the FIRST batch is the steady size: catching an
                # indivisible configuration here (before any compile)
                # beats silently never accumulating; later ragged tail
                # batches fall back to one unaccumulated step by design
                accum_checked = True
                if (self.grad_accum > 1
                        and batch.data.shape[0] % self.grad_accum):
                    raise ValueError(
                        f"set_gradient_accumulation({self.grad_accum}) "
                        f"needs the batch size ({batch.data.shape[0]}) "
                        f"divisible by n_micro")
            rng, sub = jax.random.split(rng)
            t0 = time.perf_counter()
            if watchdog is not None:
                watchdog.step_started()
            params, buffers, opt_state, loss = self._step_fn(
                params, buffers, opt_state,
                jnp.asarray(batch.data), jnp.asarray(batch.labels), sub,
                self.state["epoch"])
            bs_now = batch.data.shape[0]
            if overlap and records_this_epoch + bs_now < dataset_size:
                # fetched one step ahead so host decode hides under the
                # device step.  NOT at an epoch boundary: the prefetch
                # would wrap the infinite iterator onto the OLD
                # permutation before the rollover shuffle() below runs,
                # silently replaying last epoch's record order — one
                # serialized iteration per epoch is the correct price
                next_batch = next(data_iter)
            loss_val = float(loss)  # syncs; also what the reference logs
            if watchdog is not None:
                watchdog.step_finished()
            dt = time.perf_counter() - t0
            bs = batch.data.shape[0]
            records_this_epoch += bs
            self.metrics.add("computing time", dt)
            self.state["loss"] = loss_val
            self.state["throughput"] = bs / dt
            log.info("Epoch %d iteration %d: loss %.6f, throughput %.1f records/s",
                     self.state["epoch"], self.state["neval"], loss_val, bs / dt)
            epoch_of_step = self.state["epoch"]
            if records_this_epoch >= dataset_size:  # epoch rollover
                self.state["epoch"] += 1
                self.state["epoch_finished"] = True
                records_this_epoch = 0
                # reshuffle WITHOUT rebinding the iterator: the infinite
                # train iterator picks up the new permutation on its next
                # pass, and any Prefetcher threads in the chain stay live
                # (rebinding would leak one blocked worker per epoch)
                self.dataset.shuffle()
            # kept current EVERY iteration (not just post-loop) so any
            # checkpoint — scheduled or emergency — records how far into
            # the epoch training got, and resume_from can fast-forward
            # the data stream to the exact record
            self.state["records_processed"] = records_this_epoch
            # publish params so summaries/validation/checkpoint see current
            # weights (and never the buffers donated into the next step)
            self.model.params, self.model.buffers = params, buffers
            self.optim_method._state = opt_state
            # the step already advanced opt_state's counter, so the lr it
            # used corresponds to iteration-1
            it = (int(opt_state["iteration"]) - 1
                  if isinstance(opt_state, dict) and "iteration" in opt_state
                  else None)
            self._record_train_summary(loss_val, bs / dt, epoch=epoch_of_step,
                                       iteration=it)
            self.state["neval"] += 1
            self._maybe_validate()
            wrote_ckpt = self._maybe_checkpoint()
            if not wrote_ckpt:
                self._maybe_stall_checkpoint()
            if self._check_preemption():
                if self.checkpoint_path is not None and not wrote_ckpt:
                    self._checkpoint()
                log.warning("stopping on preemption at iteration %d",
                            self.state["neval"] - 1)
                break
        self.state["records_processed"] = records_this_epoch
        log.info("training finished in %.1fs", time.perf_counter() - wall0)
        log.info("phase breakdown: %s", self.metrics.summary())
        self.model.params, self.model.buffers = params, buffers
        return self.model

    def _optimize_lbfgs(self) -> Module:
        """Full-batch path for LBFGS (the reference drives LBFGS through the
        same feval machinery, optim/LocalOptimizer + LBFGS.scala)."""
        from jax.flatten_util import ravel_pytree
        model, criterion = self.model, self.criterion
        flat0, unravel = ravel_pytree(model.params)
        buffers = model.buffers

        batch = next(self.dataset.data(train=True))
        data, labels = jnp.asarray(batch.data), jnp.asarray(batch.labels)

        @jax.jit
        def val_and_grad(flat):
            def loss_fn(fl):
                out, _ = model.apply(self._cast_for_compute(unravel(fl)),
                                     data, buffers=buffers, training=True)
                return criterion.loss(self._outputs_to_f32(out), labels)
            return jax.value_and_grad(loss_fn)(flat)

        if (getattr(self, "_clip_const", None) is not None
                or getattr(self, "_clip_l2", None) is not None):
            # a clipped gradient is inconsistent with the loss the Wolfe
            # line search evaluates (Armijo/curvature tests use g·d) and
            # corrupts the y = g_new - g_prev curvature pairs; refusing
            # loudly beats silently degrading the inverse-Hessian
            raise ValueError(
                "gradient clipping is incompatible with LBFGS (the line "
                "search and curvature pairs need the true gradient) — "
                "remove the clipping or use SGD/Adam")
        if self.grad_accum > 1:
            # the line search re-evaluates the full-batch loss at trial
            # points; silently ignoring the accumulation request (and
            # its memory expectation) would be worse than refusing
            raise ValueError(
                "set_gradient_accumulation is not supported with LBFGS "
                "(the strong-Wolfe line search evaluates the full batch) "
                "— use SGD/Adam, or drop the accumulation")

        def feval(flat):
            v, g = val_and_grad(flat)
            return float(v), g

        flat = flat0
        dataset_size = self.dataset.size()
        records_this_epoch = 0
        batch_records = int(batch.data.shape[0])
        while not self.end_when(self.state):
            self.state["epoch_finished"] = False
            flat, hist = self.optim_method.optimize(feval, flat)
            self.state["loss"] = hist[-1]
            log.info("LBFGS iteration %d: loss %.6f", self.state["neval"], hist[-1])
            self.state["neval"] += 1
            records_this_epoch += batch_records
            if records_this_epoch >= dataset_size:
                self.state["epoch"] += 1
                self.state["epoch_finished"] = True
                records_this_epoch = 0
            model.params = unravel(flat)
            self._maybe_validate()
            wrote_ckpt = self._maybe_checkpoint()
            if self._check_preemption():
                if self.checkpoint_path is not None and not wrote_ckpt:
                    self._checkpoint()
                log.warning("stopping on preemption at iteration %d",
                            self.state["neval"] - 1)
                break
        return model

    def _validate(self):
        if getattr(self, "_validator", None) is None:
            self._validator = LocalValidator(self.model,
                                             self.validation_dataset)
        return self._validator.test(self.validation_methods)


class Validator:
    """Standalone evaluation (ref optim/Validator.scala:23-31)."""

    def __init__(self, model: Module, dataset: AbstractDataSet):
        self.model = model
        self.dataset = dataset
        self._fwd = None  # jitted forward, built once: validation runs
        # every epoch and a fresh jit wrapper per call would recompile

    def _jitted_fwd(self):
        if self._fwd is None:
            model = self.model

            def fwd(params, buffers, data):
                out, _ = model.apply(params, data, buffers=buffers,
                                     training=False)
                return out

            self._fwd = jax.jit(fwd)
        return self._fwd


class LocalValidator(Validator):
    """(ref optim/LocalValidator.scala:29) — eval-mode forward over the
    dataset, monoid-reduce the per-batch results."""

    def test(self, methods: Sequence[ValidationMethod]):
        model = self.model
        model._built()
        fwd = self._jitted_fwd()
        totals = [None] * len(methods)
        for batch in self.dataset.data(train=False):
            out = fwd(model.params, model.buffers, jnp.asarray(batch.data))
            labels = jnp.asarray(batch.labels)
            for i, m in enumerate(methods):
                r = m(out, labels)
                totals[i] = r if totals[i] is None else totals[i] + r
        return list(zip(methods, totals))
