"""Validation methods and monoid results (ref optim/ValidationMethod.scala:
27-218, optim/EvaluateMethods.scala).

Results support ``+`` so per-batch (and per-device, via psum upstream)
results reduce associatively, exactly like the reference's monoid reduce
over partitions (DistriOptimizer.scala:462-532).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class ValidationResult:
    def result(self) -> tuple[float, int]:
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError


class AccuracyResult(ValidationResult):
    def __init__(self, correct: int, count: int):
        self.correct = int(correct)
        self.count = int(count)

    def result(self):
        return (self.correct / max(self.count, 1), self.count)

    def __add__(self, other: "AccuracyResult") -> "AccuracyResult":
        return AccuracyResult(self.correct + other.correct, self.count + other.count)

    def __repr__(self):
        acc, n = self.result()
        return f"Accuracy(correct: {self.correct}, count: {n}, accuracy: {acc:.6f})"


class LossResult(ValidationResult):
    def __init__(self, loss: float, count: int):
        self.loss = float(loss)
        self.count = int(count)

    def result(self):
        return (self.loss / max(self.count, 1), self.count)

    def __add__(self, other: "LossResult") -> "LossResult":
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self):
        avg, n = self.result()
        return f"Loss(sum: {self.loss:.4f}, count: {n}, mean: {avg:.6f})"


class ValidationMethod:
    name = "validation"

    def __call__(self, output, target) -> ValidationResult:
        raise NotImplementedError

    def __repr__(self):
        return self.name


class Top1Accuracy(ValidationMethod):
    """argmax(output)+1 == 1-based target (ref ValidationMethod.scala:90)."""
    name = "Top1Accuracy"

    def __call__(self, output, target) -> AccuracyResult:
        pred = jnp.argmax(output, axis=-1) + 1
        t = jnp.asarray(target).astype(jnp.int32).reshape(pred.shape)
        correct = int(jnp.sum(pred.astype(jnp.int32) == t))
        return AccuracyResult(correct, int(np.prod(pred.shape)))


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def __call__(self, output, target) -> AccuracyResult:
        out = jnp.asarray(output)
        top5 = jnp.argsort(-out, axis=-1)[..., :5] + 1
        t = jnp.asarray(target).astype(jnp.int32).reshape(top5.shape[:-1] + (1,))
        correct = int(jnp.sum(jnp.any(top5.astype(jnp.int32) == t, axis=-1)))
        return AccuracyResult(correct, int(np.prod(top5.shape[:-1])))


class Loss(ValidationMethod):
    """Criterion value over the batch (ref ValidationMethod.scala:207)."""
    name = "Loss"

    def __init__(self, criterion=None):
        from bigdl_tpu.nn.criterions import ClassNLLCriterion
        self.criterion = criterion if criterion is not None else ClassNLLCriterion()

    def __call__(self, output, target) -> LossResult:
        return LossResult(float(self.criterion.loss(output, target)), 1)


class PerplexityResult(ValidationResult):
    """exp of the mean criterion value — the LM family's standard metric
    (post-reference capability alongside TransformerLM).  Accumulates the
    loss sum so the monoid ``+`` stays exact; exp is applied at
    ``result()``."""

    def __init__(self, loss: float, count: int):
        self.loss = float(loss)
        self.count = int(count)

    def result(self):
        return (float(np.exp(self.loss / max(self.count, 1))), self.count)

    def __add__(self, other: "PerplexityResult") -> "PerplexityResult":
        return PerplexityResult(self.loss + other.loss,
                                self.count + other.count)

    def __repr__(self):
        ppl, n = self.result()
        return f"Perplexity(count: {n}, perplexity: {ppl:.4f})"


class Perplexity(ValidationMethod):
    """Per-batch perplexity from a (time-distributed) NLL criterion.  The
    default consumes the LM families' (B, T, V) log-prob outputs — a bare
    ClassNLLCriterion could not (its gather clashes on the time dim)."""
    name = "Perplexity"

    def __init__(self, criterion=None):
        from bigdl_tpu.nn.criterions import (ClassNLLCriterion,
                                             TimeDistributedCriterion)
        self.criterion = (criterion if criterion is not None
                          else TimeDistributedCriterion(
                              ClassNLLCriterion(), True))

    def __call__(self, output, target) -> PerplexityResult:
        return PerplexityResult(float(self.criterion.loss(output, target)), 1)
