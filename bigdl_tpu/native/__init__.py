"""ctypes bindings for the native runtime library (csrc/bigdl_tpu_native.cpp).

The reference backs its hot host loops with a native core library loaded via
JNI (SURVEY.md §2.1: BigDL-core/MKL, ``MKL.isMKLLoaded`` gating fallbacks at
``tensor/TensorNumeric.scala:297-316``).  Here the native library covers the
host *runtime* (CRC framing, bulk Torch-RNG, shard indexing) — device math
is XLA's job — and every caller has a pure-python fallback, so ``lib`` being
``None`` only costs speed, exactly like a missing MKL did.

Build happens on demand with g++ (cached next to this file); set
``BIGDL_TPU_NO_NATIVE=1`` to force the fallbacks.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "csrc", "bigdl_tpu_native.cpp")
_SO = os.path.join(_HERE, "libbigdl_tpu_native.so")

_lock = threading.Lock()


def _build() -> bool:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return False
    tmp = _SO + f".tmp{os.getpid()}"
    try:
        subprocess.run(["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                        "-pthread", "-o", tmp, src],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        if os.path.exists(tmp):
            os.unlink(tmp)
        return False


def _load() -> ctypes.CDLL | None:
    if os.environ.get("BIGDL_TPU_NO_NATIVE") in ("1", "true"):
        return None
    with _lock:
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        try:
            dll = ctypes.CDLL(_SO)
        except OSError:
            return None
    try:
        return _set_prototypes(dll)
    except AttributeError:
        # a stale prebuilt .so missing a newer symbol (source tree absent
        # or mtimes preserved by rsync/tar): one rebuild attempt, else
        # fall back to pure python — 'lib is None' must only cost speed
        with _lock:
            if not _build():
                return None
            try:
                return _set_prototypes(ctypes.CDLL(_SO))
            except (OSError, AttributeError):
                return None


def _set_prototypes(dll: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f64p = ctypes.POINTER(ctypes.c_double)
    i64p = ctypes.POINTER(ctypes.c_int64)
    # c_char_p: C never writes through these, so bytes pass zero-copy
    dll.bt_crc32c.restype = ctypes.c_uint32
    dll.bt_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
    dll.bt_crc32.restype = ctypes.c_uint32
    dll.bt_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
    dll.bt_mt_new.restype = ctypes.c_void_p
    dll.bt_mt_new.argtypes = [ctypes.c_uint64]
    dll.bt_mt_free.argtypes = [ctypes.c_void_p]
    dll.bt_mt_set_seed.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    dll.bt_mt_random.restype = ctypes.c_double
    dll.bt_mt_random.argtypes = [ctypes.c_void_p]
    dll.bt_mt_random_int.restype = ctypes.c_uint32
    dll.bt_mt_random_int.argtypes = [ctypes.c_void_p]
    dll.bt_mt_uniform.argtypes = [ctypes.c_void_p, f64p, ctypes.c_int64,
                                  ctypes.c_double, ctypes.c_double]
    dll.bt_mt_normal.argtypes = [ctypes.c_void_p, f64p, ctypes.c_int64,
                                 ctypes.c_double, ctypes.c_double]
    dll.bt_mt_bernoulli.argtypes = [ctypes.c_void_p, f64p, ctypes.c_int64,
                                    ctypes.c_double]
    dll.bt_mt_randperm.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64]
    dll.bt_mt_get_state.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint32),
                                    ctypes.POINTER(ctypes.c_int32), f64p,
                                    ctypes.POINTER(ctypes.c_int32)]
    dll.bt_mt_set_state.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint32),
                                    ctypes.c_int32, ctypes.c_double,
                                    ctypes.c_int32]
    dll.bt_shard_index.restype = ctypes.c_int64
    dll.bt_shard_index.argtypes = [ctypes.c_char_p, ctypes.c_int64, i64p, i64p,
                                   ctypes.POINTER(ctypes.c_float),
                                   ctypes.c_int64, ctypes.c_int32]
    dll.bt_hadoop_seq_index.restype = ctypes.c_int64
    dll.bt_hadoop_seq_index.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                        i64p, i64p,
                                        ctypes.POINTER(ctypes.c_float),
                                        ctypes.c_int64]
    dll.bt_crop_flip_pack.restype = None
    dll.bt_crop_flip_pack.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        u8p, u8p, ctypes.c_int32]
    dll.bt_tokenize.restype = ctypes.c_int64
    dll.bt_tokenize.argtypes = [ctypes.c_char_p, ctypes.c_int64, i64p, i64p,
                                ctypes.c_int64]
    dll.bt_tokenize_join.restype = ctypes.c_int64
    dll.bt_tokenize_join.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char_p, ctypes.c_int64]
    return dll


class _Lib:
    """Lazy handle: ``lib.crc32c`` etc. or ``None`` when unavailable."""

    def __init__(self):
        self._dll = None
        self._tried = False

    @property
    def dll(self) -> ctypes.CDLL | None:
        if not self._tried:
            self._dll = _load()
            self._tried = True
        return self._dll

    def __bool__(self) -> bool:
        return self.dll is not None

    # -- crc ------------------------------------------------------------ #
    def crc32c(self, data: bytes, crc: int = 0) -> int:
        return int(self.dll.bt_crc32c(data, len(data), crc))

    # -- rng ------------------------------------------------------------ #
    def mt_new(self, seed: int):
        return self.dll.bt_mt_new(seed & 0xFFFFFFFFFFFFFFFF)

    def mt_free(self, handle) -> None:
        self.dll.bt_mt_free(handle)

    def mt_set_seed(self, handle, seed: int) -> None:
        self.dll.bt_mt_set_seed(handle, seed & 0xFFFFFFFFFFFFFFFF)

    def mt_random(self, handle) -> float:
        return float(self.dll.bt_mt_random(handle))

    def mt_random_int(self, handle) -> int:
        return int(self.dll.bt_mt_random_int(handle))

    def mt_uniform(self, handle, n: int, a: float, b: float):
        import numpy as np
        out = np.empty(n, dtype=np.float64)
        self.dll.bt_mt_uniform(handle, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)), n, a, b)
        return out

    def mt_normal(self, handle, n: int, mean: float, stdv: float):
        import numpy as np
        out = np.empty(n, dtype=np.float64)
        self.dll.bt_mt_normal(handle, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)), n, mean, stdv)
        return out

    def mt_bernoulli(self, handle, n: int, p: float):
        import numpy as np
        out = np.empty(n, dtype=np.float64)
        self.dll.bt_mt_bernoulli(handle, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)), n, p)
        return out

    def mt_randperm(self, handle, n: int):
        import numpy as np
        out = np.empty(n, dtype=np.int64)
        self.dll.bt_mt_randperm(handle, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)), n)
        return out

    def mt_get_state(self, handle):
        mt = (ctypes.c_uint32 * 624)()
        mti = ctypes.c_int32()
        cached = ctypes.c_double()
        has = ctypes.c_int32()
        self.dll.bt_mt_get_state(handle, mt, ctypes.byref(mti),
                                 ctypes.byref(cached), ctypes.byref(has))
        return list(mt), mti.value, cached.value, has.value

    def mt_set_state(self, handle, mt, mti, cached, has) -> None:
        arr = (ctypes.c_uint32 * 624)(*[int(x) & 0xFFFFFFFF for x in mt])
        self.dll.bt_mt_set_state(handle, arr, mti, cached, has)

    # -- image batcher --------------------------------------------------- #
    def crop_flip_pack(self, records, stored_h: int, stored_w: int,
                       crop: int, cys, cxs, flips, n_threads: int = 0):
        """Crop/flip/pack HWC uint8 image records into one (B, crop,
        crop, 3) uint8 NHWC batch with native threads (the host hot loop
        of the input pipeline; ref MTLabeledBGRImgToBatch.scala:52-80).
        ``records``: list of bytes of size stored_h*stored_w*3 each."""
        import numpy as np
        batch = len(records)
        want = stored_h * stored_w * 3
        for i, r in enumerate(records):
            if len(r) != want:
                raise ValueError(
                    f"record {i} has {len(r)} bytes, expected "
                    f"{stored_h}x{stored_w}x3 = {want} (the native path "
                    f"must keep the python path's shape guard — an "
                    f"out-of-bounds read here is a segfault, not a "
                    f"ValueError)")
        out = np.empty((batch, crop, crop, 3), dtype=np.uint8)
        recs = (ctypes.c_char_p * batch)(*records)
        cy = np.ascontiguousarray(cys, dtype=np.int32)
        cx = np.ascontiguousarray(cxs, dtype=np.int32)
        fl = np.ascontiguousarray(flips, dtype=np.uint8)
        if (cy.min(initial=0) < 0 or cx.min(initial=0) < 0
                or cy.max(initial=0) + crop > stored_h
                or cx.max(initial=0) + crop > stored_w):
            raise ValueError("crop window out of bounds")
        if n_threads <= 0:
            n_threads = max(1, (os.cpu_count() or 8) // 2)
        # tiny batches don't amortize thread spawn/join
        n_threads = min(n_threads, max(1, batch // 8))
        self.dll.bt_crop_flip_pack(
            recs, batch, stored_h, stored_w, crop,
            cy.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            fl.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n_threads)
        return out

    # -- shard indexing -------------------------------------------------- #
    def shard_index(self, buf, validate: bool = True):
        """buf: bytes/memoryview of a whole shard file.  Returns
        (offsets, lengths, labels) numpy arrays or raises ValueError."""
        import numpy as np
        data = bytes(buf)
        # a record is >= 12 header bytes (payload may be empty)
        max_n = max((len(data) - 5) // 12, 1)
        offsets = np.empty(max_n, dtype=np.int64)
        lengths = np.empty(max_n, dtype=np.int64)
        labels = np.empty(max_n, dtype=np.float32)
        n = self.dll.bt_shard_index(
            data, len(data),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            max_n, 1 if validate else 0)
        if n == -1:
            raise ValueError("malformed record shard")
        if n == -2:
            raise ValueError("record shard crc mismatch")
        if n == -3:  # cannot happen with the sizing above; defensive
            raise ValueError("record shard index overflow")
        return offsets[:n], lengths[:n], labels[:n]

    def hadoop_seq_index(self, buf):
        """buf: bytes of a whole Text/Text SequenceFile.  Returns
        (value offsets, value lengths, labels) numpy arrays; raises
        ValueError on malformed input and NotImplementedError on
        unsupported flavors (compression, non-Text classes, version < 6)
        so callers can fall back to the python reader."""
        import numpy as np
        data = bytes(buf)
        # a record is >= 10 bytes (reclen + keylen + 1-byte key + 1-byte
        # value vints); the +1 keeps empty files from zero-size arrays
        max_n = max((len(data)) // 10, 1)
        offsets = np.empty(max_n, dtype=np.int64)
        lengths = np.empty(max_n, dtype=np.int64)
        labels = np.empty(max_n, dtype=np.float32)
        n = self.dll.bt_hadoop_seq_index(
            data, len(data),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            max_n)
        if n == -1:
            raise ValueError("malformed SequenceFile")
        if n == -3:
            raise ValueError("SequenceFile index overflow")
        if n == -4:
            raise NotImplementedError("unsupported SequenceFile flavor")
        if n == -5:
            raise ValueError("SequenceFile key has a non-numeric label")
        return offsets[:n], lengths[:n], labels[:n]

    def tokenize(self, text: str) -> list:
        """Word tokenization of an (already lowercased) string — the C
        twin of dataset/text.py SentenceTokenizer's regex: word-char runs
        as one token, any other single code point as one token.  One
        buffer crossing: C writes the tokens newline-joined, python does
        a single decode + split."""
        data = text.encode("utf-8")
        if not data:
            return []
        cap = 2 * len(data)
        out = ctypes.create_string_buffer(cap)
        n = self.dll.bt_tokenize_join(data, len(data), out, cap)
        if n < 0:  # cannot happen with cap = 2x byte count; defensive
            raise ValueError("tokenizer overflow")
        if n == 0:
            return []
        return out.raw[:n].decode("utf-8", "replace").split("\n")


lib = _Lib()


def get() -> _Lib | None:
    """The single gating point callers should use: the loaded native
    library, or None (pure-python fallbacks apply).  First call may build
    the .so; subsequent calls are cached."""
    return lib if lib.dll is not None else None
