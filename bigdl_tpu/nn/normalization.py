"""Normalization layers (ref nn/BatchNormalization.scala:151-451,
SpatialBatchNormalization, SpatialCrossMapLRN, Spatial*Normalization,
Normalize).

BatchNormalization is the one stateful module in the zoo: running mean/var
live in ``buffers`` and flow functionally through ``apply`` (the reference
mutates them in place and threads per-channel work over Engine.model; XLA
fuses the whole normalization into neighboring ops instead).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module


class BatchNormalization(Module):
    """Batch norm over (N, D) input (ref nn/BatchNormalization.scala).

    Torch momentum convention: running = (1-momentum)*running + momentum*batch.
    """

    _reduce_axes = (0,)
    _param_shape_from = "n_output"

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, data_format: str = "NCHW"):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        if data_format not in ("NCHW", "NHWC"):
            raise ValueError(f"unsupported data_format {data_format!r}")
        self.data_format = data_format

    def init(self, rng):
        if not self.affine:
            return {}
        return {"weight": jax.random.uniform(rng, (self.n_output,)),
                "bias": jnp.zeros((self.n_output,))}

    def init_buffers(self):
        return {"running_mean": jnp.zeros((self.n_output,)),
                "running_var": jnp.ones((self.n_output,))}

    def _channel_axis(self, ndim):
        if ndim <= 2 or self.data_format == "NHWC":
            return ndim - 1
        return 1

    def _reshape_stat(self, s, ndim):
        ch = self._channel_axis(ndim)
        if ch == ndim - 1:
            return s  # broadcasts naturally on the last axis
        shape = [1] * ndim
        shape[ch] = self.n_output
        return s.reshape(shape)

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or self.init_buffers()
        axes = tuple(i for i in range(x.ndim) if i != self._channel_axis(x.ndim))
        if training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            n = x.size // self.n_output
            unbiased = var * n / max(n - 1, 1)
            new_buffers = {
                "running_mean": (1 - self.momentum) * buffers["running_mean"] + self.momentum * mean,
                "running_var": (1 - self.momentum) * buffers["running_var"] + self.momentum * unbiased,
            }
        else:
            mean, var = buffers["running_mean"], buffers["running_var"]
            new_buffers = buffers
        mean = self._reshape_stat(mean, x.ndim)
        var = self._reshape_stat(var, x.ndim)
        y = (x - mean) * lax.rsqrt(var + self.eps)
        if self.affine:
            w = self._reshape_stat(params["weight"], x.ndim)
            b = self._reshape_stat(params["bias"], x.ndim)
            y = y * w + b
        return y, new_buffers


class SpatialBatchNormalization(BatchNormalization):
    """Batch norm over (N, C, H, W) reducing N,H,W
    (ref nn/SpatialBatchNormalization.scala)."""


def layer_norm(x, weight=None, bias=None, eps: float = 1e-5):
    """Functional layer norm over the trailing dim, shared by the
    ``LayerNorm`` module and the transformer block (models/transformer).
    Normalizes in f32 even under bf16 compute: mean/var cancellation loses
    bf16's 8 mantissa bits fast, and the cast pair fuses away."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


class LayerNorm(Module):
    """Layer normalization over the trailing feature dim (post-reference
    capability: the reference's zoo predates transformers — this is the
    normalization the transformer stack needs, sharing BatchNormalization's
    affine gamma/beta convention but with no running stats, so it is
    stateless and mesh-friendly: every token normalizes independently,
    nothing crosses the data/sequence axes)."""

    def __init__(self, n_output: int, eps: float = 1e-5, affine: bool = True):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.affine = affine

    def init(self, rng):
        if not self.affine:
            return {}
        return {"weight": jnp.ones((self.n_output,), jnp.float32),
                "bias": jnp.zeros((self.n_output,), jnp.float32)}

    def f(self, params, x, **kw):
        if self.affine:
            return layer_norm(x, params["weight"], params["bias"], self.eps)
        return layer_norm(x, eps=self.eps)


class Normalize(Module):
    """Lp-normalize each row (ref nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p = p
        self.eps = eps

    def f(self, params, x, **kw):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        else:
            norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), self.p), axis=-1,
                                     keepdims=True), 1.0 / self.p)
        return x / jnp.maximum(norm, self.eps)


class SpatialCrossMapLRN(Module):
    """AlexNet-style local response normalization across channels
    (ref nn/SpatialCrossMapLRN.scala):
    y = x / (k + alpha/size * sum_{window} x^2)^beta."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, data_format: str = "NCHW"):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def f(self, params, x, **kw):
        half = (self.size - 1) // 2
        sq = jnp.square(x)
        ch = 1 if self.data_format == "NCHW" else 3
        dims, pads = [1] * 4, [(0, 0)] * 4
        dims[ch] = self.size
        pads[ch] = (half, self.size - 1 - half)
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=tuple(dims),
            window_strides=(1, 1, 1, 1),
            padding=tuple(pads),
        )
        return x * jnp.power(self.k + self.alpha / self.size * window_sum, -self.beta)


def _smooth(x, kernel2d):
    """Depthwise 'same' smoothing with border renormalization: returns
    (weighted local mean, coverage coefficient) as Torch's Spatial*
    normalizations compute them."""
    kh, kw = kernel2d.shape
    k = (kernel2d / kernel2d.sum()).astype(x.dtype)
    C = x.shape[1]
    w = jnp.zeros((C, 1, kh, kw), dtype=x.dtype) + k[None, None]
    pad = ((kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2))
    mean = lax.conv_general_dilated(
        x, w, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=C) / C
    ones = jnp.ones_like(x[:, :1])
    coef = lax.conv_general_dilated(
        ones, w[:1], (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return mean, coef


def _gaussian_kernel(size: int) -> jnp.ndarray:
    import numpy as np
    g = np.exp(-0.5 * ((np.arange(size) - (size - 1) / 2.0) / (size / 4.0)) ** 2)
    k = np.outer(g, g)
    return jnp.asarray(k / k.sum(), dtype=jnp.float32)


class SpatialSubtractiveNormalization(Module):
    """Subtract the kernel-weighted local mean (summed over channels), with
    border renormalization (ref nn/SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.kernel = kernel if kernel is not None else _gaussian_kernel(9)

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        kernel2d = jnp.asarray(self.kernel)
        mean, coef = _smooth(x, kernel2d)
        mean_all = jnp.sum(mean, axis=1, keepdims=True)  # cross-channel mean
        y = x - mean_all / jnp.maximum(coef, 1e-12)
        return y[0] if squeeze else y


class SpatialDivisiveNormalization(Module):
    """Divide by the local standard deviation, thresholded at its per-sample
    mean (ref nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.kernel = kernel if kernel is not None else _gaussian_kernel(9)
        self.threshold = threshold
        self.thresval = thresval

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        kernel2d = jnp.asarray(self.kernel)
        mean_sq, coef = _smooth(jnp.square(x), kernel2d)
        local_std = jnp.sqrt(jnp.maximum(
            jnp.sum(mean_sq, axis=1, keepdims=True) / jnp.maximum(coef, 1e-12), 0.0))
        per_sample_mean = jnp.mean(local_std, axis=(1, 2, 3), keepdims=True)
        divisor = jnp.maximum(local_std, per_sample_mean)
        divisor = jnp.maximum(divisor, self.threshold)
        y = x / divisor
        return y[0] if squeeze else y


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization
    (ref nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel, threshold, thresval)

    def f(self, params, x, **kw):
        return self.div.f({}, self.sub.f({}, x))
