"""Stochastic / gradient-shaping layers (ref nn/Dropout.scala:49-93,
L1Penalty, GradientReversal).

Dropout noise comes from ``jax.random`` keys threaded through ``apply``
(the reference generates Bernoulli noise on the Engine.model pool; on TPU
the PRNG runs on device inside the fused program).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Dropout(Module):
    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = init_p
        self.inplace = inplace  # API parity; meaningless under XLA
        self.scale = scale

    def f(self, params, x, *, training=False, rng=None, **kw):
        if not training or self.p == 0.0:
            if not self.scale:
                return x * (1 - self.p)
            return x
        if rng is None:
            raise ValueError("Dropout in training mode needs an rng key")
        keep = jax.random.bernoulli(rng, 1.0 - self.p, x.shape)
        y = jnp.where(keep, x, 0.0)
        if self.scale:
            y = y / (1.0 - self.p)
        return y

    def set_p(self, p: float) -> "Dropout":
        self.p = p
        return self


class L1Penalty(Module):
    """Identity forward that injects an L1 subgradient into the backward
    pass (ref nn/L1Penalty.scala).  Expressed as a custom VJP — the
    functional rendering of the reference's gradInput += l1weight*sign(x)."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = l1weight
        self.size_average = size_average

        @jax.custom_vjp
        def _penalty(x):
            return x

        def _fwd(x):
            return x, (x,)

        def _bwd(res, g):
            (x,) = res
            w = self.l1weight / x.size if self.size_average else self.l1weight
            return (g + w * jnp.sign(x),)

        _penalty.defvjp(_fwd, _bwd)
        self._penalty = _penalty

    def f(self, params, x, **kw):
        return self._penalty(x)


class GradientReversal(Module):
    """Identity forward, -lambda-scaled gradient backward
    (ref nn/GradientReversal.scala — the DANN trick)."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self.the_lambda = the_lambda

        @jax.custom_vjp
        def _rev(x, lam):
            return x

        def _fwd(x, lam):
            return x, (lam,)

        def _bwd(res, g):
            (lam,) = res
            return (-lam * g, None)

        _rev.defvjp(_fwd, _bwd)
        self._rev = _rev

    def set_lambda(self, lam: float) -> "GradientReversal":
        self.the_lambda = lam
        return self

    def f(self, params, x, **kw):
        return self._rev(x, self.the_lambda)
