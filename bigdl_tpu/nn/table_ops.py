"""Elementwise table-combining layers (ref nn/CAddTable.scala etc.) and
per-element reductions over one tensor (ref nn/Sum.scala, Mean, Max, Min).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn._util import to_axis
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import Table


def _seq(x):
    return x.to_seq() if isinstance(x, Table) else list(x)


class CAddTable(Module):
    def __init__(self, inplace: bool = False):
        super().__init__()

    def f(self, params, x, **kw):
        xs = _seq(x)
        out = xs[0]
        for t in xs[1:]:
            out = out + t
        return out


class CSubTable(Module):
    def f(self, params, x, **kw):
        a, b = _seq(x)
        return a - b


class CMulTable(Module):
    def f(self, params, x, **kw):
        xs = _seq(x)
        out = xs[0]
        for t in xs[1:]:
            out = out * t
        return out


class CDivTable(Module):
    def f(self, params, x, **kw):
        a, b = _seq(x)
        return a / b


class CMaxTable(Module):
    def f(self, params, x, **kw):
        xs = _seq(x)
        out = xs[0]
        for t in xs[1:]:
            out = jnp.maximum(out, t)
        return out


class CMinTable(Module):
    def f(self, params, x, **kw):
        xs = _seq(x)
        out = xs[0]
        for t in xs[1:]:
            out = jnp.minimum(out, t)
        return out


class Sum(Module):
    """Sum over a 1-based dim; size_average divides by dim size; squeeze
    drops the dim (ref nn/Sum.scala)."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.size_average = size_average
        self.squeeze = squeeze

    def f(self, params, x, **kw):
        nid = self.n_input_dims if self.n_input_dims > 0 else None
        axis = to_axis(self.dimension, x.ndim, nid)
        y = jnp.sum(x, axis=axis, keepdims=not self.squeeze)
        if self.size_average:
            y = y / x.shape[axis]
        return y


class Mean(Module):
    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def f(self, params, x, **kw):
        nid = self.n_input_dims if self.n_input_dims > 0 else None
        axis = to_axis(self.dimension, x.ndim, nid)
        return jnp.mean(x, axis=axis, keepdims=not self.squeeze)


class Max(Module):
    """Max values over a 1-based dim (ref nn/Max.scala)."""

    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def f(self, params, x, **kw):
        nid = self.num_input_dims if self.num_input_dims > 0 else None
        axis = to_axis(self.dim, x.ndim, nid)
        return jnp.max(x, axis=axis)


class Min(Module):
    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def f(self, params, x, **kw):
        nid = self.num_input_dims if self.num_input_dims > 0 else None
        axis = to_axis(self.dim, x.ndim, nid)
        return jnp.min(x, axis=axis)
