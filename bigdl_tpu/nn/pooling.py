"""Pooling layers (ref nn/SpatialMaxPooling.scala, SpatialAveragePooling.scala,
RoiPooling.scala).  The reference hand-writes pooling loops in NNPrimitive
(:356-498); here they are ``lax.reduce_window`` — XLA lowers to VPU code and
autodiff derives the backward (the reference's argmax-index bookkeeping
disappears).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import Table


def _pool_geometry(x, kernel_h, kernel_w, stride_h, stride_w, pad_h, pad_w,
                   ceil_mode, data_format):
    """(window_dims, window_strides, paddings) for reduce_window in either
    activation layout (spatial dims at 2,3 for NCHW; 1,2 for NHWC)."""
    if data_format == "NCHW":
        hd, wd = 2, 3
    elif data_format == "NHWC":
        hd, wd = 1, 2
    else:
        raise ValueError(f"unsupported data_format {data_format!r}")
    _, ph = _pool_pads(x.shape[hd], kernel_h, stride_h, pad_h, ceil_mode)
    _, pw = _pool_pads(x.shape[wd], kernel_w, stride_w, pad_w, ceil_mode)
    dims, strides, pads = [1] * 4, [1] * 4, [(0, 0)] * 4
    dims[hd], dims[wd] = kernel_h, kernel_w
    strides[hd], strides[wd] = stride_h, stride_w
    pads[hd], pads[wd] = ph, pw
    return tuple(dims), tuple(strides), tuple(pads)


def _pool_pads(size, kernel, stride, pad, ceil_mode):
    """Torch-style output sizing: floor or ceil mode; in ceil mode the last
    window must start inside the (padded) input (Torch SpatialMaxPooling
    semantics)."""
    if ceil_mode:
        out = -(-(size + 2 * pad - kernel) // stride) + 1
        if (out - 1) * stride >= size + pad:
            out -= 1
    else:
        out = (size + 2 * pad - kernel) // stride + 1
    needed = (out - 1) * stride + kernel - size - pad
    return out, (pad, max(needed, 0))


class SpatialMaxPooling(Module):
    def __init__(self, kernel_w: int, kernel_h: int, stride_w: int = None,
                 stride_h: int = None, pad_w: int = 0, pad_h: int = 0,
                 data_format: str = "NCHW"):
        super().__init__()
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w if stride_w is not None else kernel_w
        self.stride_h = stride_h if stride_h is not None else kernel_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.ceil_mode = False
        self.data_format = data_format

    def ceil(self) -> "SpatialMaxPooling":
        self.ceil_mode = True
        return self

    def floor(self) -> "SpatialMaxPooling":
        self.ceil_mode = False
        return self

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        dims, strides, pads = _pool_geometry(
            x, self.kernel_h, self.kernel_w, self.stride_h, self.stride_w,
            self.pad_h, self.pad_w, self.ceil_mode, self.data_format)
        y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
        return y[0] if squeeze else y


class SpatialAveragePooling(Module):
    def __init__(self, kernel_w: int, kernel_h: int, stride_w: int = None,
                 stride_h: int = None, pad_w: int = 0, pad_h: int = 0,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True, data_format: str = "NCHW"):
        super().__init__()
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w if stride_w is not None else kernel_w
        self.stride_h = stride_h if stride_h is not None else kernel_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide
        self.data_format = data_format

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        dims, strides, pads = _pool_geometry(
            x, self.kernel_h, self.kernel_w, self.stride_h, self.stride_w,
            self.pad_h, self.pad_w, self.ceil_mode, self.data_format)
        y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if self.divide:
            if self.count_include_pad:
                y = y / (self.kernel_h * self.kernel_w)
            else:
                ones = jnp.ones_like(x)
                counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
                y = y / counts
        return y[0] if squeeze else y


class RoiPooling(Module):
    """Region-of-interest max pooling for detection (ref nn/RoiPooling.scala).

    Input: Table {features (N,C,H,W), rois (R,5) rows = (batch_idx, x1, y1,
    x2, y2)} with 0-based batch_idx and roi coords in input-image scale.
    Output: (R, C, pooled_h, pooled_w).  Implemented as a masked max per
    output cell, vmapped over rois — static shapes throughout, so one XLA
    program regardless of roi geometry.
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float = 1.0):
        super().__init__()
        self.pooled_w = pooled_w
        self.pooled_h = pooled_h
        self.spatial_scale = spatial_scale

    def f(self, params, x, **kw):
        feats, rois = (x.to_seq() if isinstance(x, Table) else list(x))
        N, C, H, W = feats.shape
        ph, pw = self.pooled_h, self.pooled_w

        def one_roi(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale)
            y1 = jnp.round(roi[2] * self.spatial_scale)
            x2 = jnp.round(roi[3] * self.spatial_scale)
            y2 = jnp.round(roi[4] * self.spatial_scale)
            roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
            roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
            bin_h = roi_h / ph
            bin_w = roi_w / pw
            fmap = feats[b]  # (C, H, W)
            iy = jnp.arange(ph, dtype=feats.dtype)
            ix = jnp.arange(pw, dtype=feats.dtype)
            hstart = jnp.clip(jnp.floor(iy * bin_h) + y1, 0, H)
            hend = jnp.clip(jnp.ceil((iy + 1) * bin_h) + y1, 0, H)
            wstart = jnp.clip(jnp.floor(ix * bin_w) + x1, 0, W)
            wend = jnp.clip(jnp.ceil((ix + 1) * bin_w) + x1, 0, W)
            hh = jnp.arange(H, dtype=feats.dtype)
            ww = jnp.arange(W, dtype=feats.dtype)
            rmask = (hh[None, :] >= hstart[:, None]) & (hh[None, :] < hend[:, None])  # (ph,H)
            cmask = (ww[None, :] >= wstart[:, None]) & (ww[None, :] < wend[:, None])  # (pw,W)
            mask = rmask[:, None, :, None] & cmask[None, :, None, :]  # (ph,pw,H,W)
            empty = ~jnp.any(mask, axis=(2, 3))  # (ph,pw)
            vals = jnp.where(mask[None], fmap[:, None, None, :, :], -jnp.inf)
            pooled = jnp.max(vals, axis=(3, 4))  # (C,ph,pw)
            return jnp.where(empty[None], 0.0, pooled)

        return jax.vmap(one_roi)(rois)
