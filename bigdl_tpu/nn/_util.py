"""Shared helpers for the nn layer zoo."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def to_axis(dim: int, ndim: int, n_input_dims: Optional[int] = None) -> int:
    """Convert a 1-based Torch/BigDL dimension to a 0-based axis.

    ``n_input_dims`` reproduces the reference's nInputDims convention: when
    the actual rank exceeds it, leading dims are batch dims and the 1-based
    ``dim`` counts from after them (e.g. JoinTable, SplitTable).
    Negative dims count from the end (Torch allows -1 = last).
    """
    if dim < 0:
        return ndim + dim
    axis = dim - 1
    if n_input_dims is not None and ndim > n_input_dims:
        axis += ndim - n_input_dims
    return axis


def fold_rng(rng, i: int):
    return None if rng is None else jax.random.fold_in(rng, i)


def cast_f32_leaves(tree, dtype):
    """The mixed-precision param cast (f32 leaves -> compute dtype,
    everything else untouched) — ONE definition shared by
    ``Optimizer.set_compute_dtype``, ``bench.py`` and the perf
    harnesses, so the benchmarks measure exactly the recipe training
    uses."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype) if a.dtype == jnp.float32 else a, tree)


def match_compute_dtype(x, w):
    """AMP-style operand alignment for MXU-feeding ops: when the weight is
    a float of different precision than the float input, cast the input to
    the weight's dtype.  Mixed precision casts *params* to the compute
    dtype (optim.Optimizer.set_compute_dtype); aligning at the layer is
    what makes the matmul/conv actually run there — jnp's silent promotion
    would up-cast the bf16 weight back to f32, and lax.conv would reject
    the mismatch outright.  Inputs whose float payload is not resumable in
    low precision (1-based LookupTable/embedding ids riding float32) never
    reach this helper: id-consuming layers convert to int before any
    weight touches the value."""
    wdt = getattr(w, "dtype", None)  # QTensor weights align in-kernel
    if (wdt is not None and jnp.issubdtype(x.dtype, jnp.floating)
            and jnp.issubdtype(wdt, jnp.floating)
            and x.dtype != wdt):
        return x.astype(wdt)
    return x


def same_pad(size: int, kernel: int, stride: int) -> tuple[int, int]:
    """SAME-style padding pair for one spatial dim."""
    out = -(-size // stride)
    total = max(0, (out - 1) * stride + kernel - size)
    return total // 2, total - total // 2


def one_based_index(idx: int, length: int) -> int:
    """1-based index with negative-from-end semantics (ref SelectTable)."""
    return idx - 1 if idx > 0 else length + idx
