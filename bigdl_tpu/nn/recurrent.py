"""Recurrent stack (ref nn/Recurrent.scala:60-110, Cell.scala, RNN.scala,
LSTM.scala, GRU.scala, BiRecurrent.scala, TimeDistributed.scala).

The reference unrolls over time by cloning the cell per timestep with
shared weight storages.  The TPU-native rendering is ``lax.scan``: one
traced cell step, weights closed over once (the sharing is free), O(1)
compile size in sequence length, and XLA pipelines the steps.  Gates are
fused into single matmuls so the MXU sees one large GEMM per step instead
of the reference's per-gate compositional graph (nn/LSTM.scala builds LSTM
out of Linear/Sigmoid/CMulTable pieces).

Layout follows the reference: input (batch, time, feature) — batchDim=1,
timeDim=2 in 1-based terms (nn/Recurrent.scala:37-38).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn._util import match_compute_dtype
from bigdl_tpu.nn.table_ops import CAddTable


class Cell(Module):
    """Base recurrent cell: subclasses define ``init``, ``init_state`` and
    ``step`` (ref nn/Cell.scala:35-80 hidResize ~= init_state)."""

    hidden_size: int

    def init_state(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def step(self, params, x_t, state, *, training=False, rng=None):
        """(params, (B,in), state) -> (output (B,hidden), new_state)."""
        raise NotImplementedError

    def _gate_dropout(self, gates, training, rng):
        """Dropout on the gate pre-activations (the reference applies
        Dropout(p) on each gate input path, nn/LSTM.scala)."""
        p = getattr(self, "p", 0.0)
        if not training or p <= 0.0 or rng is None:
            return gates
        keep = jax.random.bernoulli(rng, 1.0 - p, gates.shape)
        return jnp.where(keep, gates / (1.0 - p), 0.0)

    # a Cell used standalone maps {input, state-table} like BigDL; the
    # common path is via Recurrent below.
    def f(self, params, x, *, training=False, rng=None, **kw):
        y, _ = self.step(params, x, self.init_state(x.shape[0], x.dtype),
                         training=training, rng=rng)
        return y


def _uniform(rng, shape, stdv):
    return jax.random.uniform(rng, shape, minval=-stdv, maxval=stdv, dtype=jnp.float32)


class RnnCell(Cell):
    """Elman cell: h' = act(W x + U h + b) (ref nn/RNN.scala)."""

    def __init__(self, input_size: int, hidden_size: int, activation: Optional[Module] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        from bigdl_tpu.nn.activations import Tanh
        self.activation = activation if activation is not None else Tanh()

    def init(self, rng):
        k = jax.random.split(rng, 4)
        stdv = 1.0 / math.sqrt(self.hidden_size)
        return {"w_ih": _uniform(k[0], (self.input_size, self.hidden_size), stdv),
                "w_hh": _uniform(k[1], (self.hidden_size, self.hidden_size), stdv),
                "bias": _uniform(k[2], (self.hidden_size,), stdv)}

    def init_state(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def step(self, params, x_t, h, *, training=False, rng=None):
        x_t = match_compute_dtype(x_t, params["w_ih"])
        h = match_compute_dtype(h, params["w_hh"])
        h_new = self.activation.f({}, x_t @ params["w_ih"] + h @ params["w_hh"] + params["bias"])
        return h_new, h_new


class LSTM(Cell):
    """LSTM cell with fused 4-gate matmul (ref nn/LSTM.scala, 210 LoC
    compositional; here one GEMM per step feeds the MXU).  ``p`` is dropout
    on the gate pre-activations (p=0 disables, the reference's default)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.p = p  # dropout on the 4 gate inputs, as in the reference

    def init(self, rng):
        k = jax.random.split(rng, 3)
        stdv = 1.0 / math.sqrt(self.hidden_size)
        H = self.hidden_size
        return {"w_ih": _uniform(k[0], (self.input_size, 4 * H), stdv),
                "w_hh": _uniform(k[1], (H, 4 * H), stdv),
                "bias": _uniform(k[2], (4 * H,), stdv)}

    def init_state(self, batch, dtype=jnp.float32):
        H = self.hidden_size
        return (jnp.zeros((batch, H), dtype), jnp.zeros((batch, H), dtype))

    def step(self, params, x_t, state, *, training=False, rng=None):
        h, c = state
        H = self.hidden_size
        x_t = match_compute_dtype(x_t, params["w_ih"])
        h = match_compute_dtype(h, params["w_hh"])
        gates = x_t @ params["w_ih"] + h @ params["w_hh"] + params["bias"]
        gates = self._gate_dropout(gates, training, rng)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRU(Cell):
    """GRU cell, fused 3-gate matmul (ref nn/GRU.scala)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.p = p

    def init(self, rng):
        k = jax.random.split(rng, 3)
        stdv = 1.0 / math.sqrt(self.hidden_size)
        H = self.hidden_size
        return {"w_ih": _uniform(k[0], (self.input_size, 3 * H), stdv),
                "w_hh": _uniform(k[1], (H, 3 * H), stdv),
                "bias": _uniform(k[2], (3 * H,), stdv)}

    def init_state(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def step(self, params, x_t, h, *, training=False, rng=None):
        H = self.hidden_size
        x_t = match_compute_dtype(x_t, params["w_ih"])
        h = match_compute_dtype(h, params["w_hh"])
        xi = x_t @ params["w_ih"] + params["bias"]
        xi = self._gate_dropout(xi, training, rng)
        hh = h @ params["w_hh"]
        r = jax.nn.sigmoid(xi[:, :H] + hh[:, :H])
        z = jax.nn.sigmoid(xi[:, H:2 * H] + hh[:, H:2 * H])
        n = jnp.tanh(xi[:, 2 * H:] + r * hh[:, 2 * H:])
        h_new = (1 - z) * n + z * h
        return h_new, h_new


class Recurrent(Module):
    """Unroll a cell over the time dim via lax.scan
    (ref nn/Recurrent.scala).  Input (B, T, F) -> output (B, T, H)."""

    def __init__(self, cell: Optional[Cell] = None):
        super().__init__()
        self.cell = cell
        self.modules = [cell] if cell is not None else []

    def add(self, cell: Cell) -> "Recurrent":
        self.cell = cell
        self.modules = [cell]
        return self

    def init(self, rng):
        return {"cell": self.cell.init(rng)}

    def f(self, params, x, *, training=False, rng=None, **kw):
        B, T = x.shape[0], x.shape[1]
        # the scan carry must keep one dtype across steps: the cell GEMMs
        # run in the weight dtype (match_compute_dtype), so the state
        # starts there too — under bf16 compute a f32 state would flip
        # dtype after the first step and fail scan's carry check
        float_leaves = [l for l in jax.tree_util.tree_leaves(params["cell"])
                        if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
        state_dtype = float_leaves[0].dtype if float_leaves else x.dtype
        state0 = self.cell.init_state(B, state_dtype)
        xs = jnp.swapaxes(x, 0, 1)  # (T, B, F)
        use_rng = rng is not None and getattr(self.cell, "p", 0.0) > 0.0 and training
        keys = jax.random.split(rng, T) if use_rng else jnp.zeros((T, 2), dtype=jnp.uint32)

        def body(state, inputs):
            x_t, key = inputs
            y_t, new_state = self.cell.step(
                params["cell"], x_t, state, training=training,
                rng=key if use_rng else None)
            return new_state, y_t

        _, ys = lax.scan(body, state0, (xs, keys))
        return jnp.swapaxes(ys, 0, 1)  # (B, T, H)


class BiRecurrent(Module):
    """Bidirectional recurrence; merges fwd/bwd outputs with ``merge``
    (default elementwise add, ref nn/BiRecurrent.scala)."""

    def __init__(self, cell_fwd: Cell, cell_bwd: Optional[Cell] = None,
                 merge: Optional[Module] = None):
        super().__init__()
        import copy
        self.fwd = Recurrent(cell_fwd)
        self.bwd = Recurrent(cell_bwd if cell_bwd is not None else copy.deepcopy(cell_fwd))
        self.merge = merge if merge is not None else CAddTable()
        self.modules = [self.fwd, self.bwd]

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"fwd": self.fwd.init(k1), "bwd": self.bwd.init(k2)}

    def f(self, params, x, **kw):
        y_f = self.fwd.f(params["fwd"], x)
        y_b = jnp.flip(self.bwd.f(params["bwd"], jnp.flip(x, axis=1)), axis=1)
        return self.merge.f({}, [y_f, y_b])


class TimeDistributed(Module):
    """Apply an inner module independently at every timestep by folding
    time into batch (ref nn/TimeDistributed.scala) — one big batched GEMM
    instead of T small ones."""

    def __init__(self, module: Module):
        super().__init__()
        self.module = module
        self.modules = [module]

    def init(self, rng):
        return {"module": self.module.init(rng)}

    def init_buffers(self):
        return {"module": self.module.init_buffers()}

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        B, T = x.shape[0], x.shape[1]
        flat = x.reshape((B * T,) + x.shape[2:])
        y, b = self.module.apply(params["module"], flat,
                                 buffers=(buffers or {}).get("module", {}),
                                 training=training, rng=rng)
        return y.reshape((B, T) + y.shape[1:]), {"module": b}
