"""Module system: Torch-style modules compiled to pure JAX functions.

Rebuild of the reference's ``nn/abstractnn/AbstractModule.scala:40-311`` and
``nn/abstractnn/AbstractCriterion.scala:29-55``.  The reference mutates
``output``/``gradInput`` caches and accumulates gradients in place; under
XLA everything must be pure, so each module is split into:

- hyperparameters: plain Python attributes fixed at construction (BigDL
  constructors take explicit dims, so no lazy shape inference is needed);
- ``init(rng) -> params``: a pytree (nested dict) of trainable arrays;
- ``init_buffers() -> buffers``: non-trainable state (e.g. BatchNorm
  running stats), usually ``{}``;
- ``apply(params, x, buffers=..., training=..., rng=...) -> (y, buffers')``:
  the pure forward, traced once per (training,) under ``jax.jit``.

On top of this sits the Torch-style object shell for API parity: ``build``
materializes ``self.params``; ``forward``/``backward`` mirror the
reference's ``updateOutput``/``updateGradInput``+``accGradParameters``
(backward is a ``jax.vjp`` pullback — on TPU there is no hand-written
backward per layer; XLA differentiates the forward).  Training loops use
the functional path (``value_and_grad`` over ``apply``), never ``backward``.

``Activity`` (Tensor ∪ Table, ref nn/abstractnn/Activity.scala:25) needs no
class here: any pytree (array, Table, tuple, dict) is a valid activity.
"""
from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Params = Any  # pytree of jnp arrays
Buffers = Any
Activity = Any


def _is_array_like(x) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray, jax.Array))


class Module:
    """Base module (ref AbstractModule).  Subclasses implement ``init`` and
    either ``f`` (stateless: params, x -> y) or ``apply`` (stateful)."""

    # set by utils.profiling during a shape-recording pass: called as
    # probe(parent, child_index, child, child_input, child_params,
    # child_buffers) from every container dispatch, so per-layer cost
    # attribution sees each layer's actual inputs AND its params slice
    # (nested containers' OO-shell .params is None; only the dispatched
    # slice is real)
    _probe = None

    def __init__(self):
        self._name: Optional[str] = None
        # OO shell state (not used by the functional path)
        self.params: Params = None
        self.buffers: Buffers = {}
        self.grad_params: Params = None
        self.output: Activity = None
        self.grad_input: Activity = None
        self.train: bool = True
        self.forward_time: float = 0.0
        self.backward_time: float = 0.0
        self._jit_cache: dict = {}
        self._rng = None
        self._vjp_fun = None
        self._batch_buckets: Optional[tuple] = None

    # ------------------------------------------------------------------ #
    # functional core                                                    #
    # ------------------------------------------------------------------ #
    def init(self, rng: jax.Array) -> Params:
        """Create trainable parameters. Default: none."""
        return {}

    def init_buffers(self) -> Buffers:
        return {}

    def f(self, params: Params, x: Activity, *, training: bool = False,
          rng: Optional[jax.Array] = None) -> Activity:
        raise NotImplementedError(f"{type(self).__name__} must implement f() or apply()")

    def apply(self, params: Params, x: Activity, *, buffers: Buffers = None,
              training: bool = False, rng: Optional[jax.Array] = None):
        """Pure forward. Returns (output, new_buffers)."""
        y = self.f(params, x, training=training, rng=rng)
        return y, (buffers if buffers is not None else {})

    # ------------------------------------------------------------------ #
    # parameter bookkeeping                                              #
    # ------------------------------------------------------------------ #
    def has_params(self) -> bool:
        leaves = jax.tree_util.tree_leaves(self.init(jax.random.PRNGKey(0))) \
            if self.params is None else jax.tree_util.tree_leaves(self.params)
        return len(leaves) > 0

    def set_name(self, name: str) -> "Module":
        self._name = name
        return self

    def get_name(self) -> str:
        return self._name or type(self).__name__

    # ------------------------------------------------------------------ #
    # Torch-style OO shell                                               #
    # ------------------------------------------------------------------ #
    def build(self, seed: int | jax.Array = 0) -> "Module":
        """Materialize params/buffers on the shell (ref: modules are born
        initialized; here init is explicit because JAX params are pure)."""
        rng = jax.random.PRNGKey(seed) if isinstance(seed, int) else seed
        init_rng, self._rng = jax.random.split(rng)
        self.params = self.init(init_rng)
        self.buffers = self.init_buffers()
        self.zero_grad_parameters()
        return self

    def reset(self, seed: int | jax.Array = 0) -> "Module":
        return self.build(seed)

    def _built(self):
        if self.params is None:
            self.build()
        return self.params

    def _next_rng(self):
        if self._rng is None:
            self._rng = jax.random.PRNGKey(0)
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _jitted_apply(self, training: bool):
        key = ("apply", training)
        if key not in self._jit_cache:
            def run(params, buffers, x, rng):
                # quantized params: expand non-native QTensors here,
                # inside the trace — int8 stays the stored form, the
                # dequant fuses into the consumers (identity for f32
                # trees; see quant/transform.dequantize_entry)
                from bigdl_tpu.quant.transform import dequantize_entry
                params = dequantize_entry(params)
                return self.apply(params, x, buffers=buffers, training=training, rng=rng)
            self._jit_cache[key] = jax.jit(run)
        return self._jit_cache[key]

    def register_batch_buckets(self, buckets: Sequence[int]) -> "Module":
        """Pad eval-mode ``forward`` batches up to these leading-dim
        buckets so a novel batch size within a bucket reuses the cached
        jitted apply instead of retracing (every new leading dim is
        otherwise a fresh trace + XLA compile).  Inference only: the
        training path never pads — zero-filled rows would pollute
        buffer updates (BatchNorm stats) and loss scales.  Pass None to
        unregister.  ``serving.ServingEngine`` is the batched-traffic
        version of the same idea."""
        self._batch_buckets = (tuple(sorted(set(int(b) for b in buckets)))
                               if buckets is not None else None)
        if self._batch_buckets and self._batch_buckets[0] < 1:
            raise ValueError("buckets must be positive ints")
        return self

    def _bucket_batch(self, x) -> Optional[int]:
        """The bucket to pad ``x``'s leading dim to, or None for the
        exact-shape path (training mode, no buckets registered, non-
        array input, or batch larger than the largest bucket)."""
        buckets = getattr(self, "_batch_buckets", None)  # pre-bucket pickles
        if self.train or not buckets or not _is_array_like(x) \
                or getattr(x, "ndim", 0) < 1:
            return None
        n = int(x.shape[0])
        for b in buckets:
            if b >= n:
                return b if b != n else None  # exact hit: no pad needed
        return None

    def forward(self, x: Activity) -> Activity:
        """Stateful forward (ref AbstractModule.forward:144-150, with timing)."""
        self._built()
        t0 = time.perf_counter()
        rng = self._next_rng()
        bucket = self._bucket_batch(x)
        if bucket is not None:
            n = int(x.shape[0])
            pad = jnp.zeros((bucket - n,) + tuple(x.shape[1:]), x.dtype)
            xp = jnp.concatenate([jnp.asarray(x), pad], axis=0)
            y, _ = self._jitted_apply(self.train)(self.params, self.buffers, xp, rng)
            y = jax.tree_util.tree_map(
                lambda a: a[:n] if (_is_array_like(a)
                                    and getattr(a, "ndim", 0) >= 1
                                    and a.shape[0] == bucket) else a, y)
        else:
            y, new_buffers = self._jitted_apply(self.train)(self.params, self.buffers, x, rng)
            if self.train:
                self.buffers = new_buffers
        self.output = y
        self.forward_time += time.perf_counter() - t0
        return y

    def update_output(self, x: Activity) -> Activity:
        return self.forward(x)

    def backward(self, x: Activity, grad_output: Activity) -> Activity:
        """Stateful backward: computes gradInput AND accumulates parameter
        gradients (ref AbstractModule.backward:162-169 = updateGradInput +
        accGradParameters).  Implemented as one ``jax.vjp`` pullback over
        (params, input) — XLA derives what the reference hand-writes."""
        self._built()
        t0 = time.perf_counter()
        rng = self._next_rng()
        training = self.train

        key = ("vjp", training)
        if key not in self._jit_cache:
            def run(params, inp, g, buffers, rng_):
                def fwd(p, i):
                    y, _ = self.apply(p, i, buffers=buffers, training=training, rng=rng_)
                    return y
                _, pullback = jax.vjp(fwd, params, inp)
                return pullback(g)
            self._jit_cache[key] = jax.jit(run)
        grad_p, grad_in = self._jit_cache[key](self.params, x, grad_output, self.buffers, rng)
        if self.grad_params is None:
            self.grad_params = grad_p
        else:
            self.grad_params = jax.tree_util.tree_map(jnp.add, self.grad_params, grad_p)
        self.grad_input = grad_in
        self.backward_time += time.perf_counter() - t0
        return grad_in

    def update_grad_input(self, x: Activity, grad_output: Activity) -> Activity:
        """Gradient w.r.t. input only (no param-grad accumulation)."""
        self._built()
        rng = self._next_rng()
        training = self.train

        def fwd(inp):
            y, _ = self.apply(self.params, inp, buffers=self.buffers, training=training, rng=rng)
            return y

        _, pullback = jax.vjp(fwd, x)
        (grad_in,) = pullback(grad_output)
        self.grad_input = grad_in
        return grad_in

    def acc_grad_parameters(self, x: Activity, grad_output: Activity) -> None:
        self._built()
        rng = self._next_rng()
        training = self.train

        def fwd(params):
            y, _ = self.apply(params, x, buffers=self.buffers, training=training, rng=rng)
            return y

        _, pullback = jax.vjp(fwd, self.params)
        (grad_p,) = pullback(grad_output)
        if self.grad_params is None:
            self.grad_params = grad_p
        else:
            self.grad_params = jax.tree_util.tree_map(jnp.add, self.grad_params, grad_p)

    def zero_grad_parameters(self) -> None:
        if self.params is not None:
            self.grad_params = jax.tree_util.tree_map(jnp.zeros_like, self.params)

    def parameters(self):
        """(weights, gradWeights) as parallel leaf lists (ref :227)."""
        self._built()
        w = jax.tree_util.tree_leaves(self.params)
        g = jax.tree_util.tree_leaves(self.grad_params)
        return w, g

    def get_parameters(self):
        """Flatten all params (and grads) each into ONE contiguous vector
        (ref getParameters/Module.flatten, nn/Module.scala:41 — the
        flattened-storage trick becomes pytree ravel)."""
        from jax.flatten_util import ravel_pytree
        self._built()
        flat_w, unravel = ravel_pytree(self.params)
        flat_g, _ = ravel_pytree(self.grad_params)
        return flat_w, flat_g, unravel

    def get_parameters_table(self):
        """name -> {weight, bias, gradWeight, gradBias} (ref :242)."""
        from bigdl_tpu.utils.table import T
        self._built()
        table = T()
        self._collect_param_table(table, self.get_name(), self.params, self.grad_params)
        return table

    def _collect_param_table(self, table, name, params, grads):
        if isinstance(params, dict) and params:
            entry = T()
            for k, v in params.items():
                if _is_array_like(v):
                    entry[k] = v
                    gv = grads[k] if grads is not None and k in grads else None
                    entry["grad" + k[0].upper() + k[1:]] = gv
            if len(entry):
                table[name] = entry

    # -- mode/flags ----------------------------------------------------- #
    def training(self) -> "Module":
        self.train = True
        return self

    def evaluate(self) -> "Module":
        self.train = False
        return self

    def is_training(self) -> bool:
        return self.train

    # -- timing (ref :125-135) ------------------------------------------ #
    def get_times(self):
        return [(self, self.forward_time, self.backward_time)]

    def reset_times(self) -> None:
        self.forward_time = 0.0
        self.backward_time = 0.0

    def clear_state(self) -> "Module":
        self.output = None
        self.grad_input = None
        return self

    # -- (de)materialization -------------------------------------------- #
    def clone_module(self) -> "Module":
        """Clone sharing nothing (ref cloneModule via java ser, :284)."""
        import copy
        new = copy.copy(self)
        new._jit_cache = {}
        new.params = jax.tree_util.tree_map(lambda a: a, self.params) if self.params is not None else None
        new.buffers = jax.tree_util.tree_map(lambda a: a, self.buffers)
        new.grad_params = jax.tree_util.tree_map(lambda a: a, self.grad_params) if self.grad_params is not None else None
        return new

    def save(self, path: str, overwrite: bool = False) -> "Module":
        from bigdl_tpu.utils import file_io
        file_io.save_module(self, path, overwrite=overwrite)
        return self

    @staticmethod
    def load(path: str, template: "Optional[Module]" = None) -> "Module":
        """Load a saved module.  Pass ``template`` (a code-constructed
        instance of the architecture) to restore arrays into it without
        consulting the checkpoint's class names — immune to renames."""
        from bigdl_tpu.utils import file_io
        return file_io.load_module(path, template=template)

    def save_torch(self, path: str, overwrite: bool = False) -> "Module":
        """Write a Torch7-readable .t7 (ref AbstractModule.saveTorch)."""
        from bigdl_tpu.utils import torch_file
        torch_file.save_model(self, path, overwrite=overwrite)
        return self

    @staticmethod
    def load_torch(path: str) -> "Module":
        """Load a Torch7 .t7 model (ref Module.loadTorch, nn/Module.scala:31)."""
        from bigdl_tpu.utils import torch_file
        return torch_file.load_model(path)

    def load_caffe(self, def_path: str, model_path: str,
                   match_all: bool = True) -> "Module":
        """Copy caffe blobs into this model's same-named modules
        (ref Module.loadCaffe, nn/Module.scala:35-39)."""
        from bigdl_tpu.utils import caffe_loader
        self._built()
        return caffe_loader.load(self, def_path, model_path, match_all)

    def save_pytorch(self, path) -> "Module":
        """Write this model's params/buffers as a ``torch.save``d
        PyTorch-convention state dict.  The file round-trips through
        ``load_pytorch``; loading it into an actual torch module needs
        a positional key rename plus ``strict=False`` (we emit no
        ``num_batches_tracked``), and recurrent cells export our fused
        layout, which torch RNN modules cannot consume (see
        utils/torch_import.export_torch_state_dict)."""
        import torch
        from bigdl_tpu.utils import torch_import
        sd = torch_import.export_torch_state_dict(self)
        # np.array: forced writable copy — jax-backed arrays are
        # read-only views torch.from_numpy warns about and documents
        # mutating as UB
        torch.save({k: torch.from_numpy(np.array(v))
                    for k, v in sd.items()}, path)
        return self

    def load_pytorch(self, state_dict_or_path, strict: bool = True) -> "Module":
        """Import a PyTorch state dict (or a ``torch.save``d checkpoint
        path) into this model — the modern pretrained-import path (ref
        example/loadmodel/ModelValidator.scala's role; see
        utils/torch_import.py for the positional mapping contract)."""
        import os
        from bigdl_tpu.utils import torch_import
        self._built()
        if isinstance(state_dict_or_path, (str, bytes, os.PathLike)):
            return torch_import.load_torch_checkpoint(
                self, state_dict_or_path, strict=strict)
        return torch_import.load_torch_state_dict(
            self, state_dict_or_path, strict=strict)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_jit_cache"] = {}  # jitted callables are not picklable
        state["_vjp_fun"] = None
        return state

    def serve(self, **kwargs) -> "Any":
        """This built module as a servable endpoint — see
        :class:`bigdl_tpu.serving.ServingEngine` for the knobs
        (buckets, max_batch_size, max_wait_ms, backpressure)."""
        from bigdl_tpu.serving import ServingEngine
        self._built()
        return ServingEngine(self, **kwargs)

    def quantize(self, dtype: str = "int8", *, policy=None,
                 compute: Optional[str] = None) -> "Module":
        """Weight-only quantized EVAL-MODE clone of this built module
        (``self`` keeps its f32 params untouched — both replicas can be
        served side by side, the compile cache keys them apart).

        ``dtype="int8"``: eligible weights become
        :class:`~bigdl_tpu.quant.QTensor` (int8 + per-channel f32
        scales).  ``compute`` picks the kernel regime: the default
        ``"dequant"`` dequantizes on the fly inside the MXU kernel
        (bf16 operands, f32 accumulation); ``"int8"`` quantizes
        activations per token and feeds BOTH int8 operands to the MXU
        with exact int32 accumulation and one f32 rescale; ``"auto"``
        follows the measured int8-vs-dequant duel in ops/autotune.py;
        ``"fp8"`` gates on capable device kinds.  ``dtype="bf16"``: a
        plain storage cast.  The include/exclude ``policy`` defaults
        skip norms, biases and embedding tables (see quant.QuantPolicy);
        an explicit ``policy`` wins over ``compute``.

        The clone is inference-only: its int8 leaves are not
        differentiable, so train on the f32 original and re-quantize.
        Byte savings, per-layer max abs dequant error and (for int8
        compute) the int32-accumulator overflow-risk gauge are published
        as ``quant/*`` gauges on the obs registry and kept on
        ``clone.quant_report``.
        """
        from bigdl_tpu.obs import get_registry
        from bigdl_tpu.quant import QuantPolicy, quantize_params
        self._built()
        if policy is None and compute is not None:
            policy = QuantPolicy(dtype, compute=compute)
        report: dict = {}
        new = self.clone_module()
        new.params = quantize_params(self.params, dtype, policy=policy,
                                     module=self, report=report)
        new.grad_params = None  # int8 leaves are not differentiable
        new.quant_report = report
        reg = get_registry()
        reg.gauge("quant/bytes_saved", unit="B").set(report["bytes_saved"])
        reg.gauge("quant/payload_ratio").set(report["payload_ratio"])
        reg.gauge("quant/max_abs_dequant_error").set(
            report["max_abs_dequant_error"])
        for path, err in report["per_layer_max_abs_err"].items():
            reg.gauge(f"quant/max_abs_dequant_error/{path}").set(err)
        if report.get("per_layer_overflow_risk"):
            reg.gauge("quant/overflow_risk").set(report["overflow_risk"])
            for path, risk in report["per_layer_overflow_risk"].items():
                reg.gauge(f"quant/overflow_risk/{path}").set(risk)
        return new.evaluate()

    def __repr__(self) -> str:
        return f"{type(self).__name__}"

    # predict / evaluate conveniences are provided by optim.* and models.*


class Criterion:
    """Loss base (ref AbstractCriterion).  Subclasses implement
    ``loss(output, target) -> scalar`` as a pure function."""

    def __init__(self):
        self.output: Optional[jnp.ndarray] = None
        self.grad_input: Activity = None
        self._jit_cache: dict = {}

    def loss(self, output: Activity, target: Activity) -> jnp.ndarray:
        raise NotImplementedError

    def _flat_time_reduction(self) -> Optional[str]:
        """How this loss reduces a batch, IF flattening extra leading
        structure into the batch dim is value-equivalent: "mean" /
        "sum", or None when it is not (e.g. per-call weighted
        normalization).  TimeDistributedCriterion uses this to evaluate
        (B, T, ...) as one (B*T, ...) call instead of tracing T
        per-timestep calls — at long context the unrolled trace is
        O(T) compile time and HLO size."""
        return None

    # functional aliases
    def apply(self, output: Activity, target: Activity) -> jnp.ndarray:
        return self.loss(output, target)

    # Torch-style shell
    def forward(self, output: Activity, target: Activity) -> jnp.ndarray:
        if "fwd" not in self._jit_cache:
            self._jit_cache["fwd"] = jax.jit(self.loss)
        self.output = self._jit_cache["fwd"](output, target)
        return self.output

    def backward(self, output: Activity, target: Activity) -> Activity:
        if "bwd" not in self._jit_cache:
            self._jit_cache["bwd"] = jax.jit(
                lambda o, t: jax.grad(lambda oo: self.loss(oo, t).sum())(o)
            )
        self.grad_input = self._jit_cache["bwd"](output, target)
        return self.grad_input

    def update_output(self, output, target):
        return self.forward(output, target)

    def update_grad_input(self, output, target):
        return self.backward(output, target)

    def clone_criterion(self) -> "Criterion":
        import copy
        new = copy.copy(self)
        new._jit_cache = {}
        return new

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_jit_cache"] = {}
        return state

    def __repr__(self) -> str:
        return f"{type(self).__name__}"
