"""Parameter initialization methods (ref nn/InitializationMethod.scala:22:
Default, Xavier, BilinearFiller).

``Default`` reproduces Torch's per-layer fan-based uniform; ``Xavier`` the
Glorot uniform.  Draws use ``jax.random`` (fast, on-device); Torch-MT19937
bit-parity, when a test needs it, is obtained by setting weights explicitly
from ``bigdl_tpu.utils.rng.RandomGenerator`` draws.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


class InitializationMethod:
    name = "default"


class Default(InitializationMethod):
    name = "default"

    @staticmethod
    def weight(rng, shape, fan_in):
        stdv = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(rng, shape, minval=-stdv, maxval=stdv, dtype=jnp.float32)

    bias = weight


class Xavier(InitializationMethod):
    name = "xavier"

    @staticmethod
    def weight(rng, shape, fan_in, fan_out=None):
        if fan_out is None:
            fan_out = shape[0] if len(shape) > 1 else fan_in
        stdv = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, minval=-stdv, maxval=stdv, dtype=jnp.float32)

    @staticmethod
    def bias(rng, shape, fan_in):
        return jnp.zeros(shape, dtype=jnp.float32)


class BilinearFiller(InitializationMethod):
    """Bilinear-upsampling kernel init for SpatialFullConvolution
    (ref nn/InitializationMethod.scala BilinearFiller)."""
    name = "bilinearfiller"

    @staticmethod
    def weight(rng, shape, fan_in=None):
        # shape: (nInput, nOutput, kH, kW) or (nOutput, nInput, kH, kW)
        kh, kw = shape[-2], shape[-1]
        f = math.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype=np.float32)
        flat = w.reshape(-1, kh * kw)
        for i in range(kh * kw):
            x = i % kw
            y = i // kw
            flat[:, i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(w)
