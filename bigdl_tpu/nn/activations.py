"""Activation layers (ref nn/: ReLU, Tanh, Sigmoid, SoftMax, ... one Scala
file each; here thin pure functions over jnp — XLA fuses them into adjacent
matmuls/convs, which is the TPU answer to the reference's MKL VML calls
(tensor/TensorNumeric.scala:180-420)).

All are stateless TensorModules except PReLU (learnable) and RReLU
(stochastic in training).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class ReLU(Module):
    def __init__(self, ip: bool = False):
        super().__init__()
        self.ip = ip  # in-place flag kept for API parity; meaningless under XLA

    def f(self, params, x, **kw):
        return jnp.maximum(x, 0)


class ReLU6(Module):
    def f(self, params, x, **kw):
        return jnp.clip(x, 0, 6)


class GELU(Module):
    """Gaussian error linear unit (post-reference capability, the
    transformer stack's activation).  ``approximate=True`` is the tanh
    form — one less erf on the VPU, the usual TPU choice."""

    def __init__(self, approximate: bool = True):
        super().__init__()
        self.approximate = approximate

    def f(self, params, x, **kw):
        return jax.nn.gelu(x, approximate=self.approximate)


class Tanh(Module):
    def f(self, params, x, **kw):
        return jnp.tanh(x)


class Sigmoid(Module):
    def f(self, params, x, **kw):
        return jax.nn.sigmoid(x)


class SoftMax(Module):
    """Softmax over the last dim for 1D/2D input (ref nn/SoftMax.scala)."""

    def f(self, params, x, **kw):
        return jax.nn.softmax(x, axis=-1)


class SoftMin(Module):
    def f(self, params, x, **kw):
        return jax.nn.softmax(-x, axis=-1)


class LogSoftMax(Module):
    def f(self, params, x, **kw):
        return jax.nn.log_softmax(x, axis=-1)


class LogSigmoid(Module):
    def f(self, params, x, **kw):
        return jax.nn.log_sigmoid(x)


class SoftPlus(Module):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def f(self, params, x, **kw):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(Module):
    def f(self, params, x, **kw):
        return x / (1 + jnp.abs(x))


class LeakyReLU(Module):
    def __init__(self, negval: float = 0.01, inplace: bool = False):
        super().__init__()
        self.negval = negval

    def f(self, params, x, **kw):
        return jnp.where(x > 0, x, self.negval * x)


class ELU(Module):
    def __init__(self, alpha: float = 1.0, inplace: bool = False):
        super().__init__()
        self.alpha = alpha

    def f(self, params, x, **kw):
        return jnp.where(x > 0, x, self.alpha * (jnp.exp(x) - 1))


class PReLU(Module):
    """Learnable leaky slope; n_output_plane=0 means one shared slope
    (ref nn/PReLU.scala)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane

    def init(self, rng):
        n = max(self.n_output_plane, 1)
        return {"weight": jnp.full((n,), 0.25, dtype=jnp.float32)}

    def f(self, params, x, **kw):
        w = params["weight"]
        if self.n_output_plane > 0 and x.ndim > 1:
            # per-channel slope: channel dim is 1 for batched input (N,C,...)
            # or 0 for unbatched (C,...); prefer the axis whose size matches.
            n = self.n_output_plane
            if x.shape[1] == n:
                ch_axis = 1
            elif x.shape[0] == n:
                ch_axis = 0
            else:
                raise ValueError(
                    f"PReLU({n}): no input dim of size {n} in shape {x.shape}")
            shape = [1] * x.ndim
            shape[ch_axis] = n
            w = w.reshape(shape)
        return jnp.where(x > 0, x, w * x)


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U(lower, upper) in training, fixed
    mean slope in eval (ref nn/RReLU.scala)."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 inplace: bool = False):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def f(self, params, x, *, training=False, rng=None, **kw):
        if training and rng is not None:
            a = jax.random.uniform(rng, x.shape, minval=self.lower, maxval=self.upper)
        else:
            a = (self.lower + self.upper) / 2
        return jnp.where(x >= 0, x, a * x)


class HardTanh(Module):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 inplace: bool = False):
        super().__init__()
        self.min_value = min_value
        self.max_value = max_value

    def f(self, params, x, **kw):
        return jnp.clip(x, self.min_value, self.max_value)


class HardShrink(Module):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def f(self, params, x, **kw):
        return jnp.where(jnp.abs(x) > self.lam, x, 0.0)


class SoftShrink(Module):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def f(self, params, x, **kw):
        return jnp.where(x > self.lam, x - self.lam,
                         jnp.where(x < -self.lam, x + self.lam, 0.0))


class TanhShrink(Module):
    def f(self, params, x, **kw):
        return x - jnp.tanh(x)


class Threshold(Module):
    """x if x > th else v (ref nn/Threshold.scala)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__()
        self.th = th
        self.v = v
        self.ip = ip  # in-place flag kept for API parity; meaningless under XLA

    def f(self, params, x, **kw):
        return jnp.where(x > self.th, x, self.v)


class Clamp(HardTanh):
    def __init__(self, min_value: float, max_value: float):
        super().__init__(min_value, max_value)


class Power(Module):
    """(shift + scale*x)^power (ref nn/Power.scala)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power = power
        self.scale = scale
        self.shift = shift

    def f(self, params, x, **kw):
        return jnp.power(self.shift + self.scale * x, self.power)


class Square(Module):
    def f(self, params, x, **kw):
        return jnp.square(x)


class Sqrt(Module):
    def f(self, params, x, **kw):
        return jnp.sqrt(x)


class Log(Module):
    def f(self, params, x, **kw):
        return jnp.log(x)


class Exp(Module):
    def f(self, params, x, **kw):
        return jnp.exp(x)


class Abs(Module):
    def f(self, params, x, **kw):
        return jnp.abs(x)
