"""Criterions (ref nn/: ClassNLLCriterion, MSECriterion, BCECriterion, ...,
~25 losses; each was a Scala file with hand-written updateOutput and
updateGradInput — here each is one pure ``loss`` function and the gradient
is derived by XLA).

Conventions preserved from Torch/BigDL: class targets are **1-based**;
``size_average=True`` (the default) means mean-reduction over the batch.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion
from bigdl_tpu.utils.table import Table


def _seq(x):
    return x.to_seq() if isinstance(x, Table) else list(x)


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities, 1-based integer
    targets, optional per-class weights (ref nn/ClassNLLCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def loss(self, output, target):
        if output.ndim == 1:
            output = output[None]
            target = jnp.atleast_1d(target)
        idx = target.astype(jnp.int32) - 1
        picked = jnp.take_along_axis(output, idx[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, idx)
            total = -jnp.sum(w * picked)
            return total / jnp.sum(w) if self.size_average else total
        return _reduce(-picked, self.size_average)

    def _flat_time_reduction(self):
        if self.weights is not None:
            # weighted size_average normalizes by each call's own
            # weight sum — flattening changes the normalizer; the
            # weighted SUM has no normalizer and flattens exactly
            return None if self.size_average else "sum"
        return "mean" if self.size_average else "sum"


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (ref nn/CrossEntropyCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self._nll = ClassNLLCriterion(weights, size_average)

    def loss(self, output, target):
        return self._nll.loss(jax.nn.log_softmax(output, axis=-1), target)

    def _flat_time_reduction(self):
        return self._nll._flat_time_reduction()  # softmax is per-row


class MSECriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jnp.square(output - target), self.size_average)

    def _flat_time_reduction(self):
        # mean/sum over ALL elements: equal per-timestep element counts
        # make the flattened call value-identical
        return "mean" if self.size_average else "sum"


class AbsCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jnp.abs(output - target), self.size_average)


class BCECriterion(Criterion):
    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def loss(self, output, target):
        eps = 1e-12
        per = -(target * jnp.log(output + eps) + (1 - target) * jnp.log(1 - output + eps))
        if self.weights is not None:
            per = per * self.weights
        return _reduce(per, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL(target || output) with output already log-probabilities
    (ref nn/DistKLDivCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        per = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - output), 0.0)
        return _reduce(per, self.size_average)


class SmoothL1Criterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        d = jnp.abs(output - target)
        per = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(per, self.size_average)


class SmoothL1CriterionWithWeights(Criterion):
    """Detection-style smooth-L1 with sigma scaling and inside/outside
    weights (ref nn/SmoothL1CriterionWithWeights.scala).  Target is a table
    {bbox_target, inside_w, outside_w}; ``num`` normalizes the sum."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def loss(self, output, target):
        tgt, in_w, out_w = _seq(target)
        d = in_w * (output - tgt)
        ad = jnp.abs(d)
        per = jnp.where(ad < 1.0 / self.sigma2,
                        0.5 * self.sigma2 * d * d,
                        ad - 0.5 / self.sigma2)
        total = jnp.sum(out_w * per)
        return total / self.num if self.num > 0 else total


class MarginCriterion(Criterion):
    """Hinge: max(0, margin - y*x) (ref nn/MarginCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jnp.maximum(0.0, self.margin - output * target), self.size_average)


class MarginRankingCriterion(Criterion):
    """max(0, -y*(x1-x2) + margin) over table input {x1, x2}
    (ref nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        x1, x2 = _seq(output)
        y = target[1] if isinstance(target, Table) else target
        return _reduce(jnp.maximum(0.0, -y * (x1 - x2) + self.margin), self.size_average)


class MultiMarginCriterion(Criterion):
    """Multiclass hinge loss, p in {1,2} (ref nn/MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__()
        self.p = p
        self.weights = None if weights is None else jnp.asarray(weights)
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        if output.ndim == 1:
            output = output[None]
            target = jnp.atleast_1d(target)
        n, c = output.shape
        idx = target.astype(jnp.int32) - 1
        x_y = jnp.take_along_axis(output, idx[:, None], axis=1)
        m = jnp.maximum(0.0, self.margin - x_y + output)
        if self.p == 2:
            m = m * m
        if self.weights is not None:
            m = m * jnp.take(self.weights, idx)[:, None]
        not_target = jnp.arange(c)[None, :] != idx[:, None]
        per = jnp.sum(jnp.where(not_target, m, 0.0), axis=1) / c
        return _reduce(per, self.size_average)


class MultiLabelMarginCriterion(Criterion):
    """Torch multilabel hinge: per sample, targets are 1-based class indices
    padded with 0 (ref nn/MultiLabelMarginCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        if output.ndim == 1:
            output = output[None]
            target = target[None]
        n, c = output.shape
        tgt = target.astype(jnp.int32)
        # valid targets: nonzero entries before the first zero
        first_zero = jnp.cumsum(tgt == 0, axis=1) > 0
        valid = (tgt > 0) & ~first_zero
        idx0 = jnp.clip(tgt - 1, 0, c - 1)
        # membership by comparison, not scatter: padding slots must not
        # collide with a real target on the clamp index (a .at[].set
        # with duplicate indices let a padded False overwrite class C's
        # True whenever C was a target — counting it as a non-target)
        is_target = jnp.any(
            valid[:, :, None] & (idx0[:, :, None] == jnp.arange(c)), axis=1)
        x_t = jnp.where(valid, jnp.take_along_axis(output, idx0, axis=1), 0.0)  # (n, K)
        # for each valid target t and each non-target j: max(0, 1 - (x_t - x_j))
        diff = 1.0 - x_t[:, :, None] + output[:, None, :]  # (n, K, C)
        hinge = jnp.maximum(0.0, diff)
        mask = valid[:, :, None] & ~is_target[:, None, :]
        per = jnp.sum(jnp.where(mask, hinge, 0.0), axis=(1, 2)) / c
        return _reduce(per, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid + BCE multilabel loss (ref nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def loss(self, output, target):
        per = jax.nn.softplus(-output) * target + jax.nn.softplus(output) * (1 - target)
        if self.weights is not None:
            per = per * self.weights
        if output.ndim > 1:
            per = jnp.sum(per, axis=-1) / output.shape[-1]
        return _reduce(per, self.size_average)


class SoftMarginCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jax.nn.softplus(-output * target), self.size_average)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        per = jnp.where(target == 1, output, jnp.maximum(0.0, self.margin - output))
        return _reduce(per, self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """Hinge on the L1 distance of a pair {x1, x2}
    (ref nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def loss(self, output, target):
        x1, x2 = _seq(output)
        d = jnp.sum(jnp.abs(x1 - x2))
        y = target if jnp.ndim(target) == 0 else target.reshape(())
        return jnp.where(y == 1, d, jnp.maximum(0.0, self.margin - d))


class CosineEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        x1, x2 = _seq(output)
        if x1.ndim == 1:
            x1, x2 = x1[None], x2[None]
        y = target[1] if isinstance(target, Table) else target
        y = jnp.reshape(y, (-1,))
        cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(per, self.size_average)


class ClassSimplexCriterion(Criterion):
    """MSE against a regular-simplex embedding of the target class
    (ref nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes: int):
        super().__init__()
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._build_simplex(n_classes))

    @staticmethod
    def _build_simplex(n):
        """n unit vectors in R^n with pairwise dot -1/n (Cholesky-style
        recursive construction of the regular simplex)."""
        import numpy as np
        mat = np.zeros((n, n), dtype=np.float64)
        for k in range(n):
            mat[k, k] = np.sqrt(max(1.0 - float(np.dot(mat[k, :k], mat[k, :k])), 0.0))
            if mat[k, k] > 0:
                for c in range(k + 1, n):
                    mat[c, k] = (-1.0 / n - float(np.dot(mat[k, :k], mat[c, :k]))) / mat[k, k]
        return mat.astype(np.float32)

    def loss(self, output, target):
        idx = target.astype(jnp.int32) - 1
        tgt = jnp.take(self.simplex, idx, axis=0)
        return jnp.mean(jnp.square(output - tgt))


class L1Cost(Criterion):
    """Sum of absolute values; target ignored (ref nn/L1Cost.scala)."""

    def loss(self, output, target=None):
        return jnp.sum(jnp.abs(output))


class SoftmaxWithCriterion(Criterion):
    """Caffe SoftmaxWithLoss: softmax + NLL with ignore_label and
    normalization modes (ref nn/SoftmaxWithCriterion.scala).  Input is
    (N, C, ...) raw scores."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID"):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def loss(self, output, target):
        logp = jax.nn.log_softmax(output, axis=1)
        idx = target.astype(jnp.int32) - 1
        picked = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0] \
            if logp.ndim == 2 else jnp.take_along_axis(
                logp, idx[:, None], axis=1).squeeze(1)
        if self.ignore_label is not None:
            validm = target.astype(jnp.int32) != self.ignore_label
            picked = jnp.where(validm, picked, 0.0)
            count = jnp.sum(validm)
        else:
            validm = None
            count = picked.size
        total = -jnp.sum(picked)
        if self.normalize_mode == "VALID":
            return total / jnp.maximum(count, 1)
        if self.normalize_mode == "BATCH_SIZE":
            return total / output.shape[0]
        if self.normalize_mode == "FULL":
            return total / picked.size
        return total  # NONE


class ParallelCriterion(Criterion):
    """Weighted sum of member criterions applied to corresponding
    input/target table slots (ref nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self.criterions: list[Criterion] = []
        self.weights: list[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, output, target):
        outs = _seq(output)
        tgts = [target] * len(outs) if self.repeat_target else _seq(target)
        total = 0.0
        for crit, w, o, t in zip(self.criterions, self.weights, outs, tgts):
            total = total + w * crit.loss(o, t)
        return total


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the SAME input/target
    (ref nn/MultiCriterion.scala)."""

    def __init__(self):
        super().__init__()
        self.criterions: list[Criterion] = []
        self.weights: list[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "MultiCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, output, target):
        total = 0.0
        for crit, w in zip(self.criterions, self.weights):
            total = total + w * crit.loss(output, target)
        return total


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (batch, time, ...) output
    (ref nn/TimeDistributedCriterion.scala)."""

    def __init__(self, criterion: Criterion, size_average: bool = False):
        super().__init__()
        self.criterion = criterion
        self.size_average = size_average

    def loss(self, output, target):
        t_steps = output.shape[1]
        if t_steps == 0:
            # the old per-timestep loop summed zero iterations; keep a
            # defined zero instead of NaN (mean of empty) or a
            # ZeroDivisionError (size_average)
            return jnp.zeros((), jnp.float32)
        red = self.criterion._flat_time_reduction()
        if red is not None:
            # one flattened call instead of T traced per-timestep calls:
            # the unrolled loop costs O(T) trace time and HLO size — at
            # the long-context T=16384 LM shapes that is the difference
            # between compiling in seconds and burning the measurement
            # window.  "mean" inner losses recover the per-timestep SUM
            # as flat_mean * T (equal element counts per step).
            flat_o = jnp.reshape(output, (-1,) + output.shape[2:])
            flat_t = jnp.reshape(target, (-1,) + target.shape[2:])
            flat = self.criterion.loss(flat_o, flat_t)
            if red == "mean":
                # mean+size_average IS the flat mean — no *T/T round trip
                return flat if self.size_average else flat * t_steps
            return flat / t_steps if self.size_average else flat
        # generic criterion (weighted normalizers etc.): lax.scan over
        # the time axis compiles the body ONCE; the python loop it
        # replaces unrolled T copies into the trace.  Accumulate in f32
        # for stability, return in the inner loss's own dtype (what both
        # the old loop and the flat path produce).
        o_t = jnp.moveaxis(output, 1, 0)
        y_t = jnp.moveaxis(target, 1, 0)
        out_dtype = jax.eval_shape(self.criterion.loss, o_t[0], y_t[0]).dtype

        def body(carry, xt):
            o, y = xt
            return carry + self.criterion.loss(o, y).astype(jnp.float32), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (o_t, y_t))
        total = total.astype(out_dtype)
        return total / t_steps if self.size_average else total


class CriterionTable(Criterion):
    """Wrap a criterion so (input, target) both come from one table
    (ref nn/CriterionTable.scala)."""

    def __init__(self, criterion: Criterion):
        super().__init__()
        self.criterion = criterion

    def loss(self, output, target=None):
        xs = _seq(output)
        return self.criterion.loss(xs[0], xs[1] if len(xs) > 1 else target)
