"""Linear-algebra layers (ref nn/: Linear, Bilinear, MM, MV, Cosine,
Euclidean, DotProduct, PairwiseDistance, CosineDistance, LookupTable, and
the scalar/affine family Add/AddConstant/Mul/MulConstant/CMul/CAdd/Scale).

Weight layouts preserve Torch conventions for import parity: Linear weight
is (outputSize, inputSize) and y = x @ W.T + b (ref nn/Linear.scala).
The matmul is the MXU path — XLA tiles it onto the 128x128 systolic array;
there is no BLAS dispatch layer to write (ref tensor/DenseTensorBLAS.scala
collapses into one jnp.dot).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.initialization import Default, InitializationMethod, Xavier
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn._util import match_compute_dtype
from bigdl_tpu.quant.qtensor import is_qtensor
from bigdl_tpu.utils.table import Table


def _pair(x):
    return x.to_seq() if isinstance(x, Table) else list(x)


class Linear(Module):
    """Fully connected layer (ref nn/Linear.scala, 218 LoC)."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 init_method: type[InitializationMethod] = Default):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.init_method = init_method

    def init(self, rng):
        wk, bk = jax.random.split(rng)
        p = {"weight": self.init_method.weight(
            wk, (self.output_size, self.input_size), fan_in=self.input_size)}
        if self.with_bias:
            p["bias"] = self.init_method.bias(bk, (self.output_size,), fan_in=self.input_size)
        return p

    def f(self, params, x, **kw):
        w = params["weight"]
        if is_qtensor(w):
            from bigdl_tpu.quant.kernels import qlinear
            return qlinear(x, w, params.get("bias")
                           if self.with_bias else None)
        x = match_compute_dtype(jnp.asarray(x), w)
        y = x @ w.T
        if self.with_bias:
            y = y + params["bias"]
        return y


class Bilinear(Module):
    """y_k = x1 @ W_k @ x2 + b_k over a table input {x1, x2}
    (ref nn/Bilinear.scala)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True):
        super().__init__()
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res

    def init(self, rng):
        wk, bk = jax.random.split(rng)
        stdv = 1.0 / math.sqrt(self.input_size1)
        p = {"weight": jax.random.uniform(
            wk, (self.output_size, self.input_size1, self.input_size2),
            minval=-stdv, maxval=stdv)}
        if self.bias_res:
            p["bias"] = jax.random.uniform(bk, (self.output_size,), minval=-stdv, maxval=stdv)
        return p

    def f(self, params, x, **kw):
        x1, x2 = _pair(x)
        y = jnp.einsum("bi,kij,bj->bk", x1, params["weight"], x2)
        if self.bias_res:
            y = y + params["bias"]
        return y


class MM(Module):
    """Batch or plain matrix-matrix product of a table {A, B}
    (ref nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a = trans_a
        self.trans_b = trans_b

    def f(self, params, x, **kw):
        a, b = _pair(x)
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MV(Module):
    """Matrix-vector product of a table {M, v} (ref nn/MV.scala)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def f(self, params, x, **kw):
        m, v = _pair(x)
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class DotProduct(Module):
    """Row-wise dot product of a table {x1, x2} (ref nn/DotProduct.scala)."""

    def f(self, params, x, **kw):
        x1, x2 = _pair(x)
        return jnp.sum(x1 * x2, axis=-1)


class Cosine(Module):
    """Cosine similarity to each of ``output_size`` learned prototypes
    (ref nn/Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size

    def init(self, rng):
        stdv = 1.0 / math.sqrt(self.input_size)
        return {"weight": jax.random.uniform(
            rng, (self.output_size, self.input_size), minval=-stdv, maxval=stdv)}

    def f(self, params, x, **kw):
        w = params["weight"]
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        wn = w / (jnp.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
        return xn @ wn.T


class Euclidean(Module):
    """Euclidean distance to each learned prototype (ref nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int, fast_backward: bool = True):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size

    def init(self, rng):
        stdv = 1.0 / math.sqrt(self.input_size)
        return {"weight": jax.random.uniform(
            rng, (self.output_size, self.input_size), minval=-stdv, maxval=stdv)}

    def f(self, params, x, **kw):
        diff = x[..., None, :] - params["weight"]
        return jnp.linalg.norm(diff, axis=-1)


class PairwiseDistance(Module):
    """L-p distance between table elements {x1, x2} (ref nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def f(self, params, x, **kw):
        x1, x2 = _pair(x)
        d = jnp.abs(x1 - x2)
        return jnp.power(jnp.sum(jnp.power(d, self.norm), axis=-1), 1.0 / self.norm)


class CosineDistance(Module):
    """Cosine similarity between table elements {x1, x2}
    (ref nn/CosineDistance.scala)."""

    def f(self, params, x, **kw):
        x1, x2 = _pair(x)
        n1 = jnp.linalg.norm(x1, axis=-1)
        n2 = jnp.linalg.norm(x2, axis=-1)
        return jnp.sum(x1 * x2, axis=-1) / jnp.maximum(n1 * n2, 1e-12)


class LookupTable(Module):
    """Embedding lookup with 1-based indices and optional max-norm
    renormalization (ref nn/LookupTable.scala)."""

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False):
        super().__init__()
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type

    def init(self, rng):
        return {"weight": jax.random.normal(rng, (self.n_index, self.n_output))}

    def f(self, params, x, **kw):
        w = params["weight"]
        if self.max_norm != float("inf"):
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=-1, keepdims=True)
            w = jnp.where(norms > self.max_norm, w * (self.max_norm / norms), w)
        idx = x.astype(jnp.int32) - 1  # 1-based Torch indices
        return jnp.take(w, idx, axis=0)


# ---------------------------------------------------------------------- #
# scalar / affine family                                                 #
# ---------------------------------------------------------------------- #
class Add(Module):
    """Learnable bias vector added to the input (ref nn/Add.scala)."""

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size

    def init(self, rng):
        stdv = 1.0 / math.sqrt(self.input_size)
        return {"bias": jax.random.uniform(rng, (self.input_size,), minval=-stdv, maxval=stdv)}

    def f(self, params, x, **kw):
        return x + params["bias"]


class AddConstant(Module):
    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant_scalar = constant_scalar

    def f(self, params, x, **kw):
        return x + self.constant_scalar


class Mul(Module):
    """Single learnable scalar gain (ref nn/Mul.scala)."""

    def init(self, rng):
        return {"weight": jax.random.uniform(rng, (1,), minval=-1.0, maxval=1.0)}

    def f(self, params, x, **kw):
        return x * params["weight"][0]


class MulConstant(Module):
    def __init__(self, scalar: float, inplace: bool = False):
        super().__init__()
        self.scalar = scalar

    def f(self, params, x, **kw):
        return x * self.scalar


class CMul(Module):
    """Learnable componentwise gain with broadcastable shape
    (ref nn/CMul.scala)."""

    def __init__(self, size: tuple[int, ...]):
        super().__init__()
        self.size = tuple(size)

    def init(self, rng):
        n = 1
        for s in self.size:
            n *= s
        stdv = 1.0 / math.sqrt(n)
        return {"weight": jax.random.uniform(rng, self.size, minval=-stdv, maxval=stdv)}

    def f(self, params, x, **kw):
        return x * params["weight"]


class CAdd(Module):
    """Learnable componentwise bias with broadcastable shape
    (ref nn/CAdd.scala)."""

    def __init__(self, size: tuple[int, ...]):
        super().__init__()
        self.size = tuple(size)

    def init(self, rng):
        n = 1
        for s in self.size:
            n *= s
        stdv = 1.0 / math.sqrt(n)
        return {"bias": jax.random.uniform(rng, self.size, minval=-stdv, maxval=stdv)}

    def f(self, params, x, **kw):
        return x + params["bias"]


class Scale(Module):
    """CMul then CAdd (ref nn/Scale.scala)."""

    def __init__(self, size: tuple[int, ...]):
        super().__init__()
        self.size = tuple(size)
        self._cmul = CMul(size)
        self._cadd = CAdd(size)

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"cmul": self._cmul.init(k1), "cadd": self._cadd.init(k2)}

    def f(self, params, x, **kw):
        return self._cadd.f(params["cadd"], self._cmul.f(params["cmul"], x))
