"""Composite modules (ref nn/Container.scala and the structural zoo:
Sequential, Concat, ConcatTable, ParallelTable, MapTable, Bottle,
FlattenTable, SplitTable, JoinTable, MixtureTable, NarrowTable, SelectTable).

The reference has no Graph/DAG module in v0.1 — DAGs are expressed with
Concat/ConcatTable + CAddTable (see ResNet shortcut,
models/resnet/ResNet.scala:142-205); same here.

Where the reference runs Concat branches on the ``Engine.model`` thread pool
(nn/Concat.scala:69,155), here the branches are traced into one XLA program
and the compiler schedules them — intra-op threading is not a framework
concern on TPU.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn._util import fold_rng, one_based_index, to_axis
from bigdl_tpu.nn.module import Activity, Buffers, Module, Params
from bigdl_tpu.utils.table import T, Table


class Container(Module):
    """Base of composites: owns an ordered child list; parameters are the
    dict {index: child_params} (ref nn/Container.scala)."""

    def __init__(self, *modules: Module):
        super().__init__()
        self.modules: list[Module] = list(modules)
        self.remat: bool = False

    def add(self, module: Module) -> "Container":
        self.modules.append(module)
        return self

    def checkpoint(self, enable: bool = True) -> "Container":
        """Rematerialize each child's activations in the backward pass
        (``jax.checkpoint`` per child): trades recompute FLOPs for HBM,
        the standard TPU memory knob for deep towers."""
        self.remat = enable
        return self

    def __len__(self) -> int:
        return len(self.modules)

    def get(self, index: int) -> Module:
        """1-based child access."""
        return self.modules[index - 1]

    def init(self, rng) -> Params:
        return {str(i): m.init(fold_rng(rng, i)) for i, m in enumerate(self.modules)}

    def init_buffers(self) -> Buffers:
        return {str(i): m.init_buffers() for i, m in enumerate(self.modules)}

    def _child_apply(self, i, params, x, buffers, training, rng):
        p = params.get(str(i), {}) if params else {}
        b_in = buffers.get(str(i), {}) if buffers else {}
        r = fold_rng(rng, i)
        if Module._probe is not None:
            Module._probe(self, i, self.modules[i], x, p, b_in)
        if getattr(self, "remat", False):
            # rematerialize child activations in the backward pass
            # (jax.checkpoint: trades FLOPs for HBM — the TPU-idiomatic
            # memory knob; the reference has no analog, its activations
            # live in JVM heap caches)
            def run(p, x, b_in):
                return self.modules[i].apply(p, x, buffers=b_in,
                                             training=training, rng=r)
            return jax.checkpoint(run)(p, x, b_in)
        return self.modules[i].apply(p, x, buffers=b_in,
                                     training=training, rng=r)

    # OO-shell aggregation (ref Container aggregates over children)
    def training(self) -> "Container":
        super().training()
        for m in self.modules:
            m.training()
        return self

    def evaluate(self) -> "Container":
        super().evaluate()
        for m in self.modules:
            m.evaluate()
        return self

    def get_times(self):
        out = super().get_times()
        for m in self.modules:
            out.extend(m.get_times())
        return out

    def reset_times(self) -> None:
        super().reset_times()
        for m in self.modules:
            m.reset_times()

    def _collect_param_table(self, table, name, params, grads):
        for i, m in enumerate(self.modules):
            child_g = grads[str(i)] if grads is not None else None
            m._collect_param_table(table, m.get_name() if m._name else f"{m.get_name()}@{i}",
                                   params[str(i)], child_g)

    def __repr__(self) -> str:
        inner = "\n".join(f"  ({i}): " + repr(m).replace("\n", "\n  ")
                          for i, m in enumerate(self.modules))
        return f"{type(self).__name__} {{\n{inner}\n}}"


class Sequential(Container):
    """Feed-forward chain (ref nn/Sequential.scala)."""

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        new_buffers = {}
        for i in range(len(self.modules)):
            x, b = self._child_apply(i, params, x, buffers, training, rng)
            new_buffers[str(i)] = b
        return x, new_buffers


class Concat(Container):
    """Apply every child to the same input; concatenate outputs along a
    1-based dimension (ref nn/Concat.scala)."""

    def __init__(self, dimension: int, *modules: Module):
        super().__init__(*modules)
        self.dimension = dimension

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        outs, new_buffers = [], {}
        for i in range(len(self.modules)):
            y, b = self._child_apply(i, params, x, buffers, training, rng)
            outs.append(y)
            new_buffers[str(i)] = b
        axis = to_axis(self.dimension, outs[0].ndim)
        return jnp.concatenate(outs, axis=axis), new_buffers


class DepthConcat(Concat):
    """Concat along the channel dim with spatial zero-padding to the
    largest branch output (torch nn.DepthConcat; the GoogLeNet-era
    building block whose branches emit different spatial sizes — the
    reference has no analog, it sizes its inception branches to match).
    Odd size differences pad like torch: the extra row/column goes after
    the centered map."""

    def __init__(self, *modules: Module):
        super().__init__(2, *modules)

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        outs, new_buffers = [], {}
        for i in range(len(self.modules)):
            y, b = self._child_apply(i, params, x, buffers, training, rng)
            outs.append(y)
            new_buffers[str(i)] = b
        spatial_axes = list(range(2, outs[0].ndim))
        if spatial_axes:
            targets = [max(o.shape[a] for o in outs) for a in spatial_axes]
            padded = []
            for o in outs:
                widths = [(0, 0)] * o.ndim
                for a, t in zip(spatial_axes, targets):
                    lead = (t - o.shape[a]) // 2
                    widths[a] = (lead, t - o.shape[a] - lead)
                padded.append(jnp.pad(o, widths) if any(
                    w != (0, 0) for w in widths) else o)
            outs = padded
        axis = to_axis(self.dimension, outs[0].ndim)
        return jnp.concatenate(outs, axis=axis), new_buffers


class ConcatTable(Container):
    """Apply every child to the same input; collect outputs into a Table
    (ref nn/ConcatTable.scala)."""

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        out, new_buffers = T(), {}
        for i in range(len(self.modules)):
            y, b = self._child_apply(i, params, x, buffers, training, rng)
            out.insert(y)
            new_buffers[str(i)] = b
        return out, new_buffers


class ParallelTable(Container):
    """Child i applied to input table element i (ref nn/ParallelTable.scala)."""

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        xs = x.to_seq() if isinstance(x, Table) else list(x)
        out, new_buffers = T(), {}
        for i in range(len(self.modules)):
            y, b = self._child_apply(i, params, xs[i], buffers, training, rng)
            out.insert(y)
            new_buffers[str(i)] = b
        return out, new_buffers


class MapTable(Container):
    """One shared child applied to every element of the input table
    (ref nn/MapTable.scala — clones share weights; here the same params
    pytree is literally reused, the functional analog of storage aliasing)."""

    def __init__(self, module: Module):
        super().__init__(module)

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        xs = x.to_seq() if isinstance(x, Table) else list(x)
        out = T()
        b = buffers.get("0", {})
        for i, xi in enumerate(xs):
            if Module._probe is not None:
                Module._probe(self, 0, self.modules[0], xi, params["0"], b)
            y, b = self.modules[0].apply(params["0"], xi, buffers=b,
                                         training=training, rng=fold_rng(rng, i))
            out.insert(y)
        return out, {"0": b}


class Bottle(Container):
    """Collapse leading dims to run an n-D module over higher-rank input
    (ref nn/Bottle.scala)."""

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: int = 2):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim

    def apply(self, params, x, *, buffers=None, training=False, rng=None):
        buffers = buffers or {}
        in_shape = x.shape
        lead = in_shape[: x.ndim - self.n_input_dim + 1]
        squashed = x.reshape((-1,) + in_shape[x.ndim - self.n_input_dim + 1:])
        y, b = self._child_apply(0, params, squashed, buffers, training, rng)
        y = y.reshape(lead + y.shape[1:])
        return y, {"0": b}


class FlattenTable(Module):
    """Nested table -> flat table (ref nn/FlattenTable.scala)."""

    def f(self, params, x, **kw):
        out = T()

        def rec(v):
            if isinstance(v, Table):
                for item in v.to_seq():
                    rec(item)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    rec(item)
            else:
                out.insert(v)

        rec(x)
        return out


class SplitTable(Module):
    """Tensor -> table of slices along a 1-based dim (ref nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def f(self, params, x, **kw):
        nid = self.n_input_dims if self.n_input_dims > 0 else None
        axis = to_axis(self.dimension, x.ndim, nid)
        out = T()
        for i in range(x.shape[axis]):
            out.insert(jax.lax.index_in_dim(x, i, axis, keepdims=False))
        return out


class JoinTable(Module):
    """Table of tensors -> one tensor concatenated along a 1-based dim
    (ref nn/JoinTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def f(self, params, x, **kw):
        xs = x.to_seq() if isinstance(x, Table) else list(x)
        nid = self.n_input_dims if self.n_input_dims > 0 else None
        axis = to_axis(self.dimension, xs[0].ndim, nid)
        return jnp.concatenate(xs, axis=axis)


class MixtureTable(Module):
    """Mixture-of-experts blend: input {gater, experts-table}; output =
    sum_i gater[:, i] * expert_i (ref nn/MixtureTable.scala)."""

    def __init__(self, dim: int = -1):
        super().__init__()
        self.dim = dim

    def f(self, params, x, **kw):
        xs = x.to_seq() if isinstance(x, Table) else list(x)
        gater, experts = xs[0], xs[1]
        es = experts.to_seq() if isinstance(experts, Table) else list(experts)
        out = None
        for i, e in enumerate(es):
            g = gater[:, i].reshape((-1,) + (1,) * (e.ndim - 1))
            out = g * e if out is None else out + g * e
        return out


class NarrowTable(Module):
    """Sub-table [offset, offset+length) with 1-based offset
    (ref nn/NarrowTable.scala)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset = offset
        self.length = length

    def f(self, params, x, **kw):
        xs = x.to_seq() if isinstance(x, Table) else list(x)
        n = len(xs)
        length = self.length if self.length > 0 else n + self.length - self.offset + 2
        out = T()
        for i in range(self.offset - 1, self.offset - 1 + length):
            out.insert(xs[i])
        return out


class SelectTable(Module):
    """Select one table element, 1-based, negative from end
    (ref nn/SelectTable.scala)."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def f(self, params, x, **kw):
        xs = x.to_seq() if isinstance(x, Table) else list(x)
        return xs[one_based_index(self.index, len(xs))]
