"""Shape-manipulation layers (ref nn/: Reshape, InferReshape, View, Squeeze,
Unsqueeze, Transpose, Replicate, Padding, SpatialZeroPadding, Narrow, Select,
Index, MaskedSelect, Reverse, Contiguous, Copy, Identity, Echo).

All dims are 1-based as in Torch/BigDL.  These are metadata ops: XLA folds
most of them into the surrounding computation for free (the reference's
copy/contiguity machinery in DenseTensor.scala has no runtime cost here).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn._util import to_axis
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import Table


class Identity(Module):
    def f(self, params, x, **kw):
        return x


class Echo(Module):
    """Identity that prints its input's shape (ref nn/Echo.scala) — debug aid."""

    def f(self, params, x, **kw):
        jax.debug.print("Echo: shape {}", x.shape if hasattr(x, "shape") else None)
        return x


class Contiguous(Module):
    """No-op under XLA (ref nn/Contiguous.scala — arrays are always packed)."""

    def f(self, params, x, **kw):
        return x


class Copy(Module):
    def f(self, params, x, **kw):
        return jnp.array(x)


class Reshape(Module):
    """Reshape non-batch dims to ``size``; batch_mode None auto-detects a
    leading batch dim as Torch does (ref nn/Reshape.scala)."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode
        self._n = 1
        for s in self.size:
            self._n *= s

    def f(self, params, x, **kw):
        # Torch rule (nn/Reshape.scala): explicit batch_mode wins; with
        # batch_mode None, input is non-batch only when element counts match
        # AND the first dim isn't a singleton batch dim (so a size-1 batch
        # keeps its batch axis).
        if self.batch_mode is False or (
                self.batch_mode is None and x.size == self._n and x.shape[0] != 1):
            return x.reshape(self.size)
        return x.reshape((x.shape[0],) + self.size)


class InferReshape(Module):
    """Reshape with -1 (infer) and 0 (copy input dim) entries
    (ref nn/InferReshape.scala)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def f(self, params, x, **kw):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i])
            else:
                out.append(s)
        if self.batch_mode:
            return x.reshape((x.shape[0],) + tuple(out))
        return x.reshape(tuple(out))


class View(Module):
    """View with fixed sizes; -1 allowed (ref nn/View.scala)."""

    def __init__(self, *sizes: int):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n: int) -> "View":
        self.num_input_dims = n
        return self

    def f(self, params, x, **kw):
        n = 1
        for s in self.sizes:
            n *= s
        if n > 0 and (x.size != n or x.shape[0] == 1):
            return x.reshape((x.shape[0],) + self.sizes)  # leading batch dim
        return x.reshape(self.sizes)


class Squeeze(Module):
    def __init__(self, dim: Optional[int] = None, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def f(self, params, x, **kw):
        if self.dim is None:
            return jnp.squeeze(x)
        nid = self.num_input_dims if self.num_input_dims > 0 else None
        axis = to_axis(self.dim, x.ndim, nid)
        return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


class Unsqueeze(Module):
    def __init__(self, pos: int, num_input_dims: int = -1):
        super().__init__()
        self.pos = pos
        self.num_input_dims = num_input_dims

    def f(self, params, x, **kw):
        nid = self.num_input_dims if self.num_input_dims > 0 else None
        axis = to_axis(self.pos, x.ndim + 1, (nid + 1) if nid else None)
        return jnp.expand_dims(x, axis=axis)


class Transpose(Module):
    """Sequence of pairwise 1-based dim swaps (ref nn/Transpose.scala)."""

    def __init__(self, permutations: Sequence[tuple[int, int]]):
        super().__init__()
        self.permutations = [tuple(p) for p in permutations]

    def f(self, params, x, **kw):
        for d1, d2 in self.permutations:
            x = jnp.swapaxes(x, to_axis(d1, x.ndim), to_axis(d2, x.ndim))
        return x


class Replicate(Module):
    """Insert a new dim of size n_features at 1-based position dim
    (ref nn/Replicate.scala)."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = -1):
        super().__init__()
        self.n_features = n_features
        self.dim = dim
        self.n_dim = n_dim

    def f(self, params, x, **kw):
        nid = self.n_dim if self.n_dim > 0 else None
        axis = to_axis(self.dim, x.ndim + 1, (nid + 1) if nid else None)
        return jnp.repeat(jnp.expand_dims(x, axis), self.n_features, axis=axis)


class Padding(Module):
    """Pad ``pad`` slots (left if negative) along a 1-based dim with
    ``value`` (ref nn/Padding.scala)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int = -1,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim = dim
        self.pad = pad
        self.n_input_dim = n_input_dim
        self.value = value
        self.n_index = n_index

    def f(self, params, x, **kw):
        nid = self.n_input_dim if self.n_input_dim > 0 else None
        axis = to_axis(self.dim, x.ndim, nid)
        widths = [(0, 0)] * x.ndim
        widths[axis] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value)


class SpatialZeroPadding(Module):
    def __init__(self, pad_left: int, pad_right: int = None,
                 pad_top: int = None, pad_bottom: int = None):
        super().__init__()
        self.pad_left = pad_left
        self.pad_right = pad_right if pad_right is not None else pad_left
        self.pad_top = pad_top if pad_top is not None else pad_left
        self.pad_bottom = pad_bottom if pad_bottom is not None else pad_left

    def f(self, params, x, **kw):
        widths = [(0, 0)] * (x.ndim - 2) + \
            [(self.pad_top, self.pad_bottom), (self.pad_left, self.pad_right)]
        return jnp.pad(x, widths)


class Narrow(Module):
    """Slice [offset, offset+length) along a 1-based dim; negative offset
    counts from the end (ref nn/Narrow.scala)."""

    def __init__(self, dimension: int, offset: int, length: int = 1):
        super().__init__()
        self.dimension = dimension
        self.offset = offset
        self.length = length

    def f(self, params, x, **kw):
        axis = to_axis(self.dimension, x.ndim)
        size = x.shape[axis]
        start = self.offset - 1 if self.offset > 0 else size + self.offset
        length = self.length if self.length > 0 else size - start + self.length + 1
        return jax.lax.slice_in_dim(x, start, start + length, axis=axis)


class Select(Module):
    """Select one 1-based index along a 1-based dim, squeezing it
    (ref nn/Select.scala)."""

    def __init__(self, dimension: int, index: int):
        super().__init__()
        self.dimension = dimension
        self.index = index

    def f(self, params, x, **kw):
        axis = to_axis(self.dimension, x.ndim)
        idx = self.index - 1 if self.index > 0 else x.shape[axis] + self.index
        return jax.lax.index_in_dim(x, idx, axis, keepdims=False)


class Index(Module):
    """Gather along a 1-based dim with a 1-based index tensor from a table
    {tensor, indices} (ref nn/Index.scala)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def f(self, params, x, **kw):
        t, idx = (x.to_seq() if isinstance(x, Table) else list(x))
        axis = to_axis(self.dimension, t.ndim)
        return jnp.take(t, idx.astype(jnp.int32) - 1, axis=axis)


class MaskedSelect(Module):
    """Select elements where mask is nonzero, flattened
    (ref nn/MaskedSelect.scala).  The output length is data-dependent, so
    this op cannot live under jax.jit (no dynamic shapes in XLA); it is
    evaluated eagerly — the same reason it has no SPMD story in any
    framework."""

    def f(self, params, x, **kw):
        t, mask = (x.to_seq() if isinstance(x, Table) else list(x))
        import numpy as np
        return jnp.asarray(np.asarray(t)[np.asarray(mask) != 0])


class Reverse(Module):
    def __init__(self, dimension: int = 1):
        super().__init__()
        self.dimension = dimension

    def f(self, params, x, **kw):
        return jnp.flip(x, axis=to_axis(self.dimension, x.ndim))
