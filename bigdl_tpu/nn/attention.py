"""Attention layers (capability-gap fill: the reference predates attention —
SURVEY.md §5.7 — so long-context support is designed TPU-first rather than
ported: batched (B, H, T, D) matmuls for the MXU, online-softmax blockwise
streaming for HBM, and a ring/sequence-parallel path in
``bigdl_tpu.parallel.sequence``).

API follows the house style: modules are (B, T, F) like Recurrent
(ref nn/Recurrent.scala batch x time x feature layout).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn._util import match_compute_dtype

NEG_INF = float("-inf")


def _safe_exp(x, m):
    """exp(x - m) with -inf maxima treated as empty (0 weight)."""
    return jnp.where(jnp.isneginf(m), 0.0, jnp.exp(x - jnp.where(
        jnp.isneginf(m), 0.0, m)))


def online_softmax_update(carry, block):
    """One step of the streaming-softmax accumulation used by blockwise and
    ring attention: merge a new (m_blk, l_blk, o_blk) partial into the
    running (o, l, m).  Shapes: m,l (..., Tq); o (..., Tq, D)."""
    o, l, m = carry
    m_blk, l_blk, o_blk = block
    m_new = jnp.maximum(m, m_blk)
    alpha = _safe_exp(m, m_new)
    beta = _safe_exp(m_blk, m_new)
    o = o * alpha[..., None] + o_blk * beta[..., None]
    l = l * alpha + l_blk * beta
    return o, l, m_new


def segment_mask(seg_q, seg_k):
    """(B, 1, Tq, Tk) boolean packed-document isolation mask from
    (B, Tq)/(B, Tk) segment ids — broadcasts over the head dim; the one
    definition of the layout every attention path shares."""
    return seg_q[:, None, :, None] == seg_k[:, None, None, :]


def _block_scores(q, k, v, mask, scale):
    """Partial attention of q against one k/v block.
    q: (..., Tq, D); k, v: (..., Tk, D); mask: broadcastable (..., Tq, Tk)
    or None.  Returns (m_blk (...,Tq), l_blk (...,Tq), o_blk (...,Tq,D))."""
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)
    p = _safe_exp(s, m_blk[..., None])
    l_blk = jnp.sum(p, axis=-1)
    o_blk = jnp.einsum("...qk,...kd->...qd", p, v)
    return m_blk, l_blk, o_blk


def _finalize(o, l):
    return o / jnp.where(l == 0.0, 1.0, l)[..., None]


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None):
    """Plain attention, one XLA fusion. q,k,v: (..., T, D)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if causal:
        tq, tk = q.shape[-2], k.shape[-2]
        cmask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        mask = cmask if mask is None else jnp.logical_and(mask, cmask)
    m, l, o = _block_scores(q, k, v, mask, scale)
    return _finalize(o, l)


def blockwise_attention(q, k, v, *, block_size: int = 512,
                        causal: bool = False,
                        scale: Optional[float] = None):
    """Memory-efficient streaming attention: the (Tq, Tk) score matrix is
    never materialized — k/v are consumed in blocks with an online softmax
    (the single-chip half of ring attention; HBM-bound regime).
    q,k,v: (B, H, T, D)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    tk = k.shape[-2]
    block_size = min(block_size, tk)
    rem = tk % block_size
    padded = rem != 0
    if padded:  # pad the tail block; pad keys are masked out by position
        pad = block_size - rem
        widths = [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)]
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    n_blocks = k.shape[-2] // block_size
    k_blocks = k.reshape(k.shape[:-2] + (n_blocks, block_size, k.shape[-1]))
    v_blocks = v.reshape(v.shape[:-2] + (n_blocks, block_size, v.shape[-1]))
    k_blocks = jnp.moveaxis(k_blocks, -3, 0)  # (n, B, H, bs, D)
    v_blocks = jnp.moveaxis(v_blocks, -3, 0)
    tq = q.shape[-2]
    q_pos = jnp.arange(tq) + (tk - tq)  # align ends when Tq != Tk

    def step(carry, inp):
        blk_idx, kb, vb = inp
        mask = None
        if causal or padded:
            k_pos = blk_idx * block_size + jnp.arange(block_size)
            mask = (q_pos[:, None] >= k_pos[None, :]) if causal \
                else jnp.ones((tq, block_size), bool)
            if padded:
                mask = jnp.logical_and(mask, (k_pos < tk)[None, :])
        blk = _block_scores(q, kb, vb, mask, scale)
        return online_softmax_update(carry, blk), None

    o0 = jnp.zeros(q.shape, q.dtype)
    l0 = jnp.zeros(q.shape[:-1], q.dtype)
    m0 = jnp.full(q.shape[:-1], NEG_INF, q.dtype)
    (o, l, _), _ = lax.scan(
        step, (o0, l0, m0), (jnp.arange(n_blocks), k_blocks, v_blocks))
    return _finalize(o, l)


class MultiHeadAttention(Module):
    """Multi-head attention over (B, T, F) (post-reference capability; the
    TPU-idiomatic replacement for long-sequence modeling that the
    reference's Recurrent stack cannot scale to).

    Input: a tensor (self-attention) or a table/tuple (query, key, value).
    """

    def __init__(self, hidden_size: int, n_head: int,
                 head_dim: Optional[int] = None, causal: bool = False,
                 with_bias: bool = True, block_size: Optional[int] = None,
                 attention_impl: str = "auto"):
        super().__init__()
        assert head_dim is not None or hidden_size % n_head == 0
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.head_dim = head_dim or hidden_size // n_head
        self.causal = causal
        self.with_bias = with_bias
        self.block_size = block_size  # None -> plain fused attention
        # "xla": always the fused XLA attention (required under GSPMD
        # sharding rules — pallas_call only partitions inside shard_map);
        # "flash": always the Pallas kernel; "auto": crossover dispatch —
        # flash on TPU past FLASH_AUTO_MIN_T, XLA otherwise
        if attention_impl not in ("auto", "flash", "xla"):
            raise ValueError(f"attention_impl must be 'auto', 'flash' or "
                             f"'xla', got {attention_impl!r}")
        self.attention_impl = attention_impl

    def init(self, rng):
        ks = jax.random.split(rng, 4)
        inner = self.n_head * self.head_dim
        std = 1.0 / math.sqrt(self.hidden_size)
        p = {name: jax.random.uniform(k, shape, jnp.float32, -std, std)
             for name, k, shape in (
                 ("wq", ks[0], (self.hidden_size, inner)),
                 ("wk", ks[1], (self.hidden_size, inner)),
                 ("wv", ks[2], (self.hidden_size, inner)),
                 ("wo", ks[3], (inner, self.hidden_size)))}
        if self.with_bias:
            for name in ("bq", "bk", "bv", "bo"):
                p[name] = jnp.zeros((self.hidden_size,)
                                    if name == "bo" else (inner,))
        return p

    def resolve_use_flash(self, seq_len: int, dtype=None) -> bool:
        """ONE dispatch rule for every call path (module forward,
        TransformerLM block, generation prefill): explicit "flash" always;
        "xla" never; "auto" by the measured crossover (the autotune cache
        when this device kind has a verdict for (seq_len, head_dim,
        dtype), the static TPU heuristic otherwise) — unless a block_size
        was set, which pins the blockwise-XLA core."""
        if self.attention_impl == "flash":
            return True
        if self.attention_impl == "auto" and not self.block_size:
            from bigdl_tpu.ops.flash_attention import use_flash_auto
            return use_flash_auto(seq_len, self.head_dim, dtype,
                                  self.causal)
        return False

    def _split_heads(self, x):  # (B, T, H*D) -> (B, H, T, D)
        b, t, _ = x.shape
        return x.reshape(b, t, self.n_head, self.head_dim).transpose(0, 2, 1, 3)

    def _merge_heads(self, x):  # (B, H, T, D) -> (B, T, H*D)
        b, h, t, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)

    def project_qkv(self, params, q_in, k_in, v_in):
        # qmatmul is the QTensor-aware seam: plain arrays fall straight
        # through to @, int8-compute drafter weights hit the MXU as int8
        from bigdl_tpu.quant.kernels import qmatmul
        q_in = match_compute_dtype(jnp.asarray(q_in), params["wq"])
        k_in = match_compute_dtype(jnp.asarray(k_in), params["wk"])
        v_in = match_compute_dtype(jnp.asarray(v_in), params["wv"])
        q = qmatmul(q_in, params["wq"])
        k = qmatmul(k_in, params["wk"])
        v = qmatmul(v_in, params["wv"])
        if self.with_bias:
            q, k, v = q + params["bq"], k + params["bk"], v + params["bv"]
        return (self._split_heads(q), self._split_heads(k),
                self._split_heads(v))

    def project_out(self, params, o):
        from bigdl_tpu.quant.kernels import qmatmul
        y = qmatmul(self._merge_heads(o), params["wo"])
        if self.with_bias:
            y = y + params["bo"]
        return y

    def attend(self, q, k, v, *, segment_ids=None, allow_blockwise=True):
        """The ONE attention-core dispatch shared by the module forward
        and TransformerLM blocks: flash (per resolve_use_flash) ->
        blockwise (pinned block_size, module path only) -> plain XLA.
        ``segment_ids`` (B, T): packed-document isolation, self-attention
        only — masked inside the flash tiles or via an explicit mask on
        the plain path."""
        if segment_ids is not None and q.shape[-2] != k.shape[-2]:
            # mirror ops.flash_attention's guard so the XLA path fails
            # with the same clear message instead of a deep broadcast
            # error (and never silently masks k by q's document ids)
            raise ValueError("segment_ids requires self-attention "
                             "(Tq == Tk)")
        if self.resolve_use_flash(q.shape[-2], dtype=q.dtype):
            from bigdl_tpu.ops import flash_attention
            if self.attention_impl == "flash" or self.block_size:
                # an explicit kernel choice (or pinned block size) must
                # stay on the Pallas kernel regardless of the cache
                bs = self.block_size or 128
                return flash_attention(q, k, v, causal=self.causal,
                                       segment_ids=segment_ids,
                                       block_q=bs, block_k=bs)
            # "auto": leave blocks None so the tuned-crossover plan picks
            # the winning blocks (or reroutes to the XLA fallback)
            return flash_attention(q, k, v, causal=self.causal,
                                   segment_ids=segment_ids)
        if self.block_size and allow_blockwise:
            if segment_ids is not None:
                raise ValueError(
                    "segment_ids is not supported with a pinned "
                    "block_size (blockwise-XLA core); use "
                    "attention_impl='flash', or unset block_size for "
                    "the plain XLA core")
            return blockwise_attention(q, k, v, block_size=self.block_size,
                                       causal=self.causal)
        mask = (None if segment_ids is None
                else segment_mask(segment_ids, segment_ids))
        return dot_product_attention(q, k, v, causal=self.causal, mask=mask)

    def f(self, params, x, *, segment_ids=None, **kw):
        """``segment_ids`` (B, T): packed-document isolation for the
        self-attention case — masked inside the flash tiles or via an
        explicit mask on the XLA paths (the same contract as
        ``ops.flash_attention`` and ``TransformerLM.doc_start_id``)."""
        from bigdl_tpu.utils.table import Table
        if isinstance(x, Table):
            q_in, k_in, v_in = x.to_seq()[:3]
        elif isinstance(x, (tuple, list)):
            q_in, k_in, v_in = x[0], x[1], x[2]
        else:
            q_in = k_in = v_in = x
        q, k, v = self.project_qkv(params, q_in, k_in, v_in)
        o = self.attend(q, k, v, segment_ids=segment_ids)
        return self.project_out(params, o)
