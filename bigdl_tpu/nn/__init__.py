"""nn: the module system and layer zoo (ref spark/dl/.../nn/, 142 files).

Every public layer/criterion name from the reference's zoo is exported here
so ``from bigdl_tpu import nn; nn.Linear(...)`` mirrors
``com.intel.analytics.bigdl.nn.Linear``.
"""
from bigdl_tpu.nn.module import Module, Criterion
from bigdl_tpu.nn.containers import (
    Container, Sequential, Concat, DepthConcat, ConcatTable, ParallelTable, MapTable,
    Bottle, FlattenTable, SplitTable, JoinTable, MixtureTable, NarrowTable,
    SelectTable,
)
from bigdl_tpu.nn.activations import (
    ReLU, ReLU6, GELU, Tanh, Sigmoid, SoftMax, SoftMin, LogSoftMax,
    LogSigmoid, SoftPlus, SoftSign, LeakyReLU, ELU, PReLU, RReLU, HardTanh,
    HardShrink, SoftShrink, TanhShrink, Threshold, Clamp, Power, Square,
    Sqrt, Log, Exp, Abs,
)
from bigdl_tpu.nn.linear import (
    Linear, Bilinear, MM, MV, DotProduct, Cosine, Euclidean,
    PairwiseDistance, CosineDistance, LookupTable, Add, AddConstant, Mul,
    MulConstant, CMul, CAdd, Scale,
)
from bigdl_tpu.nn.conv import (
    SpatialConvolution, SpatialShareConvolution, SpatialDilatedConvolution,
    SpatialFullConvolution, SpatialConvolutionMap,
)
from bigdl_tpu.nn.pooling import SpatialMaxPooling, SpatialAveragePooling, RoiPooling
from bigdl_tpu.nn.normalization import (
    BatchNormalization, SpatialBatchNormalization, LayerNorm, Normalize,
    SpatialCrossMapLRN, SpatialSubtractiveNormalization,
    SpatialDivisiveNormalization, SpatialContrastiveNormalization,
)
from bigdl_tpu.nn.shape import (
    Identity, Echo, Contiguous, Copy, Reshape, InferReshape, View, Squeeze,
    Unsqueeze, Transpose, Replicate, Padding, SpatialZeroPadding, Narrow,
    Select, Index, MaskedSelect, Reverse,
)
from bigdl_tpu.nn.table_ops import (
    CAddTable, CSubTable, CMulTable, CDivTable, CMaxTable, CMinTable,
    Sum, Mean, Max, Min,
)
from bigdl_tpu.nn.dropout import Dropout, L1Penalty, GradientReversal
from bigdl_tpu.nn.detection import Nms, nms
from bigdl_tpu.nn.recurrent import (
    Cell, RnnCell, LSTM, GRU, Recurrent, BiRecurrent, TimeDistributed,
)
from bigdl_tpu.nn.criterions import (
    ClassNLLCriterion, CrossEntropyCriterion, MSECriterion, AbsCriterion,
    BCECriterion, DistKLDivCriterion, SmoothL1Criterion,
    SmoothL1CriterionWithWeights, MarginCriterion, MarginRankingCriterion,
    MultiMarginCriterion, MultiLabelMarginCriterion,
    MultiLabelSoftMarginCriterion, SoftMarginCriterion,
    HingeEmbeddingCriterion, L1HingeEmbeddingCriterion,
    CosineEmbeddingCriterion, ClassSimplexCriterion, L1Cost,
    SoftmaxWithCriterion, ParallelCriterion, MultiCriterion,
    TimeDistributedCriterion, CriterionTable,
)
from bigdl_tpu.nn.initialization import (
    InitializationMethod, Default, Xavier, BilinearFiller,
)
from bigdl_tpu.nn.attention import (
    MultiHeadAttention, dot_product_attention, blockwise_attention,
)
