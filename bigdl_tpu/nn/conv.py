"""Convolution layers (ref nn/SpatialConvolution.scala:104-199 and family).

The reference lowers conv to im2col + MKL gemm with hand-threaded per-sample
parallelism (NNPrimitive.scala:24-335).  On TPU the whole of that machinery
is one ``lax.conv_general_dilated`` call: XLA tiles it onto the MXU and
fuses the bias/activation — there is no im2col buffer, no per-sample
threading, no col2im backward (autodiff derives it).

Layouts preserve Torch conventions for import parity at the API edge:
weights are always stored OIHW and the default activation layout is NCHW.
``data_format="NHWC"`` switches a layer's *activation* layout to the
TPU-native channels-last form (the MXU wants NHWC; with NCHW the compiler
inserts relayout ops around every conv).  Weight storage is unchanged, so
.t7/Caffe import and the Torch oracles work identically in both modes —
models opt in per-layer and transpose activations once at the model edge.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.initialization import Default, InitializationMethod
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn._util import match_compute_dtype
from bigdl_tpu.quant.qtensor import is_qtensor


def _dn(data_format: str):
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")
    return (data_format, "OIHW", data_format)


def _add_bias(y, bias, data_format: str):
    if data_format == "NCHW":
        return y + bias[None, :, None, None]
    return y + bias  # NHWC: channel is last, plain broadcast


class SpatialConvolution(Module):
    """2-D convolution (ref nn/SpatialConvolution.scala, 579 LoC).

    Args mirror the reference: (n_input_plane, n_output_plane, kernel_w,
    kernel_h, stride_w, stride_h, pad_w, pad_h, n_group).  Note the
    reference's W-before-H argument order is kept.
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1,
                 propagate_back: bool = True, with_bias: bool = True,
                 init_method: type[InitializationMethod] = Default,
                 data_format: str = "NCHW"):
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.n_group = n_group
        self.with_bias = with_bias
        self.init_method = init_method
        self.data_format = data_format
        _dn(data_format)  # validate early

    def _fans(self):
        fan_in = self.n_input_plane // self.n_group * self.kernel_h * self.kernel_w
        fan_out = self.n_output_plane // self.n_group * self.kernel_h * self.kernel_w
        return fan_in, fan_out

    def init(self, rng):
        wk, bk = jax.random.split(rng)
        fan_in, fan_out = self._fans()
        shape = (self.n_output_plane, self.n_input_plane // self.n_group,
                 self.kernel_h, self.kernel_w)
        p = {"weight": self.init_method.weight(wk, shape, fan_in=fan_in, fan_out=fan_out)
             if self.init_method is not Default
             else Default.weight(wk, shape, fan_in=fan_in)}
        if self.with_bias:
            p["bias"] = Default.bias(bk, (self.n_output_plane,), fan_in=fan_in)
        return p

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:  # CHW -> NCHW (the reference accepts 3-D input)
            x = x[None]
        w = params["weight"]
        if is_qtensor(w):
            from bigdl_tpu.quant.kernels import qconv
            y = qconv(x, w,
                      window_strides=(self.stride_h, self.stride_w),
                      padding=((self.pad_h, self.pad_h),
                               (self.pad_w, self.pad_w)),
                      dimension_numbers=_dn(self.data_format),
                      feature_group_count=self.n_group)
        else:
            x = match_compute_dtype(x, w)
            y = lax.conv_general_dilated(
                x, w,
                window_strides=(self.stride_h, self.stride_w),
                padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
                dimension_numbers=_dn(self.data_format),
                feature_group_count=self.n_group,
            )
        if self.with_bias:
            y = _add_bias(y, params["bias"], self.data_format)
        return y[0] if squeeze else y


class SpatialShareConvolution(SpatialConvolution):
    """Memory-sharing variant of SpatialConvolution
    (ref nn/SpatialShareConvolution.scala).  The reference shares im2col
    buffers across instances; under XLA buffer reuse is the compiler's job,
    so this is computationally identical to SpatialConvolution."""


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous convolution (ref nn/SpatialDilatedConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 init_method: type[InitializationMethod] = Default,
                 data_format: str = "NCHW"):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h,
                         init_method=init_method, data_format=data_format)
        self.dilation_w = dilation_w
        self.dilation_h = dilation_h

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        w = params["weight"]
        if is_qtensor(w):
            from bigdl_tpu.quant.kernels import qconv
            y = qconv(x, w,
                      window_strides=(self.stride_h, self.stride_w),
                      padding=((self.pad_h, self.pad_h),
                               (self.pad_w, self.pad_w)),
                      rhs_dilation=(self.dilation_h, self.dilation_w),
                      dimension_numbers=_dn(self.data_format))
        else:
            x = match_compute_dtype(x, w)
            y = lax.conv_general_dilated(
                x, w,
                window_strides=(self.stride_h, self.stride_w),
                padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
                rhs_dilation=(self.dilation_h, self.dilation_w),
                dimension_numbers=_dn(self.data_format),
            )
        if self.with_bias:
            y = _add_bias(y, params["bias"], self.data_format)
        return y[0] if squeeze else y


class SpatialFullConvolution(Module):
    """Transposed convolution / "deconvolution"
    (ref nn/SpatialFullConvolution.scala).  Output size =
    (in-1)*stride - 2*pad + kernel + adj.  Implemented as an input-dilated
    conv with the spatially-flipped kernel — exactly the op XLA emits for
    conv gradients, so it lands on the MXU the same way."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 init_method: type[InitializationMethod] = Default,
                 data_format: str = "NCHW"):
        super().__init__()
        self.data_format = data_format
        _dn(data_format)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.adj_w = adj_w
        self.adj_h = adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.init_method = init_method

    def init(self, rng):
        wk, bk = jax.random.split(rng)
        fan_in = self.n_output_plane // self.n_group * self.kernel_h * self.kernel_w
        # Torch layout for full conv: (nInput, nOutput/group, kH, kW)
        shape = (self.n_input_plane, self.n_output_plane // self.n_group,
                 self.kernel_h, self.kernel_w)
        p = {"weight": self.init_method.weight(wk, shape, fan_in=fan_in)}
        if self.with_bias:
            p["bias"] = Default.bias(bk, (self.n_output_plane,), fan_in=fan_in)
        return p

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        x = match_compute_dtype(x, params["weight"])
        w = params["weight"]
        # (I, O/g, kh, kw) -> flip spatial, swap to (O, I/g, kh, kw)
        w = jnp.flip(w, axis=(-2, -1))
        if self.n_group > 1:
            ig = self.n_input_plane // self.n_group
            w = w.reshape(self.n_group, ig, self.n_output_plane // self.n_group,
                          self.kernel_h, self.kernel_w)
            w = jnp.swapaxes(w, 1, 2).reshape(
                self.n_output_plane, ig, self.kernel_h, self.kernel_w)
        else:
            w = jnp.swapaxes(w, 0, 1)
        pad_h = (self.kernel_h - 1 - self.pad_h, self.kernel_h - 1 - self.pad_h + self.adj_h)
        pad_w = (self.kernel_w - 1 - self.pad_w, self.kernel_w - 1 - self.pad_w + self.adj_w)
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1),
            padding=(pad_h, pad_w),
            lhs_dilation=(self.stride_h, self.stride_w),
            dimension_numbers=_dn(self.data_format),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = _add_bias(y, params["bias"], self.data_format)
        return y[0] if squeeze else y


class SpatialConvolutionMap(Module):
    """Convolution with an explicit input->output connection table
    (ref nn/SpatialConvolutionMap.scala).  ``conn_table`` is a (K, 2) array
    of 1-based (input_plane, output_plane) pairs, as in Torch.  Implemented
    as a dense conv with a frozen sparsity mask — XLA still gets one MXU
    matmul, and masked weights stay exactly zero through training because
    the mask also zeroes their gradients (mask is applied inside f)."""

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        import numpy as np
        ct = np.asarray(conn_table, dtype=np.int32)
        self.conn_table = ct
        self.n_input_plane = int(ct[:, 0].max())
        self.n_output_plane = int(ct[:, 1].max())
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        mask = np.zeros((self.n_output_plane, self.n_input_plane, 1, 1), dtype=np.float32)
        for i, o in ct:
            mask[o - 1, i - 1, 0, 0] = 1.0
        self._mask = mask

    @staticmethod
    def full(nin: int, nout: int):
        import numpy as np
        return np.array([(i + 1, o + 1) for o in range(nout) for i in range(nin)],
                        dtype=np.int32)

    @staticmethod
    def one_to_one(n: int):
        import numpy as np
        return np.array([(i + 1, i + 1) for i in range(n)], dtype=np.int32)

    @staticmethod
    def random(nin: int, nout: int, nto: int, seed: int = 0):
        import numpy as np
        rng = np.random.RandomState(seed)
        pairs = []
        for o in range(nout):
            for i in rng.choice(nin, size=nto, replace=False):
                pairs.append((i + 1, o + 1))
        return np.array(pairs, dtype=np.int32)

    def init(self, rng):
        wk, bk = jax.random.split(rng)
        nto = max(1, len(self.conn_table) // self.n_output_plane)
        stdv = 1.0 / math.sqrt(self.kernel_w * self.kernel_h * nto)
        w = jax.random.uniform(
            wk, (self.n_output_plane, self.n_input_plane, self.kernel_h, self.kernel_w),
            minval=-stdv, maxval=stdv)
        b = jax.random.uniform(bk, (self.n_output_plane,), minval=-stdv, maxval=stdv)
        return {"weight": w * self._mask, "bias": b}

    def f(self, params, x, **kw):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        w = params["weight"] * self._mask.astype(params["weight"].dtype)
        x = match_compute_dtype(x, w)
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(self.stride_h, self.stride_w),
            padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            dimension_numbers=_dn("NCHW"),
        )
        y = y + params["bias"][None, :, None, None]
        return y[0] if squeeze else y
