"""Detection utilities (ref nn/Nms.scala).

Non-maximum suppression with static output shape: returns a fixed-length
1-based index vector padded with 0 plus a valid count, so it composes with
jit (XLA has no dynamic shapes; the reference returns a variable-length
index array on CPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def nms(boxes, scores, iou_threshold: float = 0.5, max_output: int = 100):
    """Greedy NMS. boxes (N,4) as (x1,y1,x2,y2); returns (indices_1based
    padded to max_output with 0, count)."""
    boxes = jnp.asarray(boxes, dtype=jnp.float32)
    scores = jnp.asarray(scores, dtype=jnp.float32)
    n = boxes.shape[0]
    areas = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    order = jnp.argsort(-scores)

    def iou(i, j):
        xx1 = jnp.maximum(boxes[i, 0], boxes[j, 0])
        yy1 = jnp.maximum(boxes[i, 1], boxes[j, 1])
        xx2 = jnp.minimum(boxes[i, 2], boxes[j, 2])
        yy2 = jnp.minimum(boxes[i, 3], boxes[j, 3])
        w = jnp.maximum(0.0, xx2 - xx1 + 1)
        h = jnp.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        return inter / (areas[i] + areas[j] - inter)

    def body(state):
        keep, count, alive = state
        scores_alive = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(scores_alive)
        keep = keep.at[count].set(best + 1)
        ious = jax.vmap(lambda j: iou(best, j))(jnp.arange(n))
        alive = alive & (ious <= iou_threshold)
        alive = alive.at[best].set(False)
        return keep, count + 1, alive

    def cond(state):
        keep, count, alive = state
        return jnp.any(alive) & (count < max_output)

    keep0 = jnp.zeros((max_output,), dtype=jnp.int32)
    alive0 = jnp.ones((n,), dtype=bool)
    keep, count, _ = jax.lax.while_loop(cond, body, (keep0, 0, alive0))
    return keep, count


class Nms:
    """Object-style wrapper mirroring the reference's Nms class."""

    def __init__(self, iou_threshold: float = 0.5, max_output: int = 100):
        self.iou_threshold = iou_threshold
        self.max_output = max_output

    def __call__(self, boxes, scores):
        keep, count = nms(boxes, scores, self.iou_threshold, self.max_output)
        return np.asarray(keep[:int(count)])
