"""Explicit shape-bucketed compile cache for inference executables.

Under JAX every novel input shape triggers a fresh trace + XLA compile;
``jax.jit`` hides its shape cache, so a serving path that relied on it
could neither observe hit rates nor bound entries nor pre-warm.  This
cache is the explicit version: entries are ahead-of-time compiled
executables (``jit(fn).lower(...).compile()``) keyed on

    (bucket input shape, input dtype, donate flags, params quant dtype,
     placement tag)

— the quant-dtype component is what lets one cache hold f32 and int8
replicas of the same model simultaneously (quant.params_dtype_tag:
"int8" when the params tree carries QTensor leaves, "bf16"/"f32"
otherwise), and the placement tag (``MeshSlice.tag``, "" unplaced)
keeps executables compiled for different device slots apart — an AOT
executable bakes in its committed-input devices, so a slot0 entry
replayed for slot1 params would be a silent cross-slot transfer.  With
hit/miss/evict counters and a warmup API that pre-traces the
configured buckets before traffic arrives.  The batcher pads every
batch to a configured bucket, so steady state is all hits and the
cache stays small and warm (TensorFlow-serving's lesson, arXiv
1605.08695: accelerator serving throughput dies by recompilation).

Eviction is LRU with a bounded entry count — a misconfigured client
streaming novel shapes degrades to compile-per-call but can not grow
device/host memory without bound.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Sequence, Tuple

Key = Tuple[tuple, tuple, str, str]


def input_signature(x) -> tuple:
    """The cache-key component for one input: ``(shape, dtype)`` for a
    single array (the classic batcher case), or, for a multi-tensor /
    pytree input (the LM prefill case: ids + true length), the treedef
    plus a tuple of per-leaf ``(shape, dtype)`` — two containers with
    identical leaves but different structure must not share an
    executable."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return (tuple(x.shape), str(x.dtype))
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(x)
    return (str(treedef),
            tuple((tuple(l.shape), str(l.dtype)) for l in leaves))


class CompileCache:
    """AOT-compile cache for ``fn(params, buffers, x) -> y``.

    ``params``/``buffers`` are the frozen model state (same pytree every
    call — their shapes are part of the trace but not of the key, with
    one exception: their quant dtype tag IS keyed, so a caller serving
    f32 and int8 replicas of one model gets one executable each);
    ``x`` is the padded batch — a single array or any pytree of arrays
    (``input_signature``) — whose per-leaf (shape, dtype) keys the
    entry.
    """

    def __init__(self, fn: Callable, *, max_entries: int = 16,
                 donate_x: bool = False, placement_tag: str = "",
                 name: str = ""):
        import jax

        self._donate = ("x",) if donate_x else ()
        self._placement_tag = placement_tag
        # the memory-ledger namespace for this cache's executable
        # cost/memory rows (obs/xcost/*); defaults to the wrapped
        # function's name, qualified by the placement slot
        base = name or getattr(fn, "__name__", "fn").lstrip("_")
        self.ledger_tag = (f"{base}@{placement_tag}" if placement_tag
                           else base)
        # donating x lets XLA reuse the input buffer for activations;
        # params/buffers are never donated (reused every call)
        self._jit = jax.jit(fn, donate_argnums=(2,) if donate_x else ())
        self._max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[Key, Callable]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------------ #
    def key_for(self, x, params=None) -> Key:
        from bigdl_tpu.quant import params_dtype_tag
        return (input_signature(x), self._donate,
                params_dtype_tag(params) if params is not None else "f32",
                self._placement_tag)

    def _compile(self, params, buffers, x) -> Callable:
        compiled = self._jit.lower(params, buffers, x).compile()
        # file the executable's memory_analysis()/cost_analysis() with
        # the memory ledger at AOT-lower time — the roofline estimate
        # is free here and unobtainable later
        try:
            from bigdl_tpu.obs.ledger import get_ledger
            get_ledger().record_compiled(
                self.ledger_tag, self._ledger_key(self.key_for(x, params)),
                compiled)
        except Exception:
            pass
        return compiled

    @staticmethod
    def _ledger_key(key: Key) -> str:
        # input signature + quant tag; donate flags and placement are
        # constant per cache (the placement rides the ledger tag)
        return f"{key[0]}|{key[2]}"

    def _admit(self, key: Key, entry: Callable, *, count: bool) -> bool:
        """Insert a freshly compiled entry under the LRU bound; returns
        whether it was new.  ``count`` toggles the miss counter (warmup
        provisioning is not traffic)."""
        evicted = []
        with self._lock:
            if count:
                self.misses += 1
            new = key not in self._entries
            if new:
                self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self._max_entries:
                evicted.append(self._entries.popitem(last=False)[0])
                self.evictions += 1
        if evicted:
            # keep the ledger's executable table in step with the LRU
            try:
                from bigdl_tpu.obs.ledger import get_ledger
                led = get_ledger()
                for k in evicted:
                    led.release_executable(self.ledger_tag,
                                           self._ledger_key(k))
            except Exception:
                pass
        return new

    def __call__(self, params, buffers, x):
        """Run ``fn`` through the cached executable for x's shape
        bucket, compiling (miss) on first sight."""
        key = self.key_for(x, params)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self.hits += 1
                self._entries.move_to_end(key)
        if entry is None:
            # compile outside the lock: a 20s XLA compile must not
            # stall concurrent lookups for already-warm buckets
            entry = self._compile(params, buffers, x)
            self._admit(key, entry, count=True)
        return entry(params, buffers, x)

    # ------------------------------------------------------------------ #
    def warmup(self, params, buffers, shapes: Sequence[tuple],
               dtype) -> int:
        """Pre-compile an executable per (single-array) shape; returns
        how many were newly compiled.  Warmup counts neither hits nor
        misses — the hit-rate metric describes traffic, not
        provisioning."""
        import jax.numpy as jnp

        return self.warmup_inputs(
            params, buffers, [jnp.zeros(shape, dtype) for shape in shapes])

    def warmup_inputs(self, params, buffers, inputs: Sequence) -> int:
        """Pre-compile an executable per example input (each a single
        array or pytree — the multi-tensor analog of ``warmup``);
        returns how many were newly compiled."""
        compiled = 0
        for x in inputs:
            key = self.key_for(x, params)
            with self._lock:
                present = key in self._entries
            if present:
                continue
            if self._admit(key, self._compile(params, buffers, x),
                           count=False):
                compiled += 1
        return compiled

    # ------------------------------------------------------------------ #
    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else None,
                "ledger_tag": self.ledger_tag,
            }
