"""PlacementPolicy: pack replicas onto mesh slots, report headroom.

The ReplicaSet asks the policy for a slot per replica (acquire) and
hands slots back when replicas drain (release); `headroom()` is the
scale-up gate the SLO controller consults before growing — the same
contract as PR 7's `kvcache_headroom`: a falsy answer makes the ladder
fall through to admission tightening instead of oversubscribing
devices.

Slots are **phase-taggable**: disaggregated serving acquires a slot
*as* a prefill or decode replica (``acquire(phase=...)``), so the
policy can report per-phase occupancy (``serving/placement/phase/*``
gauges) and the DisaggCoordinator's two SLO ladders each see how much
of the device set their phase already holds.  Any free slot can serve
any phase — the tag records intent, it does not partition the
hardware — so ``headroom()`` stays one number.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from bigdl_tpu.serving.placement.slicer import (MeshSlice, MeshSlicer,
                                                PlacementError)
from bigdl_tpu.serving.placement.topology import DeviceTopology


class PlacementPolicy:
    """Carve once, then hand out slots first-fit.

    Args:
        topology: device set to carve (default: detect the live backend).
        slots: number of replica slots; default ``max_slots(tp)`` — use
            everything the backend has.
        tp: tensor-parallel degree within each slot.
    """

    def __init__(self, topology: Optional[DeviceTopology] = None, *,
                 slots: Optional[int] = None, tp: int = 1):
        slicer = MeshSlicer(topology)
        if slots is None:
            slots = max(1, slicer.max_slots(tp))
        self.tp = int(tp)
        self._slices: List[MeshSlice] = slicer.carve(slots, tp)
        self._free: List[MeshSlice] = list(self._slices)
        self._phase: Dict[int, str] = {}   # slot_id -> phase tag
        self._seen_phases: set = set()     # gauges zero out on release
        self._lock = threading.Lock()
        self._publish()

    # -- slot lifecycle -------------------------------------------------

    def acquire(self, phase: Optional[str] = None) -> Optional[MeshSlice]:
        """Lowest-id free slot, or None when the device set is full.
        ``phase`` tags the slot for the duration of the lease (e.g.
        ``"prefill"`` / ``"decode"`` from the DisaggCoordinator) so
        per-phase occupancy is observable; untagged acquires keep the
        original contract."""
        with self._lock:
            if not self._free:
                return None
            s = self._free.pop(0)
            if phase is not None:
                self._phase[s.slot_id] = str(phase)
                self._seen_phases.add(str(phase))
        self._publish()
        return s

    def release(self, s: MeshSlice) -> None:
        with self._lock:
            if s not in self._slices:
                raise PlacementError(f"{s!r} was not carved by this policy")
            if s in self._free:
                raise PlacementError(f"{s!r} released twice")
            self._free.append(s)
            self._free.sort(key=lambda m: m.slot_id)
            self._phase.pop(s.slot_id, None)
        self._publish()

    def phase_of(self, s: MeshSlice) -> Optional[str]:
        """The phase tag a held slot was acquired under (None when
        untagged or free)."""
        with self._lock:
            return self._phase.get(s.slot_id)

    def phase_counts(self) -> Dict[str, int]:
        """Held slots per phase tag (untagged leases count under
        ``"untagged"``)."""
        with self._lock:
            held = [s for s in self._slices if s not in self._free]
            out: Dict[str, int] = {}
            for s in held:
                key = self._phase.get(s.slot_id, "untagged")
                out[key] = out.get(key, 0) + 1
            return out

    # -- accounting -----------------------------------------------------

    @property
    def slots_total(self) -> int:
        return len(self._slices)

    def headroom(self) -> int:
        """Free slots — 0 means scale-up must be refused."""
        with self._lock:
            return len(self._free)

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
            slots = []
            for s in self._slices:
                d = s.describe()
                d["phase"] = self._phase.get(s.slot_id)
                slots.append(d)
        return {
            "slots_total": self.slots_total,
            "slots_used": self.slots_total - free,
            "slots_free": free,
            "devices_per_slot": self.tp,
            "phase_counts": self.phase_counts(),
            "slots": slots,
        }

    def _publish(self) -> None:
        from bigdl_tpu.obs import get_registry
        reg = get_registry()
        with self._lock:
            free = len(self._free)
        reg.gauge("serving/placement/slots_total").set(self.slots_total)
        reg.gauge("serving/placement/slots_used").set(self.slots_total - free)
        reg.gauge("serving/placement/devices_per_slot").set(self.tp)
        counts = self.phase_counts()
        with self._lock:
            phases = set(self._seen_phases)
        for phase in phases:
            reg.gauge(f"serving/placement/phase/{phase}").set(
                counts.get(phase, 0))

    def __repr__(self) -> str:
        return (f"PlacementPolicy({self.slots_total} slots x TP{self.tp}, "
                f"{self.headroom()} free)")
