"""Sharding rules for SERVED nn modules, and a chunked sharded loader.

`parallel.tensor_parallel` wrote its specs for (in, out) training
weights; `nn.Linear` preserves the Torch layout — weight is
(output_size, input_size) with y = x @ W.T — so the Megatron dims flip:
column-parallel (shard the OUTPUT dim, no forward comm) is P(axis, None)
here and row-parallel (shard the INPUT dim, one psum) is P(None, axis).

`serving_tp_rules` derives the alternating col/row pairing from the
module tree itself (forward-order Linears inside Containers), which
also makes it layout-uniform over int8 `QTensor` leaves: a QTensor's
children are (q, scale) with q shaped like the weight and scale
(out, 1) keepdims, so the same divisibility-guarded shape rule shards
q and scale together under col and correctly replicates the (out, 1)
scale under row.  Any dim the TP degree does not divide degrades to
replicated — sharding specs are placement hints, XLA guarantees the
same numerics either way.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.mesh import MODEL_AXIS, replicated


def _linear_prefixes(module, prefix: str = "") -> list:
    """Param-path prefixes of every Linear, in forward order."""
    from bigdl_tpu.nn.linear import Linear
    if isinstance(module, Linear):
        return [prefix]
    out = []
    mods = getattr(module, "modules", None)
    if isinstance(mods, (list, tuple)):
        for i, m in enumerate(mods):
            out.extend(_linear_prefixes(m, f"{prefix}['{i}']"))
    return out


def serving_tp_rules(module, mesh: Mesh, axis: str = MODEL_AXIS
                     ) -> Callable[[tuple, Any], Optional[NamedSharding]]:
    """Megatron col/row alternation over a served module's Linears.

    Returns a ``rules(path, leaf)`` callable for
    :func:`shard_params_chunked` / ``tensor_parallel.shard_params``.
    Leaves outside any Linear, and dims ``tp`` does not divide, return
    None (caller replicates).  TransformerLM serving does NOT go
    through this — it has its own layer-stacked
    ``transformer_lm_tp_rules``.
    """
    tp = mesh.shape[axis]
    prefixes = _linear_prefixes(module)

    def _col(shp) -> Optional[NamedSharding]:
        # shard dim 0: weight (out, in), bias (out,), qscale (out, 1)
        if len(shp) >= 1 and shp[0] >= tp and shp[0] % tp == 0:
            return NamedSharding(mesh, P(axis, *([None] * (len(shp) - 1))))
        return None

    def _row(shp) -> Optional[NamedSharding]:
        # shard dim 1: weight (out, in); bias and (out, 1) qscale stay
        # replicated — the psum output is full-width on every device
        if len(shp) >= 2 and shp[1] >= tp and shp[1] % tp == 0:
            return NamedSharding(mesh, P(None, axis, *([None] * (len(shp) - 2))))
        return None

    def rules(path, leaf):
        if tp <= 1:
            return None
        name = jax.tree_util.keystr(path)
        shp = tuple(getattr(leaf, "shape", ()))
        for j, pfx in enumerate(prefixes):
            if name.startswith(pfx + "["):
                return _col(shp) if j % 2 == 0 else _row(shp)
        return None

    return rules


def shard_params_chunked(params: Any,
                         rules: Callable[[tuple, Any], Optional[NamedSharding]],
                         mesh: Mesh, *, chunk_bytes: Optional[int] = None) -> Any:
    """`tensor_parallel.shard_params`, but every leaf rides the
    resilient 32 MB-chunked transfer straight to its sharded layout —
    one pass, no dense single-device detour (the round-4 relay died on
    a ~154 MB buffer; a big replicated-then-reshard would recreate it).
    """
    from bigdl_tpu.utils.transfer import DEFAULT_CHUNK_BYTES, chunked_device_put
    if chunk_bytes is None:
        chunk_bytes = DEFAULT_CHUNK_BYTES
    rep = replicated(mesh)

    def place(path, leaf):
        return chunked_device_put(leaf, chunk_bytes=chunk_bytes,
                                  device=rules(path, leaf) or rep)

    return jax.tree_util.tree_map_with_path(place, params)
