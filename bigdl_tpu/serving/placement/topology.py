"""DeviceTopology: enumerate and describe the backend's devices.

The serving-side analog of the reference's Engine.init topology
discovery (one executor = one node, N cores = N task slots): ask the
backend what it has, report it in one serializable dict, and degrade
gracefully — a single-device backend (or one that refuses to answer,
the dead-tunnel case) still yields a usable 1-device topology so every
placement-aware code path runs unchanged on a laptop CPU.
"""
from __future__ import annotations

from typing import Optional, Sequence


class DeviceTopology:
    """A frozen snapshot of the backend's device set.

    Args:
        devices: explicit device list (tests pass a slice of the fake
            mesh); default: ``jax.devices()``.

    Attributes:
        devices: tuple of jax Device objects (may be empty only when
            the backend could not be reached — see :meth:`detect`).
        platform / device_kind: of the first device ("unknown" when
            unreachable).
        degraded: True when detection fell back because the backend
            raised (the tunneled-relay wedge) — carving anything wider
            than the devices actually held raises PlacementError.
    """

    def __init__(self, devices: Optional[Sequence] = None, *,
                 degraded: bool = False):
        if devices is None:
            import jax
            devices = jax.devices()
        self.devices = tuple(devices)
        self.degraded = bool(degraded)
        if self.devices:
            self.platform = getattr(self.devices[0], "platform", "unknown")
            self.device_kind = getattr(self.devices[0], "device_kind",
                                       "unknown")
        else:
            self.platform = "unknown"
            self.device_kind = "unknown"

    @classmethod
    def detect(cls, platform: Optional[str] = None) -> "DeviceTopology":
        """Topology of the live backend; never raises.  A backend that
        fails to answer (dead relay mid-init) yields an empty degraded
        topology instead of wedging the caller — the serving stack then
        surfaces the real error at first dispatch, where the resilience
        layer's classification and retries own it."""
        import jax
        try:
            devs = jax.devices(platform) if platform else jax.devices()
        except Exception:  # noqa: BLE001 — backend init is the hazard here
            return cls(devices=(), degraded=True)
        return cls(devices=devs)

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def describe(self) -> dict:
        """One serializable snapshot (BENCH_MESH.json embeds it)."""
        return {
            "platform": self.platform,
            "device_kind": self.device_kind,
            "n_devices": self.n_devices,
            "degraded": self.degraded,
            "devices": [
                {"id": int(d.id),
                 "platform": getattr(d, "platform", "unknown"),
                 "process_index": int(getattr(d, "process_index", 0))}
                for d in self.devices],
        }

    def __repr__(self) -> str:
        return (f"DeviceTopology({self.n_devices}x{self.platform}"
                f"{', degraded' if self.degraded else ''})")
