"""MeshSlicer: carve a device set into named submeshes (replica slots).

A *slot* is the serving unit of placement: a contiguous group of ``tp``
devices carrying one engine replica, tensor-parallel within the slot.
Data parallelism across slots is NOT a mesh axis here — it is the
ReplicaSet's least-loaded dispatch, so a dead slot is a replica-death
event the resilience layer already handles, not a collective hang.
Each slot therefore gets its own 1-axis ``model`` mesh rather than one
global 2-D mesh.
"""
from __future__ import annotations

from typing import List, Optional

from bigdl_tpu.parallel.mesh import MODEL_AXIS, create_mesh, replicated
from bigdl_tpu.serving.placement.topology import DeviceTopology


class PlacementError(RuntimeError):
    """A carve or acquire that the device set cannot satisfy."""


class MeshSlice:
    """One replica slot: ``tp`` devices under a 1-D ``model``-axis mesh.

    The slice IS the engine's placement parameter — it owns the mesh and
    derives every sharding the engine needs from it.
    """

    __slots__ = ("slot_id", "devices", "tp", "mesh")

    def __init__(self, slot_id: int, devices, tp: int):
        if len(devices) != tp:
            raise PlacementError(
                f"slot {slot_id}: {len(devices)} devices != tp={tp}")
        self.slot_id = int(slot_id)
        self.devices = tuple(devices)
        self.tp = int(tp)
        self.mesh = create_mesh({MODEL_AXIS: tp}, devices=list(devices))

    @property
    def tag(self) -> str:
        """Stable string for compile-cache keys and stats: the same
        bucket compiled for a different slot (different devices) must
        not collide in a shared CompileCache."""
        return f"slot{self.slot_id}:tp{self.tp}:d{','.join(str(i) for i in self.device_ids)}"

    @property
    def device_ids(self) -> tuple:
        return tuple(int(d.id) for d in self.devices)

    def replicated(self):
        """NamedSharding replicating a value across the slot's devices."""
        return replicated(self.mesh)

    def input_sharding(self):
        """Where staged request payloads land: replicated across the
        slot (TP shards weights, not the batch — every device sees the
        full batch and XLA psums the row-parallel outputs)."""
        return replicated(self.mesh)

    def describe(self) -> dict:
        return {"slot_id": self.slot_id, "tp": self.tp,
                "device_ids": list(self.device_ids)}

    def __repr__(self) -> str:
        return f"MeshSlice({self.tag})"


class MeshSlicer:
    """Carve a :class:`DeviceTopology` into equal-width replica slots."""

    def __init__(self, topology: Optional[DeviceTopology] = None):
        self.topology = topology or DeviceTopology.detect()

    def max_slots(self, tp: int = 1) -> int:
        """How many tp-wide slots the device set can hold."""
        if tp < 1:
            raise PlacementError(f"tp must be >= 1, got {tp}")
        return self.topology.n_devices // tp

    def carve(self, slots: int, tp: int = 1) -> List[MeshSlice]:
        """``slots`` slices of ``tp`` contiguous devices each.

        Contiguity matters on real hardware: jax.devices() orders TPU
        chips by ICI coordinates, so adjacent ids share the fastest
        links — the same reason the reference pinned one executor's
        task slots to one physical node.
        """
        if slots < 1:
            raise PlacementError(f"slots must be >= 1, got {slots}")
        need = slots * tp
        have = self.topology.n_devices
        if need > have:
            raise PlacementError(
                f"cannot carve {slots} slot(s) x TP{tp} = {need} devices "
                f"from a {have}-device topology"
                f"{' (degraded detection)' if self.topology.degraded else ''}")
        devs = self.topology.devices
        return [MeshSlice(i, devs[i * tp:(i + 1) * tp], tp)
                for i in range(slots)]
