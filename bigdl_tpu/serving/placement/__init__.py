"""bigdl_tpu.serving.placement — device topology, mesh slicing, and
replica placement for multi-chip serving.

The reference framework's core trick was mapping each physical compute
unit to a Spark task slot so one engine drove the whole cluster
(Engine.init, arXiv 1804.05839).  The TPU-native equivalent is
placement as a first-class ``NamedSharding`` parameter (GSPMD named
meshes, arXiv 2004.13336): carve the backend's devices into named
submeshes — N data-parallel replica *slots* x M-way tensor-parallel
within a slot — hand each :class:`~bigdl_tpu.serving.engine.ServingEngine`
replica its slot's :class:`MeshSlice`, and XLA inserts the collectives.

Three layers, smallest first:

- :class:`DeviceTopology` — enumerate/describe the backend's devices;
  degrades gracefully to one device (a laptop CPU serves exactly as
  before, through a 1-slot x TP1 slice).
- :class:`MeshSlicer` — carve the device set into :class:`MeshSlice`
  submeshes, reusing :mod:`bigdl_tpu.parallel.mesh` axis names (a slot's
  mesh is a 1-D ``model`` axis — tensor parallelism *within* the slot;
  data parallelism *across* slots is the ReplicaSet's dispatch).
- :class:`PlacementPolicy` — pack replicas onto slots (acquire/release
  with headroom accounting), publish ``serving/placement/*`` gauges.

Everything is proven on CPU with the 8-virtual-device fake mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8`` — the
mosaic_export_check pattern): ``bench.py --serve --mesh`` writes the
resumable BENCH_MESH.json comparing single-device vs 2-slot x TP2 vs
1-slot x TP4 against the unsharded oracle.
"""
from bigdl_tpu.serving.placement.topology import DeviceTopology
from bigdl_tpu.serving.placement.slicer import (MeshSlice, MeshSlicer,
                                                PlacementError)
from bigdl_tpu.serving.placement.policy import PlacementPolicy
from bigdl_tpu.serving.placement.rules import (serving_tp_rules,
                                               shard_params_chunked)

__all__ = [
    "DeviceTopology", "MeshSlice", "MeshSlicer", "PlacementError",
    "PlacementPolicy", "serving_tp_rules", "shard_params_chunked",
]
