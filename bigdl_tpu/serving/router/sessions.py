"""Session stickiness that composes with kvtier hibernation.

A "session" is a caller-provided identity spanning multiple requests
(chat turns share it).  The table remembers which replica last served
each session, and — the part that composes with the host KV tier —
which replica's :class:`HostBlockStore` holds a **hibernated** stream's
``("session", rid)`` entry.  Stickiness is a *preference*: the routed
set consults the table before the affinity score, but a dead or
breaker-open sticky replica is simply skipped — the request re-routes,
re-prefills, and the table is repointed (bit-exact by deterministic
prefill + the seeded sampling chain), never stranded.

Bounded LRU: sessions are client-driven state with no natural end, so
the table caps at ``max_sessions`` and silently forgets the oldest —
a forgotten session just degrades to a cold (affinity-scored) dispatch.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional


class _Session:
    __slots__ = ("replica", "hibernated_on", "turns")

    def __init__(self, replica: str):
        self.replica = replica
        self.hibernated_on: Optional[str] = None
        self.turns = 0


class SessionTable:
    """Thread-safe session → replica affinity map (bounded LRU)."""

    def __init__(self, max_sessions: int = 4096):
        self.max_sessions = int(max_sessions)
        self._lock = threading.Lock()
        self._table: "OrderedDict[str, _Session]" = OrderedDict()
        self.sticky_hits = 0
        self.re_routes = 0
        self.evicted = 0

    def record(self, session_id: str, replica: str) -> None:
        """A request for ``session_id`` was dispatched to ``replica``
        (repointing clears any hibernation marker — the live stream is
        wherever it runs now)."""
        with self._lock:
            s = self._table.pop(session_id, None)
            if s is None:
                s = _Session(replica)
                while len(self._table) >= self.max_sessions:
                    self._table.popitem(last=False)
                    self.evicted += 1
            else:
                s.replica = replica
                s.hibernated_on = None
            s.turns += 1
            self._table[session_id] = s

    def lookup(self, session_id: Optional[str]) -> Optional[str]:
        """Preferred replica for the session (refreshes LRU), or None."""
        if session_id is None:
            return None
        with self._lock:
            s = self._table.pop(session_id, None)
            if s is None:
                return None
            self._table[session_id] = s
            return s.hibernated_on or s.replica

    def mark_hibernated(self, session_id: str, replica: str) -> None:
        """The session's stream hibernated into ``replica``'s host
        tier: resuming THERE promotes the chain back through the 32 MB
        chunked path instead of re-prefilling."""
        with self._lock:
            s = self._table.get(session_id)
            if s is None:
                s = _Session(replica)
                self._table[session_id] = s
            s.hibernated_on = replica

    def note_sticky_hit(self) -> None:
        with self._lock:
            self.sticky_hits += 1

    def note_re_route(self) -> None:
        with self._lock:
            self.re_routes += 1

    def forget(self, session_id: str) -> None:
        with self._lock:
            self._table.pop(session_id, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._table)

    def stats(self) -> dict:
        with self._lock:
            return {"sessions": len(self._table),
                    "max_sessions": self.max_sessions,
                    "sticky_hits": self.sticky_hits,
                    "re_routes": self.re_routes,
                    "evicted": self.evicted}
