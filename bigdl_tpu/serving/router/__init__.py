"""bigdl_tpu.serving.router — cache-aware replica dispatch.

Prefix-affinity routing over per-replica radix summaries, session
stickiness that composes with kvtier hibernation, and the routed LM
replica set that inherits the resilience breaker core.  This is the
control-plane layer the multi-host pool stands on: the router never
reads a remote trie, only its published fingerprint summary.

Quickstart::

    from bigdl_tpu.serving.router import LMReplicaSet, RadixRouter

    rset = LMReplicaSet(model, n_replicas=3,
                        router=RadixRouter(affinity_weight=0.7),
                        slots=8, max_new_tokens=32)
    s = rset.submit(prompt, session_id="chat-42")
    for tok in s.tokens():
        ...
"""
from bigdl_tpu.serving.router.replicaset import (LMReplicaSet,
                                                 RoutedLMStream)
from bigdl_tpu.serving.router.router import RadixRouter
from bigdl_tpu.serving.router.sessions import SessionTable
from bigdl_tpu.serving.router.summary import RadixSummary

__all__ = ["LMReplicaSet", "RoutedLMStream", "RadixRouter",
           "SessionTable", "RadixSummary"]
