"""LMReplicaSet: N LMServingEngine replicas behind one routed front.

The LM twin of :class:`~bigdl_tpu.resilience.replicaset.ReplicaSet`,
built on the same :class:`ReplicaSetCore` breaker machinery, with the
unit of dispatch changed from a padded batch to a **stream**: each
submit picks a replica once (sticky session → affinity score →
least-loaded fallback, in that order) and the request's whole
prefill+decode life runs there, so the replica's RadixCache actually
accumulates the session's prefix.

Failover is stream-granular and bit-exact: a relay thread forwards the
inner engine stream into the client-visible :class:`RoutedLMStream`;
when the inner stream dies with a re-routable error (transient,
backend-lost, or the member engine closing), the relay re-submits the
SAME prompt with the SAME seed/temperature to another replica and
skips the tokens it already forwarded — deterministic prefill plus the
seeded sampling chain make the replayed tokens identical, so the
client sees one uninterrupted, exact stream (the re-prefill+replay
contract kvtier and disagg already honor).  An accepted request is
lost only when every replica is gone, same as the batch set.

Hibernation composes: :meth:`hibernate` swaps the stream into its
replica's host tier and records that replica in the session table;
:meth:`resume` prefers it (chunked promote — no recompute).  If the
sticky replica died meanwhile, its ``_fail_all`` already resolved the
hibernated inner stream with an error, the relay has re-prefilled and
replayed elsewhere, and the session is repointed — degraded, never
stranded.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

import numpy as np

from bigdl_tpu.obs import get_registry, get_tracer
from bigdl_tpu.obs.tracer import mint_request_id
from bigdl_tpu.resilience.errors import (BackendLostError,
                                         ServingDeadlineExceeded,
                                         classify_error)
from bigdl_tpu.resilience.replicaset import (DRAINING, HedgePolicy,
                                             ReplicaSetCore, _Replica)
from bigdl_tpu.serving.batcher import ServingClosed, ServingOverloaded
from bigdl_tpu.serving.kvcache.radix import prefix_signatures
from bigdl_tpu.serving.lm_engine import (LMMetrics, LMServingEngine,
                                         LMStream)
from bigdl_tpu.serving.router.router import RadixRouter
from bigdl_tpu.serving.router.sessions import SessionTable
from bigdl_tpu.serving.router.summary import RadixSummary

log = logging.getLogger("bigdl_tpu.serving")
_tracer = get_tracer()


class RoutedLMStream(LMStream):
    """Client handle for a routed request: an :class:`LMStream` whose
    tokens arrive via the relay, surviving replica failover underneath.
    ``replica_name`` / ``inner`` track the CURRENT placement (they move
    on failover); ``re_dispatches`` counts the hops; ``hedged`` marks a
    request that fired a speculative duplicate dispatch."""

    def __init__(self, prompt_1b, max_new, request_id=None,
                 session_id=None, deadline_s=None):
        super().__init__(prompt_1b, max_new, request_id=request_id,
                         deadline_s=deadline_s)
        self.session_id = session_id
        self.replica_name: Optional[str] = None
        self.inner: Optional[LMStream] = None
        self.re_dispatches = 0
        self.hedged = False
        self._hedge_inner: Optional[LMStream] = None

    def cancel(self) -> bool:
        """Cooperative cancel, propagated through the routed front:
        the CURRENT inner engine stream (and a hedge duplicate, if one
        is in flight) each get the cancel, so every replica touching
        this request recycles its slot at its next scheduler round."""
        live = super().cancel()
        for s in (self.inner, self._hedge_inner):
            if s is not None:
                try:
                    s.cancel()
                except Exception:
                    pass
        return live


class LMReplicaSet(ReplicaSetCore):
    """Serve one built ``TransformerLM`` from ``n_replicas`` engines
    with cache-aware routing and stream-granular failover.

    Args:
        model: a built ``TransformerLM`` — every replica freezes the
            same params, so any replica's output for a given
            (prompt, seed, temperature) is exactly the single-engine
            output: the bit-exact replay failover depends on this.
        n_replicas: member count (default 2).
        router: a :class:`RadixRouter` for prefix-affinity dispatch, or
            None for the radix-blind least-loaded baseline (the bench's
            control arm).  Each member's RadixCache publishes a
            :class:`RadixSummary` into the router.
        sessions: a :class:`SessionTable` (default: private table) —
            session stickiness runs ahead of affinity scoring.
        kvtier_factory: ``factory(replica_name) -> HostBlockStore | None``
            building one PRIVATE host tier per replica (a shared store
            would alias ``("session", rid)`` keys across members).
        failure_threshold / cooldown_s / max_redispatch / clock: the
            :class:`ReplicaSetCore` breaker knobs (max_redispatch
            defaults to ``n_replicas - 1``: try every other member).
        hedge: a :class:`HedgePolicy` enabling speculative re-dispatch
            (Spark's speculative execution reborn at stream granularity):
            a hedge-eligible request whose wait-to-first-token exceeds
            the policy's windowed tail trigger is duplicated onto the
            next-best replica; the first stream to finish wins and the
            loser is cooperatively cancelled.  None (default) disables.
        **engine_kwargs: forwarded to every :class:`LMServingEngine`
            (slots, cache_len, block_len, num_blocks, temperature, ...).
    """

    def __init__(self, model, n_replicas: int = 2, *,
                 router: Optional[RadixRouter] = None,
                 sessions: Optional[SessionTable] = None,
                 kvtier_factory: Optional[Callable] = None,
                 failure_threshold: int = 3,
                 cooldown_s: float = 5.0,
                 max_redispatch: Optional[int] = None,
                 clock=time.monotonic,
                 hedge: Optional[HedgePolicy] = None,
                 name: str = "lmset",
                 **engine_kwargs):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self._init_core(
            failure_threshold=failure_threshold, cooldown_s=cooldown_s,
            max_redispatch=(int(max_redispatch) if max_redispatch
                            is not None else max(1, n_replicas - 1)),
            clock=clock, dispatch_policy=self._policy,
            hedge_policy=hedge)
        self.name = name
        self.router = router
        self.sessions = sessions if sessions is not None else SessionTable()
        self.hibernations = 0
        self.resumes = 0
        self.resume_re_routes = 0
        self._closed = False
        reg = self._registry
        self._c_dispatches = reg.counter("serving/router/dispatches")
        self._c_sticky = reg.counter("serving/router/sticky_hits")
        self._c_re_routes = reg.counter("serving/router/re_routes")
        # one shared LMMetrics: set-wide TTFT/ITL histograms (the SLO
        # view), same pattern as the disagg phase pools
        slots = int(engine_kwargs.get("slots", 8))
        self.metrics = LMMetrics(slots * n_replicas)
        for i in range(n_replicas):
            ename = f"{name}-r{i}"
            tier = kvtier_factory(ename) if kvtier_factory else None
            eng = LMServingEngine(model, name=ename, metrics=self.metrics,
                                  kvtier=tier, **engine_kwargs)
            rep = _Replica(ename, eng)
            if self.router is not None and eng.radix is not None:
                summary = RadixSummary(ename)
                eng.attach_radix_summary(summary)
                self.router.register(ename, summary)
            self._replicas.append(rep)
        self.block_len = self._replicas[0].engine.block_len
        self.max_new_tokens = self._replicas[0].engine.max_new_tokens
        self._publish_open_circuits()
        self._publish_replica_count()
        try:
            import weakref
            from bigdl_tpu.obs import flight
            wself = weakref.ref(self)

            def _flight_state():
                rs = wself()
                return rs.stats() if rs is not None else None
            flight.register_state("lm_replicaset", _flight_state)
        except Exception:
            pass

    # -- replica selection ----------------------------------------------- #
    def _policy(self, healthy, ctx):
        """ReplicaSetCore dispatch policy: sticky session first, then
        the router's affinity score; None falls back to least-loaded.
        Runs under the set lock — lookups only, no engine calls."""
        sticky = ctx.get("sticky")
        if sticky is not None:
            for r in healthy:
                if r.name == sticky:
                    ctx["picked_sticky"] = True
                    return r
            # the preferred replica is excluded/unhealthy/gone: the
            # request re-routes (and re-prefills) elsewhere
            ctx["sticky_lost"] = True
        if self.router is not None:
            return self.router.pick(healthy, ctx)
        return None

    def _by_name(self, name: str) -> Optional[_Replica]:
        with self._lock:
            for r in self._replicas:
                if r.name == name:
                    return r
        return None

    # -- dispatch --------------------------------------------------------- #
    def _dispatch(self, prompt, kw: dict, ctx: dict, tried: set):
        """Pick a replica and enqueue the prompt there, walking the
        candidates on replica-local failures.  Returns ``(rep, inner)``
        with the pick's inflight slot held (released by the relay's
        success/failure record).  Raises the last typed overload when
        every candidate shed, BackendLostError when none was left."""
        last: Optional[BaseException] = None
        while True:
            ctx.pop("picked_sticky", None)
            ctx.pop("sticky_lost", None)
            rep = self._pick(tried, ctx)
            if rep is None:
                if isinstance(last, ServingOverloaded):
                    raise last   # saturated, not gone: typed backpressure
                self._registry.counter("resilience/backend_lost").add(1)
                raise BackendLostError(
                    f"no LM replica available ({len(tried)} tried): "
                    f"{last}") from last
            try:
                inner = rep.engine.submit(prompt, **kw)
            except ServingDeadlineExceeded:
                # a blown deadline is a property of the REQUEST, not of
                # the replica: walking more candidates cannot un-expire
                # it, and charging the breaker would punish a healthy
                # member for correct admission control.  Release the
                # inflight slot as a clean interaction and surface the
                # typed shed to the caller.
                self._record_success(rep)
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                self._record_failure(rep, e)
                # a closed MEMBER is a dead replica, not a dead set
                if (classify_error(e) == "fatal"
                        and not isinstance(e, ServingClosed)):
                    raise
                tried.add(rep.name)
                last = e
                continue
            if ctx.pop("picked_sticky", False):
                self.sessions.note_sticky_hit()
                self._c_sticky.add(1)
            elif ctx.pop("sticky_lost", False):
                self.sessions.note_re_route()
                self._c_re_routes.add(1)
            sid = ctx.get("session_id")
            if sid is not None:
                self.sessions.record(sid, rep.name)
            self._c_dispatches.add(1)
            return rep, inner

    def submit(self, prompt_ids, *, session_id: Optional[str] = None,
               max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None,
               rng=None, deadline_s: Optional[float] = None,
               hedgeable: bool = False) -> RoutedLMStream:
        """Route one prompt; returns a stream that survives the death
        of any replica serving it.  Pass ``rng`` as an int seed when
        ``temperature > 0`` — failover re-submits with the same seed,
        which is what keeps the replayed tokens identical.

        ``deadline_s`` is the request's end-to-end wall-clock budget,
        minted HERE: failover re-dispatch forwards the REMAINING budget
        (never a reset one), and each member engine sheds/truncates
        against the same absolute instant.  ``hedgeable=True`` marks a
        request the client consumes whole (not token-by-token), making
        it eligible for the set's :class:`HedgePolicy` speculative
        duplicate — duplicated decode is invisible only when nobody is
        watching the stream race."""
        if self._closed:
            raise ServingClosed("LMReplicaSet is closed")
        prompt = np.asarray(prompt_ids).reshape(-1).astype(np.int32)
        rid = mint_request_id()
        ctx = {
            "rid": rid,
            "session_id": session_id,
            "sticky": self.sessions.lookup(session_id),
            "prompt_sigs": prefix_signatures(prompt - 1, self.block_len),
            "hedgeable": bool(hedgeable),
        }
        kw = dict(max_new_tokens=max_new_tokens, temperature=temperature,
                  eos_id=eos_id, rng=rng, deadline_s=deadline_s)
        tried: set = set()
        if self.hedge_policy is not None:
            self.hedge_policy.note_dispatch()
        rep, inner = self._dispatch(prompt, kw, ctx, tried)
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        out = RoutedLMStream(prompt, max_new, request_id=rid,
                             session_id=session_id, deadline_s=deadline_s)
        out.replica_name, out.inner = rep.name, inner
        t = threading.Thread(
            target=self._relay, args=(out, rep, inner, prompt, kw, ctx),
            name=f"{self.name}-relay-{rid}", daemon=True)
        t.start()
        return out

    def _relay(self, out: RoutedLMStream, rep, inner, prompt, kw, ctx):
        """Forward the inner stream into the routed one; on a
        re-routable death, re-submit the same request elsewhere and
        skip what the client already saw (bit-exact replay).  The relay
        is also where the request's lifecycle rides the hops: a hedge
        window opens before the first token, failover forwards the
        REMAINING deadline budget, and a client cancel noticed here
        short-circuits re-dispatch entirely."""
        tried: set = set()
        while True:
            if (self.hedge_policy is not None and ctx.get("hedgeable")
                    and not out.hedged and len(out.generated) == 0):
                picked = self._maybe_hedge(out, rep, inner, prompt, kw,
                                           ctx, tried)
                if picked is not None:
                    rep, inner = picked
                    out.replica_name, out.inner = rep.name, inner
            try:
                skip = len(out.generated)
                i = 0
                for tok in inner.tokens():
                    i += 1
                    if i > skip:
                        out._emit(tok)
                self._record_success(rep)
                if self.hedge_policy is not None and not out.hedged:
                    ttft = inner.ttft_s
                    if ttft is not None:
                        self.hedge_policy.observe(ttft)
                tr = getattr(inner, "truncation", None)
                if tr is not None and out.truncation is None:
                    # the member truncated (deadline/cancel honored
                    # mid-stream): the routed front finishes the same
                    # way — cleanly, with the typed marker
                    out._finish_truncated(tr.reason)
                else:
                    out._finish()
                return
            except BaseException as e:  # noqa: BLE001 — classified below
                if isinstance(e, ServingDeadlineExceeded):
                    # the member shed a blown deadline: correct
                    # admission control, not a replica fault — don't
                    # charge the breaker, don't walk other replicas
                    self._record_success(rep)
                    if len(out.generated):
                        out._finish_truncated("deadline")
                    else:
                        out._finish(e)
                    return
                self._record_failure(rep, e)
                if (classify_error(e) == "fatal"
                        and not isinstance(e, ServingClosed)):
                    out._finish(e)
                    return
                if out.cancel_requested:
                    # the client already walked away: re-dispatching
                    # would burn decode on an unwatched stream
                    out._finish_truncated("cancelled")
                    return
                rem = out.remaining_s()
                if isinstance(e, ServingDeadlineExceeded) or (
                        rem is not None and rem <= 0.0):
                    # the budget died with the replica: no re-dispatch
                    if len(out.generated):
                        out._finish_truncated("deadline")
                    else:
                        out._finish(e if isinstance(
                            e, ServingDeadlineExceeded)
                            else ServingDeadlineExceeded(
                                f"request {out.request_id} deadline "
                                f"expired during failover"))
                    return
                tried.add(rep.name)
                out.re_dispatches += 1
                if out.re_dispatches > self.max_redispatch:
                    self._registry.counter("resilience/backend_lost").add(1)
                    out._finish(BackendLostError(
                        f"stream failed on {out.re_dispatches} replicas "
                        f"(re-dispatch bound reached): {e}"))
                    return
                self._registry.counter("resilience/failovers").add(1)
                self._c_re_routes.add(1)
                self.sessions.note_re_route()
                if _tracer.sampled(out.request_id):
                    _tracer.instant(
                        "router/failover", cat="serve",
                        request_id=out.request_id, failed_replica=rep.name,
                        re_dispatch=out.re_dispatches,
                        replayed_tokens=len(out.generated),
                        error=f"{type(e).__name__}: {e}")
                log.warning("%s: stream %s lost replica %s, re-routing "
                            "(%d/%d, replaying %d tokens): %s", self.name,
                            out.request_id, rep.name, out.re_dispatches,
                            self.max_redispatch, len(out.generated), e)
                ctx = dict(ctx)
                ctx["sticky"] = None   # the sticky replica just failed
                if rem is not None:
                    # the re-dispatch inherits what is LEFT of the
                    # budget, never a fresh one — a hop is not a reason
                    # to promise the client more time
                    kw = dict(kw)
                    kw["deadline_s"] = rem
                try:
                    rep, inner = self._dispatch(prompt, kw, ctx, tried)
                except BaseException as e2:  # noqa: BLE001
                    out._finish(e2)
                    return
                out.replica_name, out.inner = rep.name, inner

    def _maybe_hedge(self, out: RoutedLMStream, rep, inner, prompt, kw,
                     ctx, tried: set):
        """Hedge window: wait for the primary's first token up to the
        policy's tail trigger; past it (and within the hedge budget),
        duplicate the request onto the next-best replica and race the
        two streams.  Returns the winning ``(rep, inner)`` pair for the
        relay to forward, or None to continue with the primary.  Both
        replicas compute identical tokens (same prompt, same seed), so
        whichever finishes first IS the answer — the loser is
        cooperatively cancelled and frees its slot within one scheduler
        round."""
        pol = self.hedge_policy
        trig = pol.trigger_s()
        if trig is None:
            return None   # not enough wait evidence to aim a hedge yet
        with inner._cond:
            inner._cond.wait_for(
                lambda: inner._tokens or inner._done,
                timeout=max(0.0, (out.submitted_at + trig)
                            - time.perf_counter()))
            started = bool(inner._tokens) or inner._done
        if started:
            return None   # primary is producing (or already resolved)
        waited = time.perf_counter() - out.submitted_at
        if not pol.should_hedge(waited):
            return None
        hctx = dict(ctx)
        hctx["sticky"] = None   # the point is a DIFFERENT replica
        hkw = dict(kw)
        rem = out.remaining_s()
        if rem is not None:
            if rem <= 0.0:
                return None   # the deadline sweep owns this request now
            hkw["deadline_s"] = rem
        try:
            hrep, hinner = self._dispatch(prompt, hkw, hctx,
                                          set(tried) | {rep.name})
        except BaseException:  # noqa: BLE001 — no second seat, no hedge
            return None
        pol.note_fired()
        out.hedged = True
        out._hedge_inner = hinner
        if _tracer.sampled(out.request_id):
            _tracer.instant(
                "router/hedge_fired", cat="serve",
                request_id=out.request_id, primary=rep.name,
                hedge=hrep.name, waited_s=round(waited, 6),
                trigger_s=round(trig, 6))
        log.info("%s: request %s hedged %s -> %s (waited %.3fs, "
                 "trigger %.3fs)", self.name, out.request_id, rep.name,
                 hrep.name, waited, trig)
        # a side stream's inflight/breaker accounting settles when its
        # cancel is honored (next scheduler round on its engine) — a
        # tiny waiter keeps the relay free to forward the winner NOW
        def _settle(side_stream, side_rep):
            def _run():
                with side_stream._cond:
                    side_stream._cond.wait_for(
                        lambda: side_stream._done, timeout=30.0)
                if side_stream._error is not None:
                    self._record_failure(side_rep, side_stream._error)
                else:
                    self._record_success(side_rep)
            threading.Thread(target=_run, daemon=True,
                             name=f"{self.name}-hedge-settle-"
                                  f"{out.request_id}").start()

        # first completion WITHOUT an error wins; a mid-hedge replica
        # kill resolves its stream with an error, which simply forfeits
        # the race to the survivor.  Both dead -> hand the primary back
        # and let the relay's failover path re-dispatch (both names are
        # in ``tried``).
        while True:
            p_done, h_done = inner.done(), hinner.done()
            if p_done and inner._error is None:
                winner, wrep = inner, rep
                loser, lrep, hedge_won = hinner, hrep, False
                break
            if h_done and hinner._error is None:
                winner, wrep = hinner, hrep
                loser, lrep, hedge_won = inner, rep, True
                break
            if p_done and h_done:
                tried.add(hrep.name)
                self._record_failure(hrep, hinner._error)
                pol.note_outcome(False)
                out._hedge_inner = None
                return None
            if out.cancel_requested:
                # client cancelled mid-race: both inners already got
                # the cancel via RoutedLMStream.cancel; let the relay's
                # normal path observe the primary's truncation, and
                # settle the hedge seat when its cancel lands
                pol.note_outcome(False)
                out._hedge_inner = None
                _settle(hinner, hrep)
                return None
            time.sleep(0.002)
        loser.cancel()
        pol.note_outcome(hedge_won)
        out._hedge_inner = None
        if _tracer.sampled(out.request_id):
            _tracer.instant(
                "router/hedge_resolved", cat="serve",
                request_id=out.request_id, winner=wrep.name,
                hedge_won=hedge_won)
        _settle(loser, lrep)
        return wrep, winner

    # -- hibernation (composes with kvtier) ------------------------------- #
    def hibernate(self, stream: RoutedLMStream, *,
                  timeout: Optional[float] = 30.0) -> bool:
        """Swap the stream out on ITS replica (the chain demotes into
        that replica's host tier) and pin the session there — the
        resume fast path needs the tier entry's owner."""
        rep = self._by_name(stream.replica_name)
        if rep is None:
            return False
        ok = rep.engine.hibernate(stream.inner, timeout=timeout)
        if ok:
            self.hibernations += 1
            if stream.session_id is not None:
                self.sessions.mark_hibernated(stream.session_id, rep.name)
        return ok

    def resume(self, stream: RoutedLMStream) -> bool:
        """Wake a hibernated stream.  Fast path: its replica is alive
        and promotes the chain back from its tier.  Degraded path: the
        replica died — its ``_fail_all`` resolved the inner stream, the
        relay already re-prefilled and replayed on a survivor, and this
        just repoints the session (returns True: the stream IS live).
        False only when the stream was never hibernated."""
        rep = self._by_name(stream.replica_name)
        if rep is not None and rep.state != DRAINING:
            try:
                if rep.engine.resume(stream.inner):
                    self.resumes += 1
                    return True
                if stream.re_dispatches == 0:
                    return False
                # not hibernated HERE because the holder died and the
                # relay already moved the stream: degraded path below
            except ServingClosed:
                pass
        self.resume_re_routes += 1
        self.sessions.note_re_route()
        self._c_re_routes.add(1)
        return True

    # -- chaos ------------------------------------------------------------ #
    def kill_replica(self, name: str,
                     error: Optional[BaseException] = None) -> None:
        """Abrupt replica death (chaos hook): the member stops serving
        NOW and every stream it held — seated, queued, or hibernated —
        resolves with a backend-lost error, which is exactly what wakes
        each relay into its re-route+replay path.  The replica never
        returns (DRAINING)."""
        rep = self._by_name(name)
        if rep is None:
            raise KeyError(f"no replica named {name!r}")
        with self._lock:
            rep.state = DRAINING
        self._publish_open_circuits()
        self._publish_replica_count()
        if self.router is not None:
            self.router.unregister(name)
        err = error if error is not None else BackendLostError(
            f"chaos: replica {name} killed")
        eng = rep.engine
        with eng._cv:
            eng._closing = True
            eng._abort = True
            eng._cv.notify_all()
        eng._worker.join(5.0)
        eng._fail_all(err)
        _tracer.instant("router/replica_killed", cat="serve", replica=name)
        log.warning("%s: replica %s killed (chaos)", self.name, name)

    # -- introspection / lifecycle ---------------------------------------- #
    def prefix_cache_stats(self) -> dict:
        """Set-wide radix accounting: the bench's prefix-hit-rate gate
        reads the SUM over members (per-replica hit rates reward
        imbalance; the set-level rate is what routing improves)."""
        lookups = hits = saved = 0
        with self._lock:
            engines = [r.engine for r in self._replicas]
        for eng in engines:
            if eng.radix is None:
                continue
            s = eng.radix.stats()
            lookups += s["lookups"]
            hits += s["hits"]
            saved += s["prefill_tokens_saved"]
        return {"lookups": lookups, "hits": hits,
                "hit_rate": (hits / lookups) if lookups else None,
                "prefill_tokens_saved": saved}

    def warmup(self) -> int:
        with self._lock:
            engines = [r.engine for r in self._replicas
                       if r.state != DRAINING]
        return sum(e.warmup() for e in engines)

    def warmup_prefix(self, suffix_lens=None, prefix_blocks=None) -> int:
        """AOT-compile every member's prefix-suffix prefill executables
        (see :meth:`LMServingEngine.warmup_prefix`) — affinity routing
        exists to hit that path, so a TTFT-sensitive deployment warms
        it on all replicas before traffic."""
        with self._lock:
            engines = [r.engine for r in self._replicas
                       if r.state != DRAINING]
        return sum(e.warmup_prefix(suffix_lens, prefix_blocks)
                   for e in engines)

    def lifecycle_stats(self) -> dict:
        """Set-wide lifecycle accounting: the SUM of every member's
        expired/cancelled/wasted counters (the bench's goodput and
        zero-loss gates read the set, not a replica)."""
        with self._lock:
            engines = [r.engine for r in self._replicas]
        total: dict = {}
        for eng in engines:
            for k, v in eng.lifecycle_stats().items():
                total[k] = total.get(k, 0) + v
        return total

    def stats(self) -> dict:
        with self._lock:
            replicas = {
                r.name: {"state": r.state, "inflight": r.inflight,
                         "dispatched": r.dispatched,
                         "failures": r.failures,
                         "consecutive_failures": r.consecutive_failures}
                for r in self._replicas}
        return {
            "name": self.name,
            "replicas": replicas,
            "router": (self.router.stats()
                       if self.router is not None else None),
            "sessions": self.sessions.stats(),
            "prefix_cache": self.prefix_cache_stats(),
            "hibernations": self.hibernations,
            "resumes": self.resumes,
            "resume_re_routes": self.resume_re_routes,
            "lifecycle": self.lifecycle_stats(),
            "hedge": (self.hedge_policy.stats()
                      if self.hedge_policy is not None else None),
            "metrics": self.metrics.snapshot(),
        }

    def close(self, timeout: Optional[float] = 30.0) -> None:
        self._closed = True
        with self._lock:
            reps = list(self._replicas)
            for r in reps:
                r.state = DRAINING
        for r in reps:
            if self.router is not None:
                self.router.unregister(r.name)
            try:
                r.engine.close(timeout)
            except Exception:
                log.exception("closing replica %s failed", r.name)
        self._publish_open_circuits()

    def __enter__(self) -> "LMReplicaSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
