"""Per-replica radix summaries: the router's view of a remote trie.

The router must answer "which replica already holds this prompt's
prefix?" without touching any replica's RadixCache on the dispatch hot
path — the trie lock belongs to the serving worker, and the multi-host
pool this layer grows into will not even share an address space with
the router.  So each :class:`LMServingEngine` *publishes* a
:class:`RadixSummary`: the set of 64-bit cumulative prefix fingerprints
(:func:`~bigdl_tpu.serving.kvcache.radix.prefix_signatures`) of every
node in its trie, refreshed **incrementally** by the trie's per-node
insert/evict hooks — O(1) set mutation per trie event, one full walk
only at attach time, never on dispatch.

Because the hooks fire synchronously under the trie lock, the summary
can never advertise a chain the trie just evicted: the staleness window
between "router matched replica X" and "X's chain is gone" collapses to
the dispatch itself, and even then the worst case is a plain cold
prefill on X (the engine's own ``radix.match`` at admission is the
authority — the summary only *biases* placement, it never substitutes
for admission matching).
"""
from __future__ import annotations

import threading
from typing import List, Optional


class RadixSummary:
    """Compact prefix-fingerprint set mirroring one replica's trie.

    Wire with :meth:`RadixCache.attach_summary`; query with
    :meth:`match_blocks` against a prompt's cumulative sigs.  All
    methods are thread-safe (router threads query while the serving
    worker mutates).
    """

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self._lock = threading.Lock()
        self._sigs: set = set()
        self.version = 0        # bumps on every mutation (test/obs hook)
        self.inserts = 0
        self.evicts = 0

    # -- trie-side (called under the trie lock; keep O(1)) ------------- #
    def on_insert(self, sig: int) -> None:
        with self._lock:
            self._sigs.add(sig)
            self.version += 1
            self.inserts += 1

    def on_evict(self, sig: int) -> None:
        with self._lock:
            self._sigs.discard(sig)
            self.version += 1
            self.evicts += 1

    # -- router-side ---------------------------------------------------- #
    def match_blocks(self, prompt_sigs: List[int]) -> int:
        """Longest cached prefix, in whole blocks: the largest ``m``
        such that every cumulative sig of blocks ``[0, m)`` is present.
        The trie evicts leaves-first, so presence of ``sig_i`` implies
        its ancestors — the walk stops at the first gap."""
        m = 0
        with self._lock:
            for sig in prompt_sigs:
                if sig not in self._sigs:
                    break
                m += 1
        return m

    def __len__(self) -> int:
        with self._lock:
            return len(self._sigs)

    def stats(self) -> dict:
        with self._lock:
            return {"name": self.name, "sigs": len(self._sigs),
                    "version": self.version, "inserts": self.inserts,
                    "evicts": self.evicts}
