"""RadixRouter: prefix-affinity replica scoring, SGLang-router style.

Least-loaded dispatch is radix-blind: every replica grows its own
RadixCache, so a returning session lands wherever the queue is
shortest and re-prefills tokens another replica already holds in HBM.
The router replaces that with a score over the per-replica
:class:`~bigdl_tpu.serving.router.summary.RadixSummary` sets:

    score(r) = w * matched_blocks(r) / prompt_blocks
             - (1 - w) * inflight(r) / (1 + max_inflight)

``w`` (``affinity_weight``) trades cache affinity against load balance:
1.0 is pure stickiness (a hot replica keeps winning until its queue is
the score penalty), 0.0 degenerates to least-loaded.  When **no**
replica matches at least ``min_match_blocks`` (a cold prompt), the
router declines and the caller's least-loaded fallback runs — the
policy biases placement, it never owns liveness.  Ties (equal score)
break least-loaded by ``(inflight, dispatched)``, exactly the breaker
core's default, so two equally-matched replicas round-robin.

The router is shaped as a :class:`ReplicaSetCore` dispatch policy:
``pick(healthy, ctx)`` with ``ctx["prompt_sigs"]`` — so it plugs into
any replica set without touching breakers, bounded re-dispatch, or
failover.  Every decision lands on the ``serving/router/*`` counters
and (sampled) tracer instants.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from bigdl_tpu.obs import get_registry, get_tracer
from bigdl_tpu.serving.router.summary import RadixSummary

log = logging.getLogger("bigdl_tpu.serving")
_tracer = get_tracer()


class RadixRouter:
    """Score replicas by longest-prefix match vs live load.

    Args:
        affinity_weight: ``w`` above, in [0, 1] (default 0.7 — affinity
            dominates until load skew is severe, matching the bench's
            returning-session regime).
        min_match_blocks: smallest prefix match (whole blocks) that
            counts as affinity; prompts matching less everywhere are
            cold dispatches (least-loaded fallback).
    """

    def __init__(self, *, affinity_weight: float = 0.7,
                 min_match_blocks: int = 1):
        if not 0.0 <= affinity_weight <= 1.0:
            raise ValueError("affinity_weight must be in [0, 1]")
        self.affinity_weight = float(affinity_weight)
        self.min_match_blocks = max(1, int(min_match_blocks))
        self._summaries: Dict[str, RadixSummary] = {}
        reg = get_registry()
        self._affinity_hits = reg.counter("serving/router/affinity_hits")
        self._cold = reg.counter("serving/router/cold_dispatches")
        self.affinity_hits = 0
        self.cold_dispatches = 0

    # -- summary registry ------------------------------------------------ #
    def register(self, name: str, summary: RadixSummary) -> None:
        self._summaries[name] = summary

    def unregister(self, name: str) -> None:
        self._summaries.pop(name, None)

    # -- the dispatch policy (ReplicaSetCore contract) ------------------- #
    def pick(self, healthy: List, ctx: dict) -> Optional[object]:
        """Choose among HEALTHY candidates; None ⇒ caller falls back to
        least-loaded.  Candidates follow the ``_Replica`` protocol
        (``name`` / ``inflight`` / ``dispatched``)."""
        sigs = ctx.get("prompt_sigs")
        if not sigs:
            return None     # un-fingerprinted dispatch: least-loaded
        matches = []
        for r in healthy:
            s = self._summaries.get(r.name)
            m = s.match_blocks(sigs) if s is not None else 0
            matches.append((r, m))
        best_m = max(m for _, m in matches)
        if best_m < self.min_match_blocks:
            self.cold_dispatches += 1
            self._cold.add(1)
            self._instant(ctx, None, 0, len(sigs), cold=True)
            return None
        w = self.affinity_weight
        n = len(sigs)
        max_in = max(r.inflight for r, _ in matches)
        best, best_key = None, None
        for r, m in matches:
            score = w * (m / n) - (1.0 - w) * (r.inflight / (1 + max_in))
            # max score; exact ties fall to the core's least-loaded key
            key = (-score, r.inflight, r.dispatched)
            if best_key is None or key < best_key:
                best, best_key, best_m = r, key, m
        self.affinity_hits += 1
        self._affinity_hits.add(1)
        self._instant(ctx, best, best_m, n, cold=False)
        return best

    __call__ = pick

    def _instant(self, ctx: dict, rep, matched: int, n_blocks: int,
                 *, cold: bool) -> None:
        rid = ctx.get("rid")
        if rid is None or not _tracer.sampled(rid):
            return
        _tracer.instant(
            "router/dispatch", cat="serve", request_id=rid,
            replica=(rep.name if rep is not None else None),
            matched_blocks=matched, prompt_blocks=n_blocks, cold=cold)

    def stats(self) -> dict:
        return {
            "affinity_weight": self.affinity_weight,
            "min_match_blocks": self.min_match_blocks,
            "affinity_hits": self.affinity_hits,
            "cold_dispatches": self.cold_dispatches,
            "summaries": {n: s.stats()
                          for n, s in self._summaries.items()},
        }
