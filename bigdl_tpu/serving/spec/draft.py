"""The drafter half of draft-verify speculation.

``DraftModel`` wraps a cheap ``TransformerLM`` (the target's int8
``quantize()`` clone by default) and runs it k tokens ahead per slot
against its OWN small dense KV arena — (L, S, H, cache_len + 1, D),
one contiguous region per slot, no paging (the drafter's cache is a
scratchpad the verifier never reads, so block sharing buys nothing).
Row ``cache_len`` is a scratch position absorbing idle-slot writes,
the dense-cache analogue of the pool's scratch block.

Device programs follow the engine's exactly-one-executable contract:
one donated AOT decode step (``_decode_step_slots`` over all S slots),
one bucketed prefill per prompt bucket through a ``CompileCache``, one
donated insert per bucket.  Drafting k tokens for however many slots
are speculating costs at most ``max(pending) + k - 1`` batched drafter
steps per round — slots that finished their chains early idle on the
scratch row, never a recompile.

State discipline: the engine emits tokens the DRAFTER hasn't attended
yet (the verify bonus token always, the k-th draft when fully
accepted).  Each slot therefore carries ``pending`` — emitted tokens
not yet fed — and every draft round starts by catching the slot up.
Rollback after a partial acceptance is the same pointer-rewind the
paged arena uses: ``q_next`` rewinds to the last valid position and
stale rows above it are overwritten before they can be attended (the
per-slot position mask in ``_decode_step_slots`` hides them until
then)."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.serving.compile_cache import CompileCache
from bigdl_tpu.serving.spec.verify import draft_pick


def _ranked_alternates(logits_row: np.ndarray, temperature: float, key,
                       picked: int, n: int) -> List[int]:
    """The drafter's ``n`` next-best proposals from ONE logits row —
    the tree verifier's alternate branches, costing zero extra drafter
    steps.  Greedy ranks raw logits; a sampled-replay row ranks the
    chain key's Gumbel-perturbed scores (categorical IS Gumbel-argmax),
    so alternates are that draw's runner-ups.  ``picked`` (the spine
    draft) is excluded — an alternate duplicating the spine would be a
    wasted verify row."""
    z = np.asarray(logits_row, np.float64)
    if temperature > 0.0 and key is not None:
        import jax
        import jax.numpy as jnp
        t = max(temperature, 1e-6)
        g = jax.random.gumbel(jnp.asarray(key), (z.shape[0],))
        z = z / t + np.asarray(g, np.float64)
    order = np.argsort(-z, kind="stable")
    out: List[int] = []
    for tok in order:
        tok = int(tok)
        if tok == int(picked):
            continue
        out.append(tok)
        if len(out) >= n:
            break
    return out


def _ledger_record(tag: str, key: str, compiled) -> None:
    """File a directly-lowered executable's cost/memory row (best
    effort — the ledger must never break a compile path)."""
    try:
        from bigdl_tpu.obs.ledger import get_ledger
        get_ledger().record_compiled(tag, key, compiled)
    except Exception:
        pass


def _insert_slot_dense(k_cache, v_cache, k_new, v_new, slot):
    """Write a prefilled prompt's k/v (L, 1, H, Tb, D) into one slot's
    rows of the dense caches (L, S, H, C+1, D), starting at position 0.
    Bucket-padding rows land above the prompt, masked until decode
    overwrites them — the same stale-row invariant as the arenas."""
    from jax import lax
    k_cache = lax.dynamic_update_slice(
        k_cache, k_new.astype(k_cache.dtype), (0, slot, 0, 0, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, v_new.astype(v_cache.dtype), (0, slot, 0, 0, 0))
    return k_cache, v_cache


class _DraftSlot:
    __slots__ = ("q_next", "pending", "draft_base", "last_k")

    def __init__(self, prompt_len: int):
        self.q_next = prompt_len   # next drafter cache position to write
        self.pending: List[int] = []  # emitted, not yet fed (0-based)
        self.draft_base = prompt_len  # position of draft_1 last round
        self.last_k = 0            # k_eff of the last draft round


class DraftModel:
    """Runs the drafter for every speculating slot of one engine."""

    def __init__(self, model, *, slots: int, cache_len: int,
                 prefill_buckets, max_cache_entries: int = 16,
                 sampling: str = "replay", placement_tag: str = ""):
        import jax
        import jax.numpy as jnp
        from bigdl_tpu.models.transformer.generate import (
            _decode_step_slots, _prefill_parts)
        from bigdl_tpu.quant import (dequantize_entry, params_compute_tag,
                                     params_dtype_tag)

        model._built()
        self.model = model
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        if model.max_len < self.cache_len:
            raise ValueError(
                f"draft model max_len ({model.max_len}) is smaller than "
                f"the engine cache_len ({cache_len}): the drafter must "
                "cover every position the target can reach")
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in prefill_buckets)))
        self.sampling = sampling
        self._params = model.params
        self._buffers = model.buffers
        self.dtype_tag = params_dtype_tag(self._params) or "f32"
        self.compute_mode = params_compute_tag(self._params) or "f32"
        L = model.n_layers
        H, D = model._mha.n_head, model._mha.head_dim
        dt = self._params["embed"].dtype
        # scratch row at index cache_len: idle slots in a batched draft
        # step write there (garbage, masked for every real position)
        self.scratch_pos = self.cache_len
        shape = (L, self.slots, H, self.cache_len + 1, D)
        self.k = jnp.zeros(shape, dt)
        self.v = jnp.zeros(shape, dt)
        self.steps = 0             # drafter decode steps (overhead meter)
        self.decode_compiles = 0   # exactly-one-executable witness

        def _prefill_fn(params, buffers, x):
            del buffers
            return _prefill_parts(model, dequantize_entry(params),
                                  x["ids"], x["len"] - 1)

        self.prefill_cache = CompileCache(
            _prefill_fn, max_entries=max_cache_entries,
            placement_tag=placement_tag, name="draft/prefill")

        def _decode_fn(params, token, pos, kc, vc):
            return _decode_step_slots(model, dequantize_entry(params),
                                      token, pos, kc, vc)

        self._decode_jit = jax.jit(_decode_fn, donate_argnums=(3, 4))
        self._decode_exec = None
        self._insert_jit = jax.jit(_insert_slot_dense,
                                   donate_argnums=(0, 1))
        self._insert_execs: dict = {}
        self._st: List[Optional[_DraftSlot]] = [None] * self.slots

    @property
    def arena_bytes(self) -> int:
        """HBM footprint of the drafter's dense k + v scratch arena."""
        return 2 * self.k.size * self.k.dtype.itemsize

    # -- device programs ------------------------------------------------ #
    def _decode_compiled(self):
        if self._decode_exec is None:
            import jax
            sds = jax.ShapeDtypeStruct
            tok = sds((self.slots,), np.int32)
            pos = sds((self.slots,), np.int32)
            kc = sds(self.k.shape, self.k.dtype)
            self._decode_exec = self._decode_jit.lower(
                self._params, tok, pos, kc, kc).compile()
            self.decode_compiles += 1
            _ledger_record("draft/decode", f"slots={self.slots}",
                           self._decode_exec)
        return self._decode_exec

    def _insert_compiled(self, bucket: int):
        exe = self._insert_execs.get(bucket)
        if exe is None:
            import jax
            sds = jax.ShapeDtypeStruct
            L, S, H, C1, D = self.k.shape
            cache = sds(self.k.shape, self.k.dtype)
            new = sds((L, 1, H, bucket, D), self.k.dtype)
            exe = self._insert_jit.lower(
                cache, cache, new, new,
                sds((), np.int32)).compile()
            self._insert_execs[bucket] = exe
            _ledger_record("draft/insert", f"bucket={bucket}", exe)
        return exe

    def warmup(self) -> int:
        """Compile the drafter's prefill buckets, decode step and
        inserts ahead of traffic; returns newly-compiled prefills."""
        inputs = [{"ids": np.zeros((1, b), np.int32),
                   "len": np.int32(b)} for b in self.prefill_buckets]
        n = self.prefill_cache.warmup_inputs(
            self._params, self._buffers, inputs)
        self._decode_compiled()
        for b in self.prefill_buckets:
            self._insert_compiled(b)
        return n

    # -- per-slot lifecycle --------------------------------------------- #
    def can_draft(self, prompt_len: int) -> bool:
        """Whole-prompt bucketed prefill only: the engine's chunked
        over-length admission path skips speculation rather than grow a
        second chunked prefill plane for the drafter."""
        return prompt_len <= self.prefill_buckets[-1]

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return b
        raise ValueError(f"prompt length {prompt_len} exceeds the "
                         f"largest draft bucket "
                         f"({self.prefill_buckets[-1]})")

    def admit(self, slot: int, prompt0: np.ndarray) -> None:
        """Prefill the drafter for one admitted request.  The drafter
        always prefills the FULL prompt (its dense cache is private, so
        there is no prefix chain to reuse)."""
        t = int(prompt0.shape[0])
        bucket = self.bucket_for(t)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = prompt0
        _, k, v = self.prefill_cache(self._params, self._buffers,
                                     {"ids": ids, "len": np.int32(t)})
        self.k, self.v = self._insert_compiled(bucket)(
            self.k, self.v, k, v, np.int32(slot))
        self._st[slot] = _DraftSlot(t)

    def push(self, slot: int, token0: int) -> None:
        """Queue an emitted token the drafter hasn't attended yet."""
        self._st[slot].pending.append(int(token0))

    def release(self, slot: int) -> None:
        self._st[slot] = None

    def release_all(self) -> None:
        self._st = [None] * self.slots

    # -- the draft round ------------------------------------------------ #
    def draft_round(self, jobs: Dict[int, tuple]) -> Dict[int, tuple]:
        """Draft ``k_eff`` tokens for each job.  ``jobs`` maps slot ->
        (k_eff, temperature, keys) — or (k_eff, temperature, keys,
        alt_counts) in tree mode, where ``alt_counts[i]`` asks for that
        many ranked alternates off draft step i.  ``keys`` is an
        optional (k_eff, 2) uint32 chain-key slice.  Every job first
        catches its slot up on pending emitted tokens, then
        autoregressively drafts; all jobs advance in lockstep through
        ONE donated decode executable, with finished/absent jobs
        writing the scratch row.  Returns slot -> (drafts,
        draft_logit_rows, alternates) — logit rows kept only in
        rejection mode, where acceptance needs q; alternates is one
        ranked token list per draft step (empty unless requested)."""
        if not jobs:
            return {}
        state: Dict[int, dict] = {}
        for s, job in jobs.items():
            k_eff, temp, keys = job[:3]
            alt_counts = tuple(job[3]) if len(job) > 3 else ()
            st = self._st[s]
            feeds = list(st.pending)
            assert feeds, "draft_round on a slot with nothing pending"
            state[s] = {"feeds": feeds, "k": int(k_eff), "temp": temp,
                        "keys": keys, "drafts": [], "rows": [], "fed": 0,
                        "alts": [], "alt_counts": alt_counts,
                        "total": len(feeds) + int(k_eff) - 1}
        n_steps = max(v["total"] for v in state.values())
        keep_rows = self.sampling == "rejection"
        for _ in range(n_steps):
            token = np.zeros((self.slots,), np.int32)
            pos = np.full((self.slots,), self.scratch_pos, np.int32)
            stepped = []
            for s, v in state.items():
                if v["fed"] >= v["total"]:
                    continue
                st = self._st[s]
                nf = len(v["feeds"])
                tok = (v["feeds"][v["fed"]] if v["fed"] < nf
                       else v["drafts"][v["fed"] - nf])
                token[s] = tok
                pos[s] = st.q_next + v["fed"]
                v["fed"] += 1
                stepped.append(s)
            logits, self.k, self.v = self._decode_compiled()(
                self._params, token, pos, self.k, self.v)
            logits = np.asarray(logits)
            self.steps += 1
            for s in stepped:
                v = state[s]
                if v["fed"] >= len(v["feeds"]) and len(v["drafts"]) < v["k"]:
                    i = len(v["drafts"])
                    key = v["keys"][i] if v["keys"] is not None else None
                    v["drafts"].append(draft_pick(
                        logits[s], v["temp"], key, self.sampling))
                    if keep_rows:
                        v["rows"].append(logits[s].copy())
                    na = (v["alt_counts"][i]
                          if i < len(v["alt_counts"]) else 0)
                    v["alts"].append(_ranked_alternates(
                        logits[s], v["temp"], key, v["drafts"][-1], na)
                        if na > 0 else [])
        out = {}
        for s, v in state.items():
            st = self._st[s]
            st.draft_base = st.q_next + len(v["feeds"])
            st.q_next = st.draft_base + v["k"] - 1
            st.last_k = v["k"]
            st.pending = []
            out[s] = (v["drafts"], v["rows"] if keep_rows else None,
                      v["alts"])
        return out

    def commit(self, slot: int, accepted: int, emitted) -> None:
        """Reconcile one slot after verification: rewind ``q_next`` past
        the last VALID drafter write (drafts are only written when fed,
        so at most ``k_eff - 1`` of them are in cache) and queue the
        emitted tokens the drafter hasn't attended — always at least
        the bonus/correction token."""
        st = self._st[slot]
        valid = min(int(accepted), max(st.last_k - 1, 0))
        st.q_next = st.draft_base + valid
        st.pending = [int(t) for t in emitted[valid:]]

    # -- reading -------------------------------------------------------- #
    def describe(self) -> dict:
        return {"dtype_tag": self.dtype_tag,
                "compute_mode": self.compute_mode,
                "hidden": self.model.hidden_size,
                "layers": self.model.n_layers,
                "cache_len": self.cache_len,
                "steps": self.steps,
                "prefill_cache": self.prefill_cache.stats()}


class NgramDrafter:
    """Zero-model prompt-lookup drafter: proposals come from suffix
    n-gram matches against the request's OWN prompt + emitted tokens —
    the free-win regime for summarization / code-edit / RAG shapes
    whose outputs quote their inputs.  Duck-types the ``DraftModel``
    surface the engine drives (admit/push/commit/draft_round/release),
    with no device programs, no arena and no drafter steps: ``steps``
    and ``decode_compiles`` stay 0, which is exactly the point.

    Correctness needs nothing from the heuristic: under replay
    acceptance a proposed token is accepted IFF it equals the offline
    emission, so an unmatched (filler) node simply never accepts — KV
    written for it is garbage above the rewound pointer, same as any
    rejected draft.  Drafting is fully deterministic (pure function of
    the slot's token history), and every ingested token is validated
    against the target vocab so a corrupt client id fails loudly at
    admission instead of as an out-of-range embed gather on device."""

    def __init__(self, vocab_size: int, *, slots: int, ngram_max: int = 3,
                 max_context: int = 4096):
        self.vocab_size = int(vocab_size)
        self.slots = int(slots)
        self.ngram_max = max(1, int(ngram_max))
        # lookup window cap: suffix matching scans the whole context,
        # so bound host work per round on very long streams
        self.max_context = int(max_context)
        self._ctx: List[Optional[List[int]]] = [None] * self.slots
        self.steps = 0             # never advances: zero drafter cost
        self.decode_compiles = 0
        self.compute_mode = "ngram"
        self.dtype_tag = "none"
        self.arena_bytes = 0
        self.sampling = "replay"
        self.lookups = 0
        self.hits = 0

    # -- device-program surface (vacuous) ------------------------------- #
    def warmup(self) -> int:
        return 0

    def can_draft(self, prompt_len: int) -> bool:
        # no prefill buckets: any prompt the engine can admit is usable
        return True

    # -- per-slot lifecycle --------------------------------------------- #
    def _checked(self, toks) -> List[int]:
        out = []
        for t in np.asarray(toks, dtype=np.int64).reshape(-1).tolist():
            if not 0 <= t < self.vocab_size:
                raise ValueError(
                    f"ngram drafter fed token {t} outside the target "
                    f"vocab [0, {self.vocab_size})")
            out.append(int(t))
        return out

    def admit(self, slot: int, prompt0: np.ndarray) -> None:
        self._ctx[slot] = self._checked(prompt0)

    def push(self, slot: int, token0: int) -> None:
        self._ctx[slot].extend(self._checked([token0]))

    def commit(self, slot: int, accepted: int, emitted) -> None:
        # the drafter attends nothing, so "catching up" is just
        # extending the context with every emitted token
        del accepted
        self._ctx[slot].extend(self._checked(emitted))

    def release(self, slot: int) -> None:
        self._ctx[slot] = None

    def release_all(self) -> None:
        self._ctx = [None] * self.slots

    # -- drafting ------------------------------------------------------- #
    def _continuations(self, ctx: List[int], k: int,
                       want: int) -> List[List[int]]:
        """Ranked distinct continuations of the current suffix: longest
        matching n-gram first, most recent occurrence first — the
        prompt-lookup ranking, purely positional and deterministic."""
        out: List[List[int]] = []
        seen = set()
        L = len(ctx)
        for n in range(min(self.ngram_max, L - 1), 0, -1):
            pat = tuple(ctx[L - n:])
            for s in range(L - n - 1, -1, -1):
                if tuple(ctx[s:s + n]) == pat:
                    cont = ctx[s + n:s + n + k]
                    if cont and tuple(cont) not in seen:
                        seen.add(tuple(cont))
                        out.append(cont)
                        if len(out) >= want:
                            return out
        return out

    def draft_round(self, jobs: Dict[int, tuple]) -> Dict[int, tuple]:
        out = {}
        for s, job in jobs.items():
            k_eff = int(job[0])
            alt_counts = tuple(job[3]) if len(job) > 3 else ()
            ctx = self._ctx[s][-self.max_context:]
            self.lookups += 1
            want = 1 + (max(alt_counts) if alt_counts else 0)
            conts = self._continuations(ctx, k_eff, want)
            if conts:
                self.hits += 1
            # spine: best continuation, padded with the last context
            # token (a decent prior for degenerate/looping tails; a
            # wrong filler costs nothing under replay acceptance)
            filler = ctx[-1]
            spine = list(conts[0]) if conts else []
            spine += [filler] * (k_eff - len(spine))
            alts: List[List[int]] = []
            for i in range(k_eff):
                na = alt_counts[i] if i < len(alt_counts) else 0
                ranked: List[int] = []
                for c in conts[1:]:
                    if len(ranked) >= na:
                        break
                    if i < len(c) and c[i] != spine[i] \
                            and c[i] not in ranked:
                        ranked.append(c[i])
                alts.append(ranked)
            out[s] = (spine, None, alts)
        return out

    # -- reading -------------------------------------------------------- #
    def describe(self) -> dict:
        return {"dtype_tag": self.dtype_tag,
                "compute_mode": self.compute_mode,
                "ngram_max": self.ngram_max,
                "steps": self.steps,
                "lookups": self.lookups,
                "hit_rate": (self.hits / self.lookups
                             if self.lookups else 0.0)}
