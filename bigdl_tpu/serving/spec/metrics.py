"""Speculation counters, published as ``serving/lm/spec/*``.

Thread-safe (the engine's decode worker records; stats()/ObsSummary
read).  The two derived rates are the subsystem's health summary:
``acceptance_rate`` (accepted drafts / drafted — how often the drafter
earns its keep) and ``draft_overhead`` (drafter decode steps per
emitted token — the price paid; < 1 means speculation amortizes)."""
from __future__ import annotations

import threading

from bigdl_tpu.obs.registry import FnGauge, Histogram


class SpecMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.drafted = 0          # draft tokens proposed to verify
        self.accepted = 0         # drafts the target agreed with
        self.rolled_back = 0      # drafts rejected (pointer rewinds)
        self.draft_steps = 0      # drafter decode steps executed
        self.verify_rounds = 0    # verify executions (incl. all-plain)
        self.spec_rounds = 0      # verify rounds with >= 1 speculating slot
        self.emitted = 0          # tokens emitted by the spec engine
        self.demotions = 0        # EMA-collapse demotions
        self.fault_demotions = 0  # injected-transient demotions
        self.reprobes = 0         # demoted slots re-probed
        # drafter kernel regime ("dequant"/"int8"/"auto"/"fp8"/"f32")
        # and its worst-layer int32-accumulator overflow-risk gauge
        # (max |q_w| * 127 * K / 2^31 — see quant.transform); the
        # engine stamps both after building the drafter
        self.compute_mode = "f32"
        self.overflow_risk = 0.0
        self.acceptance = Histogram()  # per-(slot, round) acceptance rate
        # tree-verify shape telemetry: what the per-slot adaptive policy
        # actually chose, and what each choice earned
        self.tree_rounds = 0      # (slot, round) pairs verified as a tree
        self.alt_accepts = 0      # accepted ALTERNATE (off-spine) nodes
        self.tree_depth = Histogram()    # chosen shape max_depth per slot
        self.tree_width = Histogram()    # chosen shape width per slot
        self.accepted_per_step = Histogram()  # tokens emitted per
        #                                       (slot, verify round)

    def publish_to(self, registry,
                   prefix: str = "serving/lm/spec/") -> "SpecMetrics":
        for key in ("drafted", "accepted", "rolled_back", "draft_steps",
                    "verify_rounds", "spec_rounds", "emitted", "demotions",
                    "fault_demotions", "reprobes"):
            registry.register(prefix + key,
                              FnGauge(lambda k=key: getattr(self, k)),
                              replace=True)
        registry.register(
            prefix + "accept_rate",
            FnGauge(lambda: self.snapshot()["acceptance_rate"]),
            replace=True)
        registry.register(
            prefix + "draft_overhead",
            FnGauge(lambda: self.snapshot()["draft_overhead"]),
            replace=True)
        registry.register(prefix + "acceptance", self.acceptance,
                          replace=True)
        for key in ("tree_rounds", "alt_accepts"):
            registry.register(prefix + key,
                              FnGauge(lambda k=key: getattr(self, k)),
                              replace=True)
        registry.register(
            prefix + "accepted_per_verify_step",
            FnGauge(lambda: self.snapshot()["accepted_per_verify_step"]),
            replace=True)
        registry.register(prefix + "tree_depth", self.tree_depth,
                          replace=True)
        registry.register(prefix + "tree_width", self.tree_width,
                          replace=True)
        registry.register(prefix + "accepted_per_step",
                          self.accepted_per_step, replace=True)
        registry.register(prefix + "compute_mode",
                          FnGauge(lambda: self.compute_mode), replace=True)
        registry.register(prefix + "overflow_risk",
                          FnGauge(lambda: self.overflow_risk), replace=True)
        return self

    # -- recording ------------------------------------------------------ #
    def record_round(self, drafted: int, accepted: int) -> None:
        """One slot's verify-round outcome: ``drafted`` proposals, the
        leading ``accepted`` of them matched."""
        with self._lock:
            self.drafted += drafted
            self.accepted += accepted
            self.rolled_back += drafted - accepted
            if drafted:
                self.acceptance.observe(accepted / drafted)

    def record_verify_round(self, speculated: bool, emitted: int,
                            draft_steps: int) -> None:
        with self._lock:
            self.verify_rounds += 1
            if speculated:
                self.spec_rounds += 1
            self.emitted += emitted
            self.draft_steps += draft_steps

    def record_tree_slot(self, depth: int, width: int,
                         emitted: int, alt_accepted: int) -> None:
        """One slot's tree-round choice and outcome: the shape it rode
        (max depth / width after budget clamping) and what it earned
        (tokens emitted this round, off-spine nodes accepted)."""
        with self._lock:
            self.tree_rounds += 1
            self.alt_accepts += alt_accepted
            self.tree_depth.observe(depth)
            self.tree_width.observe(width)
            self.accepted_per_step.observe(emitted)

    def record_demotion(self, fault: bool = False) -> None:
        with self._lock:
            self.demotions += 1
            if fault:
                self.fault_demotions += 1

    def record_reprobe(self) -> None:
        with self._lock:
            self.reprobes += 1

    # -- reading -------------------------------------------------------- #
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "drafted": self.drafted,
                "accepted": self.accepted,
                "rolled_back": self.rolled_back,
                "draft_steps": self.draft_steps,
                "verify_rounds": self.verify_rounds,
                "spec_rounds": self.spec_rounds,
                "emitted": self.emitted,
                "demotions": self.demotions,
                "fault_demotions": self.fault_demotions,
                "reprobes": self.reprobes,
                "compute_mode": self.compute_mode,
                "overflow_risk": self.overflow_risk,
                "acceptance_rate":
                    (self.accepted / self.drafted) if self.drafted else None,
                "draft_overhead":
                    (self.draft_steps / self.emitted)
                    if self.emitted else None,
                "accepted_per_verify_step":
                    (self.emitted / self.verify_rounds)
                    if self.verify_rounds else None,
                "tree_rounds": self.tree_rounds,
                "alt_accepts": self.alt_accepts,
                "acceptance": self.acceptance.snapshot(),
                "tree_depth": self.tree_depth.snapshot(),
                "tree_width": self.tree_width.snapshot(),
                "accepted_per_step": self.accepted_per_step.snapshot(),
            }
