"""Draft-verify speculative decoding for the LM slot engine.

A cheap drafter (the target's int8 ``quantize()`` clone by default)
proposes k tokens per slot; ONE fixed-shape donated verify executable
scores all k+1 candidate positions against the paged target cache; the
host accepts the matching prefix by replaying the offline sampling key
chain, so greedy AND sampled speculative streams stay bit-exact vs
offline ``generate()``.  See the module docstrings of
:mod:`.draft`, :mod:`.verify`, :mod:`.metrics`.

Enable with ``LMServingEngine(model, spec=SpecConfig(k=4))``.
"""
from bigdl_tpu.serving.spec.draft import DraftModel
from bigdl_tpu.serving.spec.metrics import SpecMetrics
from bigdl_tpu.serving.spec.verify import (SpecConfig, accept_row,
                                           accept_walk, draft_pick,
                                           pick_token)

__all__ = ["DraftModel", "SpecConfig", "SpecMetrics", "accept_row",
           "accept_walk", "draft_pick", "pick_token"]
