"""Draft-verify speculative decoding for the LM slot engine.

A cheap drafter (the target's int8 ``quantize()`` clone by default)
proposes k tokens per slot; ONE fixed-shape donated verify executable
scores all k+1 candidate positions against the paged target cache; the
host accepts the matching prefix by replaying the offline sampling key
chain, so greedy AND sampled speculative streams stay bit-exact vs
offline ``generate()``.  See the module docstrings of
:mod:`.draft`, :mod:`.verify`, :mod:`.metrics`.

Speculation 2.0 widens the chain to a small candidate TREE
(``SpecConfig(tree=True)``): the drafter's spine plus ranked
runner-up alternates are scored in one pass per pre-lowered
:class:`TreeShape`, per-slot depth/width adapts over the shape ladder
from the acceptance EMA, and a zero-model prompt-lookup
:class:`NgramDrafter` (``drafter_compute="ngram"``) drafts from suffix
matches in the request's own prompt + emitted tokens.

Enable with ``LMServingEngine(model, spec=SpecConfig(k=4))``.
"""
from bigdl_tpu.serving.spec.draft import DraftModel, NgramDrafter
from bigdl_tpu.serving.spec.metrics import SpecMetrics
from bigdl_tpu.serving.spec.verify import (SpecConfig, TreeShape,
                                           accept_row, accept_walk,
                                           default_tree_shapes, draft_pick,
                                           pick_token, tree_accept_walk)

__all__ = ["DraftModel", "NgramDrafter", "SpecConfig", "SpecMetrics",
           "TreeShape", "accept_row", "accept_walk", "default_tree_shapes",
           "draft_pick", "pick_token", "tree_accept_walk"]
