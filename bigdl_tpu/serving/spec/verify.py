"""Host-side acceptance for draft-verify speculative decoding.

The verify executable returns one target-logits row per candidate
position; this module decides, row by row, which token the stream
actually emits.  Two modes:

- ``"replay"`` (default, the bit-exact mode): every row emits the token
  offline ``generate()`` would have picked — argmax for greedy, the
  key-chain ``jax.random.categorical`` draw for sampled — and a draft
  is "accepted" exactly when it equals that token.  The emitted stream
  is therefore ALWAYS the offline trajectory, for greedy AND sampled
  requests; speculation only changes how many of its tokens land per
  device step.  Because jax's categorical is Gumbel-argmax, a drafter
  that samples with the SAME chain keys is Gumbel-coupled to the
  target, which is what makes sampled acceptance rates non-trivial.

- ``"rejection"`` — classical speculative sampling (Leviathan et al.,
  2023): accept draft ``d`` with probability ``min(1, p(d)/q(d))``,
  else emit a draw from the normalized residual ``max(p - q, 0)``.
  The per-token DISTRIBUTION is exactly the target's, but the realized
  trajectory is not the offline key chain's, so this mode is excluded
  from the bit-exact oracle (it is still fully deterministic for a
  fixed seed: all auxiliary draws fold the chain key).

Both modes share one control-flow invariant the engine relies on: the
emitted token equals the draft IFF the draft was accepted (a rejection
residual can never re-draw ``d``, since rejection implies
``p(d) < q(d)`` and the residual mass at ``d`` is then zero), so the
engine can walk rows left to right and stop at the first mismatch.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: fold_in tags deriving the rejection mode's auxiliary streams from the
#: slot's chain key — draft draw, accept coin, residual draw.  Distinct
#: odd constants so the three never alias each other or the chain key.
FOLD_DRAFT = 101
FOLD_ACCEPT = 103
FOLD_RESIDUAL = 107

SAMPLING_MODES = ("replay", "rejection")


class SpecConfig:
    """Speculation knobs for :class:`~bigdl_tpu.serving.LMServingEngine`.

    Args:
        k: draft tokens per verify round (static per engine — the verify
            executable's candidate width is ``k + 1``).
        draft: an optional built ``TransformerLM`` drafter.  Default
            ``None`` derives one from the target: its int8
            ``quantize()`` clone (or the target itself when the target
            is already int8 — then drafting is memory-bandwidth-cheap
            verification of the engine's own stream).
        sampling: ``"replay"`` (bit-exact vs offline generate, the
            default) or ``"rejection"`` (distribution-exact speculative
            sampling).
        ema_alpha: weight of the newest round in the per-slot
            acceptance-rate EMA.
        demote_below: demote a slot to plain decode when its EMA falls
            below this after ``min_rounds`` speculated rounds.
        min_rounds: rounds of evidence before demotion can trigger.
        probe_interval: plain-decode rounds a demoted slot serves before
            speculation is re-probed.
    """

    def __init__(self, k: int = 4, *, draft=None, sampling: str = "replay",
                 drafter_compute: str = "dequant",
                 ema_alpha: float = 0.3, demote_below: float = 0.1,
                 min_rounds: int = 4, probe_interval: int = 8):
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {k}")
        if sampling not in SAMPLING_MODES:
            raise ValueError(f"sampling must be one of {SAMPLING_MODES}, "
                             f"got {sampling!r}")
        if drafter_compute not in ("dequant", "int8", "auto"):
            raise ValueError(
                "drafter_compute must be 'dequant', 'int8' or 'auto', "
                f"got {drafter_compute!r}")
        self.draft = draft
        self.sampling = sampling
        # kernel regime for the DEFAULT drafter (the target's int8
        # clone): "dequant" keeps weight-only dequant-on-the-fly,
        # "int8" feeds int8 activations x int8 weights to the MXU,
        # "auto" follows the measured duel in ops/autotune.py.  Drafter
        # numerics only move acceptance — emitted tokens are the
        # target's under "replay".  Ignored when ``draft`` is given.
        self.drafter_compute = drafter_compute
        self.ema_alpha = float(ema_alpha)
        if not 0.0 < self.ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")
        self.demote_below = float(demote_below)
        self.min_rounds = int(min_rounds)
        if self.min_rounds < 1:
            raise ValueError(f"min_rounds must be >= 1, got {min_rounds}")
        self.probe_interval = int(probe_interval)
        if self.probe_interval < 1:
            raise ValueError(
                f"probe_interval must be >= 1, got {probe_interval}")

    def describe(self) -> dict:
        return {"k": self.k, "sampling": self.sampling,
                "drafter_compute": self.drafter_compute,
                "ema_alpha": self.ema_alpha,
                "demote_below": self.demote_below,
                "min_rounds": self.min_rounds,
                "probe_interval": self.probe_interval}


def pick_token(logits_row: np.ndarray, temperature: float, key,
               clamp: bool) -> int:
    """The offline sampling rule for one logits row: argmax at
    temperature 0 (or without a key), else the key-chain categorical
    over (1, V) — shapes and clamping replicate ``generate()`` exactly,
    which is what makes serving streams bit-exact against it."""
    if temperature <= 0.0 or key is None:
        return int(np.argmax(logits_row))
    import jax
    import jax.numpy as jnp
    denom = max(temperature, 1e-6) if clamp else temperature
    return int(jax.random.categorical(
        jnp.asarray(key), jnp.asarray(logits_row)[None, :] / denom,
        axis=-1)[0])


def _softmax(logits: np.ndarray) -> np.ndarray:
    z = logits.astype(np.float64) - float(np.max(logits))
    e = np.exp(z)
    return e / e.sum()


def draft_pick(logits_row: np.ndarray, temperature: float, key,
               mode: str) -> int:
    """How the DRAFTER chooses its proposal.  Greedy without a key;
    replay mode samples with the slot's OWN chain key (Gumbel-coupling
    the draft to the target's draw); rejection mode draws from q with
    an independent folded key, as the rejection identity requires."""
    if temperature <= 0.0 or key is None:
        return int(np.argmax(logits_row))
    if mode == "rejection":
        import jax
        import jax.numpy as jnp
        t = max(temperature, 1e-6)
        return int(jax.random.categorical(
            jax.random.fold_in(jnp.asarray(key), FOLD_DRAFT),
            jnp.asarray(logits_row)[None, :] / t, axis=-1)[0])
    return pick_token(logits_row, temperature, key, clamp=True)


def accept_row(target_row: np.ndarray, draft_tok: Optional[int],
               temperature: float, key, mode: str,
               draft_row: Optional[np.ndarray] = None) -> int:
    """Emit one token for one verify row.  ``draft_tok`` is None on the
    bonus row (all drafts already accepted).  Returns the emitted
    0-based token; it equals ``draft_tok`` iff the draft is accepted."""
    if (draft_tok is None or mode != "rejection"
            or temperature <= 0.0 or key is None):
        return pick_token(target_row, temperature, key, clamp=True)
    import jax
    import jax.numpy as jnp
    t = max(temperature, 1e-6)
    p = _softmax(np.asarray(target_row) / t)
    q = _softmax(np.asarray(draft_row) / t)
    kj = jnp.asarray(key)
    u = float(jax.random.uniform(jax.random.fold_in(kj, FOLD_ACCEPT)))
    d = int(draft_tok)
    if q[d] > 0.0 and u <= min(1.0, float(p[d] / q[d])):
        return d
    r = np.maximum(p - q, 0.0)
    s = float(r.sum())
    if s <= 0.0:
        # p == q exactly: the residual is empty and acceptance was
        # certain; numerically unreachable here but fall back to p
        return pick_token(target_row, temperature, key, clamp=True)
    logr = np.log(np.where(r > 0.0, r / s, 1e-300))
    return int(jax.random.categorical(
        jax.random.fold_in(kj, FOLD_RESIDUAL),
        jnp.asarray(logr, dtype=np.float32)[None, :], axis=-1)[0])


def accept_walk(target_rows: np.ndarray, drafts: Sequence[int],
                temperature: float, keys, mode: str,
                draft_rows=None) -> tuple:
    """Pure acceptance walk (no engine state): emit rows left to right,
    stopping after the first non-matching emission or the bonus row.
    Returns (emitted 0-based tokens, n_accepted).  Exposed for tests;
    the engine inlines the same walk to interleave EOS/budget checks."""
    emitted: list = []
    accepted = 0
    k_eff = len(drafts)
    for j in range(k_eff + 1):
        key = keys[j] if keys is not None else None
        e = accept_row(target_rows[j], drafts[j] if j < k_eff else None,
                       temperature, key, mode,
                       draft_rows[j] if draft_rows is not None else None)
        emitted.append(e)
        if j >= k_eff or drafts[j] != e:
            break
        accepted += 1
    return emitted, accepted
