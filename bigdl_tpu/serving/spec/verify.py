"""Host-side acceptance for draft-verify speculative decoding.

The verify executable returns one target-logits row per candidate
position; this module decides, row by row, which token the stream
actually emits.  Two modes:

- ``"replay"`` (default, the bit-exact mode): every row emits the token
  offline ``generate()`` would have picked — argmax for greedy, the
  key-chain ``jax.random.categorical`` draw for sampled — and a draft
  is "accepted" exactly when it equals that token.  The emitted stream
  is therefore ALWAYS the offline trajectory, for greedy AND sampled
  requests; speculation only changes how many of its tokens land per
  device step.  Because jax's categorical is Gumbel-argmax, a drafter
  that samples with the SAME chain keys is Gumbel-coupled to the
  target, which is what makes sampled acceptance rates non-trivial.

- ``"rejection"`` — classical speculative sampling (Leviathan et al.,
  2023): accept draft ``d`` with probability ``min(1, p(d)/q(d))``,
  else emit a draw from the normalized residual ``max(p - q, 0)``.
  The per-token DISTRIBUTION is exactly the target's, but the realized
  trajectory is not the offline key chain's, so this mode is excluded
  from the bit-exact oracle (it is still fully deterministic for a
  fixed seed: all auxiliary draws fold the chain key).

Both modes share one control-flow invariant the engine relies on: the
emitted token equals the draft IFF the draft was accepted (a rejection
residual can never re-draw ``d``, since rejection implies
``p(d) < q(d)`` and the residual mass at ``d`` is then zero), so the
engine can walk rows left to right and stop at the first mismatch.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: fold_in tags deriving the rejection mode's auxiliary streams from the
#: slot's chain key — draft draw, accept coin, residual draw.  Distinct
#: odd constants so the three never alias each other or the chain key.
FOLD_DRAFT = 101
FOLD_ACCEPT = 103
FOLD_RESIDUAL = 107

SAMPLING_MODES = ("replay", "rejection")

DRAFTER_COMPUTE_MODES = ("dequant", "int8", "auto", "ngram")


class TreeShape:
    """A fixed-shape candidate tree for the tree verify executable.

    ``parents`` lists one node per verify row: ``parents[0] == -1`` is
    the root (the slot's last emitted token) and every later node names
    an EARLIER node as its parent, so the list is topologically sorted
    and node index doubles as the arena-offset the node's k/v row is
    scattered at.  The leading maximal chain (``parents[j] == j - 1``)
    is the SPINE — the drafter's sequential proposal, identical to the
    linear-k chain — and every off-spine node is an ALTERNATE: a ranked
    runner-up for one spine step.  Alternates must be leaves hanging
    off a spine node below the tip (``parents[j] < spine``): the
    drafter's dense cache tracks only the spine, and an alternate with
    children would need tree-shaped drafter state.

    Everything downstream is precomputed here as static constants the
    verify executable bakes into its trace: per-node depths (the TRUE
    position offset RoPE rotates at), the ancestor-or-self matrix
    ``anc`` (the tree attention mask), per-node children (the host
    walk's descent order), and the per-spine-step alternate counts the
    drafter fills.
    """

    def __init__(self, parents: Sequence[int]):
        parents = tuple(int(p) for p in parents)
        if len(parents) < 2 or parents[0] != -1:
            raise ValueError(
                "a tree shape needs the root (parent -1) plus at least "
                f"one candidate node, got parents={parents}")
        for j, p in enumerate(parents[1:], start=1):
            if not 0 <= p < j:
                raise ValueError(
                    f"node {j} names parent {p}; parents must be earlier "
                    "nodes (topological order)")
        self.parents = parents
        self.width = len(parents)
        depths = [0] * self.width
        anc = np.eye(self.width, dtype=bool)
        children: list = [[] for _ in range(self.width)]
        for j in range(1, self.width):
            p = parents[j]
            depths[j] = depths[p] + 1
            anc[j] |= anc[p]
            children[p].append(j)
        self.depths = tuple(depths)
        self.max_depth = max(depths)
        self.anc = anc
        self.children = tuple(tuple(c) for c in children)
        spine = 0
        while spine + 1 < self.width and parents[spine + 1] == spine:
            spine += 1
        self.spine = spine
        self.is_chain = self.width == spine + 1
        alt_counts = [0] * spine
        alt_rank = {}
        for j in range(spine + 1, self.width):
            p = parents[j]
            if p >= spine:
                raise ValueError(
                    f"alternate node {j} hangs off node {p}, but "
                    f"alternates must branch from a spine step below the "
                    f"tip (parent < {spine}): the drafter only ranks "
                    "runner-ups where it made a sequential pick")
            if self.children[j]:
                raise ValueError(
                    f"alternate node {j} has children {self.children[j]}; "
                    "alternates must be leaves")
            alt_rank[j] = alt_counts[p]
            alt_counts[p] += 1
        self.alt_counts = tuple(alt_counts)
        self.alt_rank = alt_rank

    def describe(self) -> dict:
        return {"parents": list(self.parents), "width": self.width,
                "spine": self.spine, "max_depth": self.max_depth,
                "is_chain": self.is_chain,
                "alt_counts": list(self.alt_counts)}


def default_tree_shapes(k: int, n_alt: Optional[int] = None) -> list:
    """The nested-prefix shape ladder: chain-1, chain-⌈k/2⌉, chain-k,
    and chain-k plus ``n_alt`` first-runner-up alternates on the lowest
    spine steps.  Every rung is a strict PREFIX of the next, so a slot
    at a lower rung can ride a higher-rung executable by truncating its
    ``n_cand`` — one donated verify executable per rung is the whole
    compile budget."""
    k = int(k)
    if n_alt is None:
        n_alt = min(k, 3)
    n_alt = int(n_alt)
    if not 0 <= n_alt <= k:
        raise ValueError(f"tree_alts must be in [0, k], got {n_alt}")
    master = [-1] + list(range(k)) + list(range(n_alt))
    widths = sorted({2, (k + 1) // 2 + 1, k + 1, k + 1 + n_alt})
    return [TreeShape(master[:w]) for w in widths if 2 <= w <= len(master)]


def _validate_ladder(shapes: Sequence[TreeShape]) -> None:
    if not shapes:
        raise ValueError("tree mode needs at least one tree shape")
    for lo, hi in zip(shapes, shapes[1:]):
        if lo.width >= hi.width:
            raise ValueError(
                "tree shapes must be sorted by strictly increasing width, "
                f"got {lo.width} then {hi.width}")
        if hi.parents[:lo.width] != lo.parents:
            raise ValueError(
                f"shape ladder must be nested prefixes (so one round can "
                f"serve mixed rungs under the widest executable); "
                f"{list(lo.parents)} is not a prefix of {list(hi.parents)}")


class SpecConfig:
    """Speculation knobs for :class:`~bigdl_tpu.serving.LMServingEngine`.

    Args:
        k: draft tokens per verify round (static per engine — the verify
            executable's candidate width is ``k + 1``).
        draft: an optional built ``TransformerLM`` drafter.  Default
            ``None`` derives one from the target: its int8
            ``quantize()`` clone (or the target itself when the target
            is already int8 — then drafting is memory-bandwidth-cheap
            verification of the engine's own stream).
        sampling: ``"replay"`` (bit-exact vs offline generate, the
            default) or ``"rejection"`` (distribution-exact speculative
            sampling).
        ema_alpha: weight of the newest round in the per-slot
            acceptance-rate EMA.
        demote_below: demote a slot to plain decode when its EMA falls
            below this after ``min_rounds`` speculated rounds.
        min_rounds: rounds of evidence before demotion can trigger.
        probe_interval: plain-decode rounds a demoted slot serves before
            speculation is re-probed.
        tree: verify a candidate TREE instead of the linear chain.  The
            spine budget stays ``k``; alternates ride the same verify
            pass for free and per-slot depth/width adapts over the
            shape ladder from the acceptance EMA.  Replay-only
            (rejection acceptance needs a drafter q row per node and
            alternates have none).
        tree_alts: alternates in the widest default ladder rung
            (default ``min(k, 3)``).  Ignored when ``tree_shapes`` is
            given.
        tree_shapes: explicit shape ladder — a list of parent-pointer
            lists, nested prefixes sorted by width (see
            :class:`TreeShape` / :func:`default_tree_shapes`).
        promote_above: move a slot one rung UP (deeper/wider tree) when
            its acceptance EMA reaches this.
        stepdown_below: move a slot one rung DOWN when its EMA falls
            below this (full demotion to plain decode still uses
            ``demote_below``/``min_rounds``).
        init_rung: ladder rung new slots start at (default: the deepest
            chain rung, i.e. linear-k behavior until the EMA says
            otherwise).
        ngram_max: longest suffix n-gram the ``"ngram"`` drafter
            matches against the request's own prompt + emitted tokens.
    """

    def __init__(self, k: int = 4, *, draft=None, sampling: str = "replay",
                 drafter_compute: str = "dequant",
                 ema_alpha: float = 0.3, demote_below: float = 0.1,
                 min_rounds: int = 4, probe_interval: int = 8,
                 tree: bool = False, tree_alts: Optional[int] = None,
                 tree_shapes: Optional[Sequence[Sequence[int]]] = None,
                 promote_above: float = 0.75, stepdown_below: float = 0.35,
                 init_rung: Optional[int] = None, ngram_max: int = 3):
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {k}")
        if sampling not in SAMPLING_MODES:
            raise ValueError(f"sampling must be one of {SAMPLING_MODES}, "
                             f"got {sampling!r}")
        if drafter_compute not in DRAFTER_COMPUTE_MODES:
            raise ValueError(
                f"drafter_compute must be one of {DRAFTER_COMPUTE_MODES}, "
                f"got {drafter_compute!r}")
        if drafter_compute == "ngram":
            if draft is not None:
                raise ValueError(
                    "drafter_compute='ngram' is the zero-model drafter; "
                    "passing an explicit draft model contradicts it")
            if sampling == "rejection":
                raise ValueError(
                    "the n-gram drafter has no q distribution, so "
                    "rejection sampling cannot form p/q acceptance "
                    "ratios; use sampling='replay'")
        self.draft = draft
        self.sampling = sampling
        # kernel regime for the DEFAULT drafter (the target's int8
        # clone): "dequant" keeps weight-only dequant-on-the-fly,
        # "int8" feeds int8 activations x int8 weights to the MXU,
        # "auto" follows the measured duel in ops/autotune.py.  Drafter
        # numerics only move acceptance — emitted tokens are the
        # target's under "replay".  Ignored when ``draft`` is given.
        self.drafter_compute = drafter_compute
        self.ema_alpha = float(ema_alpha)
        if not 0.0 < self.ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")
        self.demote_below = float(demote_below)
        self.min_rounds = int(min_rounds)
        if self.min_rounds < 1:
            raise ValueError(f"min_rounds must be >= 1, got {min_rounds}")
        self.probe_interval = int(probe_interval)
        if self.probe_interval < 1:
            raise ValueError(
                f"probe_interval must be >= 1, got {probe_interval}")
        self.tree = bool(tree)
        if tree_shapes is not None and not self.tree:
            raise ValueError("tree_shapes requires tree=True")
        self.promote_above = float(promote_above)
        self.stepdown_below = float(stepdown_below)
        self.ngram_max = int(ngram_max)
        if self.ngram_max < 1:
            raise ValueError(f"ngram_max must be >= 1, got {ngram_max}")
        self.shapes: Optional[list] = None
        self.init_rung: Optional[int] = None
        if self.tree:
            if sampling == "rejection":
                raise ValueError(
                    "tree verify is replay-only: rejection acceptance "
                    "needs a drafter q row per node, and alternates are "
                    "ranked runner-ups without one")
            if not 0.0 < self.stepdown_below <= self.promote_above <= 1.0:
                raise ValueError(
                    "need 0 < stepdown_below <= promote_above <= 1, got "
                    f"{stepdown_below} / {promote_above}")
            if tree_shapes is not None:
                shapes = [TreeShape(p) for p in tree_shapes]
            else:
                shapes = default_tree_shapes(self.k, tree_alts)
            _validate_ladder(shapes)
            deepest = max(s.spine for s in shapes)
            if deepest > self.k:
                raise ValueError(
                    f"shape ladder spines go {deepest} deep but the "
                    f"drafter budget is k={self.k}")
            self.shapes = shapes
            if init_rung is None:
                chain_rungs = [i for i, s in enumerate(shapes) if s.is_chain]
                init_rung = chain_rungs[-1] if chain_rungs else 0
            self.init_rung = int(init_rung)
            if not 0 <= self.init_rung < len(shapes):
                raise ValueError(
                    f"init_rung {init_rung} outside the ladder "
                    f"[0, {len(shapes)})")

    def describe(self) -> dict:
        d = {"k": self.k, "sampling": self.sampling,
             "drafter_compute": self.drafter_compute,
             "ema_alpha": self.ema_alpha,
             "demote_below": self.demote_below,
             "min_rounds": self.min_rounds,
             "probe_interval": self.probe_interval,
             "tree": self.tree}
        if self.tree:
            d["tree_shapes"] = [list(s.parents) for s in self.shapes]
            d["tree_widths"] = [s.width for s in self.shapes]
            d["promote_above"] = self.promote_above
            d["stepdown_below"] = self.stepdown_below
            d["init_rung"] = self.init_rung
        if self.drafter_compute == "ngram":
            d["ngram_max"] = self.ngram_max
        return d


def pick_token(logits_row: np.ndarray, temperature: float, key,
               clamp: bool) -> int:
    """The offline sampling rule for one logits row: argmax at
    temperature 0 (or without a key), else the key-chain categorical
    over (1, V) — shapes and clamping replicate ``generate()`` exactly,
    which is what makes serving streams bit-exact against it."""
    if temperature <= 0.0 or key is None:
        return int(np.argmax(logits_row))
    import jax
    import jax.numpy as jnp
    denom = max(temperature, 1e-6) if clamp else temperature
    return int(jax.random.categorical(
        jnp.asarray(key), jnp.asarray(logits_row)[None, :] / denom,
        axis=-1)[0])


def _softmax(logits: np.ndarray) -> np.ndarray:
    z = logits.astype(np.float64) - float(np.max(logits))
    e = np.exp(z)
    return e / e.sum()


def draft_pick(logits_row: np.ndarray, temperature: float, key,
               mode: str) -> int:
    """How the DRAFTER chooses its proposal.  Greedy without a key;
    replay mode samples with the slot's OWN chain key (Gumbel-coupling
    the draft to the target's draw); rejection mode draws from q with
    an independent folded key, as the rejection identity requires."""
    if temperature <= 0.0 or key is None:
        return int(np.argmax(logits_row))
    if mode == "rejection":
        import jax
        import jax.numpy as jnp
        t = max(temperature, 1e-6)
        return int(jax.random.categorical(
            jax.random.fold_in(jnp.asarray(key), FOLD_DRAFT),
            jnp.asarray(logits_row)[None, :] / t, axis=-1)[0])
    return pick_token(logits_row, temperature, key, clamp=True)


def accept_row(target_row: np.ndarray, draft_tok: Optional[int],
               temperature: float, key, mode: str,
               draft_row: Optional[np.ndarray] = None) -> int:
    """Emit one token for one verify row.  ``draft_tok`` is None on the
    bonus row (all drafts already accepted).  Returns the emitted
    0-based token; it equals ``draft_tok`` iff the draft is accepted."""
    if (draft_tok is None or mode != "rejection"
            or temperature <= 0.0 or key is None):
        return pick_token(target_row, temperature, key, clamp=True)
    import jax
    import jax.numpy as jnp
    t = max(temperature, 1e-6)
    p = _softmax(np.asarray(target_row) / t)
    q = _softmax(np.asarray(draft_row) / t)
    kj = jnp.asarray(key)
    u = float(jax.random.uniform(jax.random.fold_in(kj, FOLD_ACCEPT)))
    d = int(draft_tok)
    if q[d] > 0.0 and u <= min(1.0, float(p[d] / q[d])):
        return d
    r = np.maximum(p - q, 0.0)
    s = float(r.sum())
    if s <= 0.0:
        # p == q exactly: the residual is empty and acceptance was
        # certain; numerically unreachable here but fall back to p
        return pick_token(target_row, temperature, key, clamp=True)
    logr = np.log(np.where(r > 0.0, r / s, 1e-300))
    return int(jax.random.categorical(
        jax.random.fold_in(kj, FOLD_RESIDUAL),
        jnp.asarray(logr, dtype=np.float32)[None, :], axis=-1)[0])


def accept_walk(target_rows: np.ndarray, drafts: Sequence[int],
                temperature: float, keys, mode: str,
                draft_rows=None) -> tuple:
    """Pure acceptance walk (no engine state): emit rows left to right,
    stopping after the first non-matching emission or the bonus row.
    Returns (emitted 0-based tokens, n_accepted).  Exposed for tests;
    the engine inlines the same walk to interleave EOS/budget checks."""
    emitted: list = []
    accepted = 0
    k_eff = len(drafts)
    for j in range(k_eff + 1):
        key = keys[j] if keys is not None else None
        e = accept_row(target_rows[j], drafts[j] if j < k_eff else None,
                       temperature, key, mode,
                       draft_rows[j] if draft_rows is not None else None)
        emitted.append(e)
        if j >= k_eff or drafts[j] != e:
            break
        accepted += 1
    return emitted, accepted


def tree_accept_walk(shape: TreeShape, tokens: Sequence[int],
                     target_rows: np.ndarray, temperature: float, keys,
                     n_cand: Optional[int] = None) -> tuple:
    """Pure tree acceptance walk (replay mode): descend from the root,
    emitting the offline ``pick_token`` draw at each accepted node and
    following the child that carries it.  ``tokens[j]`` is the candidate
    token at node ``j`` (``tokens[0]`` the last emitted), ``target_rows``
    its scored logits row, and ``n_cand`` truncates the shape when the
    slot rode a wider executable at a lower rung.  Duplicate-token
    siblings are numerically identical rows (same token, position and
    ancestors), so first-match descent is well-defined.

    Returns ``(emitted, path)`` — the 0-based emitted tokens and the
    accepted node indices (root included), with
    ``len(emitted) == len(path)`` and ``accepted == len(path) - 1``.
    Exposed for tests; the engine inlines the same walk to interleave
    EOS/budget checks, metrics and the drafter commit."""
    w = shape.width if n_cand is None else int(n_cand)
    node = 0
    path = [0]
    emitted: list = []
    while True:
        key = keys[len(emitted)] if keys is not None else None
        e = pick_token(np.asarray(target_rows[node]), temperature, key,
                       clamp=True)
        emitted.append(e)
        nxt = None
        for c in shape.children[node]:
            if c < w and int(tokens[c]) == e:
                nxt = c
                break
        if nxt is None:
            return emitted, path
        node = nxt
        path.append(nxt)
