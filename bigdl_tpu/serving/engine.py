"""ServingEngine: a built ``nn.Module`` as a servable endpoint.

The inference analog of the training-side DistriOptimizer: a frozen
params/buffers pytree shared by every request (BigDL's serving model,
arXiv 1804.05839 — batched forward passes over a shared immutable
model), with

- ``apply`` always under ``jit`` with ``training=False`` (no buffer
  writes, no dropout), ahead-of-time compiled per shape bucket through
  the explicit :class:`~bigdl_tpu.serving.compile_cache.CompileCache`;
- a :class:`~bigdl_tpu.serving.batcher.DynamicBatcher` gathering
  requests into bucket-padded batches (sync ``predict`` rides the same
  queue as async ``submit`` — one dispatch path, one ordering);
- chunked host->device staging (``host_transfer.HostStager``) so a big
  batch never pushes an oversized single buffer through the TPU tunnel;
- ``metrics.ServingMetrics`` splitting latency into queue wait vs
  device time, exportable through the visualization tfevents writers.

The served model's output may be a single array or any pytree of
arrays (multi-headed models, Tables); every leaf must carry the batch
dim first — the batcher slices requests back out leaf-wise.
"""
from __future__ import annotations

from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.obs import (env_watchdog_enabled, env_watchdog_kwargs,
                           get_registry, get_tracer, shared_watchdog)
from bigdl_tpu.serving.batcher import DynamicBatcher, power_of_two_buckets
from bigdl_tpu.serving.compile_cache import CompileCache
from bigdl_tpu.serving.host_transfer import HostStager
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.utils.engine import Engine, select_platform
from bigdl_tpu.utils.transfer import DEFAULT_CHUNK_BYTES

_tracer = get_tracer()


class ServingEngine:
    """Serve a built module.

    Args:
        module: a built ``nn.Module`` (``build()`` already called —
            the engine freezes the params/buffers it finds).
        input_shape: per-example input shape (no batch dim); needed by
            ``warmup`` before the first request arrives, else inferred
            from traffic.
        buckets: batch-dim shape buckets; default powers of two up to
            ``max_batch_size``.
        max_batch_size: device batch ceiling; default ``max(buckets)``
            or 32.
        max_wait_ms: how long a partial batch waits for company.
        max_queue: bounded queue depth (backpressure beyond it).
        dtype: wire/device input dtype (default float32).
        platform: optional jax platform pin (see
            ``utils.engine.select_platform``).
        donate_x: donate the input buffer to the compiled executable.
        use_shared_pool: run the batching worker on the shared Engine
            host pool instead of a private thread.
        name: label for traces, metrics, and fault-injection filters
            (``resilience.ReplicaSet`` names its members r0..rN-1).
        with_batcher: when False the engine is built WITHOUT its own
            DynamicBatcher — submit/predict are disabled and batches
            arrive through ``_run_batch`` from an external dispatcher
            (the ReplicaSet mode: one queue fronting N engines).
        placement: optional
            :class:`~bigdl_tpu.serving.placement.MeshSlice` — the
            engine's device slot.  Params land sharded across the
            slot's devices (tensor-parallel over its ``model`` axis),
            staged inputs land replicated on the slot, and compiled
            entries are keyed by the slot tag.  None keeps the classic
            single-device behavior bit-for-bit.
        tp_rules: optional ``rules(path, leaf) -> NamedSharding|None``
            overriding the derived
            :func:`~bigdl_tpu.serving.placement.serving_tp_rules` for
            custom module trees.
    """

    def __init__(self, module, *,
                 input_shape: Optional[tuple] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch_size: Optional[int] = None,
                 max_wait_ms: float = 5.0,
                 max_queue: int = 256,
                 dtype="float32",
                 platform: Optional[str] = None,
                 donate_x: bool = False,
                 max_cache_entries: int = 16,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 use_shared_pool: bool = True,
                 name: str = "engine",
                 with_batcher: bool = True,
                 placement=None,
                 tp_rules=None):
        select_platform(platform)
        import jax
        import jax.numpy as jnp

        module._built()
        self.module = module
        self.name = name
        self.placement = placement
        # freeze: the engine holds its own references; later training
        # steps rebind module.params and never touch these
        self._params = module.params
        self._buffers = module.buffers
        self._dtype = jnp.dtype(dtype)
        self.input_shape = tuple(input_shape) if input_shape else None

        # quantized replica (module.quantize()): re-stage the int8
        # payload through the shared 32 MB chunked-transfer discipline
        # (~4x fewer bytes through the tunneled relay than f32) and
        # publish the wire win as quant/* gauges
        from bigdl_tpu.quant import (params_dtype_tag, params_nbytes,
                                     stage_quantized_params)
        self.quant_dtype = params_dtype_tag(self._params)
        self._quant_bytes_staged = 0
        if placement is not None:
            # one chunked pass straight to the sharded layout — staging
            # dense-on-one-device first and resharding would push the
            # payload through the tunnel twice
            from bigdl_tpu.serving.placement import (serving_tp_rules,
                                                     shard_params_chunked)
            if tp_rules is None and placement.tp > 1:
                tp_rules = serving_tp_rules(module, placement.mesh)
            rules = tp_rules if tp_rules is not None else (lambda p, l: None)
            self._params = shard_params_chunked(
                self._params, rules, placement.mesh, chunk_bytes=chunk_bytes)
            rep = placement.replicated()
            self._buffers = jax.tree_util.tree_map(
                lambda b: jax.device_put(b, rep), self._buffers)
            if self.quant_dtype == "int8":
                self._quant_bytes_staged = params_nbytes(self._params)
                get_registry().gauge("quant/serving_bytes_staged", unit="B") \
                    .set(self._quant_bytes_staged)
        elif self.quant_dtype == "int8":
            self._params, self._quant_bytes_staged = stage_quantized_params(
                self._params, chunk_bytes=chunk_bytes)
            get_registry().gauge("quant/serving_bytes_staged", unit="B") \
                .set(self._quant_bytes_staged)

        if max_batch_size is None:
            max_batch_size = max(buckets) if buckets else 32
        if buckets is None:
            buckets = power_of_two_buckets(max_batch_size)
        if max(buckets) < max_batch_size:
            raise ValueError(
                f"largest bucket {max(buckets)} < max_batch_size "
                f"{max_batch_size}: every dispatch must fit a bucket")
        # kept on the engine (not just the batcher): batcher-less
        # replica members still need them for warmup, and an external
        # dispatcher (ReplicaSet) reads them to configure its own queue
        self.max_batch_size = int(max_batch_size)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))

        _rng = jax.random.PRNGKey(0)  # inert: training=False paths
        _module = module

        _out_sharding = (placement.replicated()
                         if placement is not None and placement.tp > 1
                         else None)

        def _infer(params, buffers, x):
            # inside the trace: expand non-native QTensors (identity
            # for f32 replicas); native ones dequant in their kernels
            from bigdl_tpu.quant import dequantize_entry
            y, _ = _module.apply(dequantize_entry(params), x,
                                 buffers=buffers,
                                 training=False, rng=_rng)
            if _out_sharding is not None:
                # a col-parallel tail would leave the output sharded on
                # its last dim; pin it replicated so the host pull is
                # one clean gather instead of per-shard fetches
                y = jax.lax.with_sharding_constraint(y, _out_sharding)
            return y

        self.cache = CompileCache(
            _infer, max_entries=max_cache_entries, donate_x=donate_x,
            placement_tag=placement.tag if placement is not None else "",
            name=f"serve/{name}/infer")
        self.stager = HostStager(
            self._dtype, chunk_bytes=chunk_bytes,
            device=placement.input_sharding() if placement is not None
            else None)
        # live metrics, published into the process-wide obs registry
        # (latest engine owns the serving/* names)
        self.metrics = ServingMetrics().publish_to(get_registry())
        # memory-ledger attribution: staged params per placement slot
        # plus the stager's cumulative transfer traffic
        self._ledger_keys = []
        try:
            import weakref as _weakref

            from bigdl_tpu.obs.ledger import get_ledger
            from bigdl_tpu.quant import params_nbytes as _pnb
            led = get_ledger()
            _dev = placement.tag if placement is not None else None
            self._ledger_keys.append(led.register(
                "params", f"{name}/staged", _pnb(self._params),
                device=_dev, note=f"quant={self.quant_dtype}"))
            _stager_ref = _weakref.ref(self.stager)

            def _staged_bytes():
                st = _stager_ref()
                return st.bytes_staged if st is not None else None

            self._ledger_keys.append(led.register(
                "host_stager", f"{name}/bytes_staged", _staged_bytes,
                device=_dev, note="cumulative h2d traffic"))
        except Exception:
            pass
        # dispatch-cadence stall detection: a device call that hangs
        # (the tunneled-backend wedge) fires diagnose_tpu + stack dumps
        # into the trace instead of silently stalling every client
        self.watchdog = (shared_watchdog("serve_dispatch")
                         .reset(**env_watchdog_kwargs())
                         if env_watchdog_enabled() else None)
        self.batcher = None
        if with_batcher:
            self.batcher = DynamicBatcher(
                self._run_batch,
                max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms,
                max_queue=max_queue,
                buckets=buckets,
                metrics=self.metrics,
                pool=Engine.default_or_create() if use_shared_pool else None)
        self._closed = False

    # ------------------------------------------------------------------ #
    def _run_batch(self, x_padded: np.ndarray):
        """Batcher callback: stage, run the bucket executable, sync."""
        if self.watchdog is not None:
            self.watchdog.step_started()
        try:
            # resilience hook: replica death / latency spikes inject
            # here (filtered by this engine's name), before any device
            # work — exactly where a dead tunnel would first surface
            from bigdl_tpu.resilience.faults import fault_point
            fault_point("serving.dispatch", name=self.name,
                        rows=int(x_padded.shape[0]))
            misses0 = (self.cache.stats()["misses"] if _tracer.enabled
                       else 0)
            with _tracer.span("serve/h2d", cat="serve",
                              rows=int(x_padded.shape[0])):
                xd = self.stager.stage(x_padded)
            y = self.cache(self._params, self._buffers, xd)
            if _tracer.enabled:
                miss = self.cache.stats()["misses"] > misses0
                _tracer.instant(
                    "serve/cache_miss" if miss else "serve/cache_hit",
                    cat="serve", bucket=int(x_padded.shape[0]))
            # single array or pytree of arrays — every leaf must carry
            # the batch dim first or the batcher's slice-back would
            # silently hand requests the wrong rows
            import jax
            rows = int(x_padded.shape[0])
            leaves = jax.tree_util.tree_leaves(y)
            if not leaves:
                raise TypeError("model output has no array leaves")
            for leaf in leaves:
                if not hasattr(leaf, "shape") or leaf.ndim < 1 \
                        or int(leaf.shape[0]) != rows:
                    raise TypeError(
                        f"every output leaf needs a leading batch dim of "
                        f"{rows}; got {getattr(leaf, 'shape', type(leaf))}")
            # host pull doubles as the device sync
            return jax.tree_util.tree_map(np.asarray, y)
        finally:
            if self.watchdog is not None:
                self.watchdog.step_finished()

    def _coerce(self, x, batched: bool) -> np.ndarray:
        x = np.asarray(x, self._dtype)
        if not batched:
            x = x[None]
        if self.input_shape is None and x.ndim >= 1:
            self.input_shape = tuple(x.shape[1:])
        return x

    # ------------------------------------------------------------------ #
    def warmup(self, input_shape: Optional[tuple] = None) -> int:
        """Pre-compile one executable per configured bucket so the
        first real request pays no XLA compile; returns how many were
        compiled.  After a full warmup a bucketed workload's cache
        hit rate is 1.0."""
        shape = tuple(input_shape) if input_shape else self.input_shape
        if shape is None:
            raise ValueError("warmup needs input_shape (none configured "
                             "and no request seen yet)")
        self.input_shape = shape
        shapes = [(b,) + shape for b in self.buckets]
        if self.placement is not None:
            # AOT executables bake in committed-input shardings: warmup
            # inputs must arrive exactly like traffic does — through the
            # stager onto the slot — or the compiled entries would
            # expect default-device inputs and recompile on first hit
            inputs = [self.stager.stage(np.zeros(s, self._dtype))
                      for s in shapes]
            return self.cache.warmup_inputs(self._params, self._buffers,
                                            inputs)
        return self.cache.warmup(self._params, self._buffers, shapes,
                                 self._dtype)

    def submit(self, x, *, batched: bool = True) -> Future:
        """Async: enqueue a request (a batch by default), get a Future
        of the output batch.  Raises ServingQueueFull on backpressure."""
        if self._closed:
            from bigdl_tpu.serving.batcher import ServingClosed
            raise ServingClosed("engine is closed")
        if self.batcher is None:
            raise RuntimeError(
                "this engine has no batcher (with_batcher=False): it is "
                "a ReplicaSet member — submit through the ReplicaSet")
        return self.batcher.submit(self._coerce(x, batched))

    def predict(self, x, *, timeout: Optional[float] = None) -> np.ndarray:
        """Sync: serve one batch through the same queue as submit()."""
        return self.submit(x).result(timeout=timeout)

    def predict_one(self, x, *,
                    timeout: Optional[float] = None) -> np.ndarray:
        """Sync single example: adds and strips the batch dim."""
        fut = self.submit(self._coerce(x, batched=False), batched=True)
        y = fut.result(timeout=timeout)
        if hasattr(y, "shape"):
            return y[0]
        import jax
        return jax.tree_util.tree_map(lambda a: a[0], y)

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        out = {
            "name": self.name,
            "pending": self.batcher.pending() if self.batcher else 0,
            "buckets": list(self.buckets),
            "quant_dtype": self.quant_dtype,
            "quant_bytes_staged": self._quant_bytes_staged,
            "placement": (self.placement.describe()
                          if self.placement is not None else None),
            "compile_cache": self.cache.stats(),
            "host_transfer": self.stager.stats(),
            "metrics": self.metrics.snapshot(self.cache.stats()),
        }
        if self.watchdog is not None:
            out["watchdog"] = {"stalls": self.watchdog.stall_count,
                               "median_dispatch_s": self.watchdog.median()}
        return out

    def export_metrics(self, summary, step: int) -> None:
        """Write the current snapshot through a visualization Summary."""
        self.metrics.export_to_summary(summary, step, self.cache.stats())

    def close(self, timeout: Optional[float] = 30.0) -> None:
        self._closed = True
        if self.batcher is not None:
            self.batcher.close(timeout=timeout)
        try:
            from bigdl_tpu.obs.ledger import get_ledger
            led = get_ledger()
            for sub, nm in getattr(self, "_ledger_keys", []):
                led.release(sub, nm)
        except Exception:
            pass

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
