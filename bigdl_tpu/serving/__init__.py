"""bigdl_tpu.serving — dynamic-batching inference with a shape-bucketed
compile cache.

Turns any built ``nn.Module`` into a servable endpoint: requests are
gathered by a bounded dynamic batcher, padded to power-of-two shape
buckets (so the XLA compile cache stays small and warm), staged to the
device in <=32 MB chunks, and executed through ahead-of-time compiled
inference executables with hit/miss/evict accounting.  See
``serving/engine.py`` for the full design notes.

Quickstart::

    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.serving import ServingEngine

    model = LeNet5(class_num=10).build(seed=0)
    with ServingEngine(model, input_shape=(784,), max_batch_size=32) as eng:
        eng.warmup()                      # pre-trace every bucket
        scores = eng.predict(batch)       # sync, dynamic-batched
        fut = eng.submit(another_batch)   # async
        print(eng.stats()["compile_cache"]["hit_rate"])
"""
from bigdl_tpu.resilience.errors import ServingDeadlineExceeded
from bigdl_tpu.resilience.replicaset import HedgePolicy
from bigdl_tpu.serving.batcher import (DynamicBatcher, ServingClosed,
                                       ServingOverloaded, ServingQueueFull,
                                       power_of_two_buckets)
from bigdl_tpu.serving.compile_cache import CompileCache
from bigdl_tpu.serving.disagg import DisaggCoordinator
from bigdl_tpu.serving.engine import ServingEngine
from bigdl_tpu.serving.host_transfer import HostStager
from bigdl_tpu.serving.kvcache import (BlockPool, PoolExhausted, RadixCache,
                                       RequestExceedsPool)
from bigdl_tpu.serving.kvtier import HostBlockStore
from bigdl_tpu.serving.lm_engine import (KVHandoff, LMMetrics,
                                         LMServingEngine, LMStream,
                                         StreamTruncation,
                                         prefill_bucket_lengths)
from bigdl_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from bigdl_tpu.serving.router import (LMReplicaSet, RadixRouter,
                                      RadixSummary, RoutedLMStream,
                                      SessionTable)
from bigdl_tpu.serving.placement import (DeviceTopology, MeshSlice,
                                         MeshSlicer, PlacementError,
                                         PlacementPolicy, serving_tp_rules,
                                         shard_params_chunked)
from bigdl_tpu.serving.spec import DraftModel, SpecConfig, SpecMetrics

__all__ = [
    "ServingEngine", "DynamicBatcher", "CompileCache", "HostStager",
    "ServingMetrics", "LatencyHistogram", "ServingQueueFull",
    "ServingOverloaded", "ServingClosed", "ServingDeadlineExceeded",
    "power_of_two_buckets",
    "LMServingEngine", "LMStream", "LMMetrics", "StreamTruncation",
    "HedgePolicy", "prefill_bucket_lengths",
    "DisaggCoordinator", "KVHandoff",
    "BlockPool", "RadixCache", "PoolExhausted", "RequestExceedsPool",
    "HostBlockStore",
    "LMReplicaSet", "RoutedLMStream", "RadixRouter", "RadixSummary",
    "SessionTable",
    "DeviceTopology", "MeshSlice", "MeshSlicer", "PlacementError",
    "PlacementPolicy", "serving_tp_rules", "shard_params_chunked",
    "SpecConfig", "DraftModel", "SpecMetrics",
]
