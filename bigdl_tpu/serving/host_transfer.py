"""Host->device staging for serving batches.

Thin serving-side veneer over ``utils.transfer.chunked_device_put``:
the tunneled TPU backend dies on oversized single-buffer transfers
(CLAUDE.md ground rule, ~154 MB killed the round-4 relay), so every
batch is staged in <=32 MB slices with one slice in flight at a time.
The stager also keeps byte/chunk counters so the serving metrics can
report transfer pressure per engine.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from bigdl_tpu.utils.transfer import DEFAULT_CHUNK_BYTES, chunked_device_put


class HostStager:
    """Stages host batches onto the device with chunking + counters."""

    def __init__(self, dtype=None, *,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES, device=None):
        self.dtype = dtype
        self.chunk_bytes = int(chunk_bytes)
        self.device = device
        self._lock = threading.Lock()
        self.bytes_staged = 0
        self.batches_staged = 0

    def stage(self, x_host):
        """Upload one batch; returns the ready device array."""
        import jax.numpy as jnp

        x_host = np.asarray(x_host)
        out = chunked_device_put(x_host, self.dtype,
                                 chunk_bytes=self.chunk_bytes,
                                 device=self.device)
        wire = jnp.dtype(self.dtype) if self.dtype is not None \
            else x_host.dtype
        with self._lock:
            self.bytes_staged += int(x_host.size) * jnp.dtype(wire).itemsize
            self.batches_staged += 1
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"bytes_staged": self.bytes_staged,
                    "batches_staged": self.batches_staged,
                    "chunk_bytes": self.chunk_bytes}
