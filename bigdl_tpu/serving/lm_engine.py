"""LM serving: continuous batching over a PAGED HBM-resident KV cache.

Offline ``generate()`` decodes one homogeneous batch in lockstep: every
prompt prefills together, every row steps together, and the batch
finishes when the SLOWEST request does — a serving workload with
staggered arrivals and mixed lengths wastes most of its FLOPs on
padding and waiting.  ``LMServingEngine`` is the iteration-level
(continuous) batching alternative (Orca, OSDI'22; the throughput model
vLLM popularized), built from fixed-shape device programs over the
paged block arena of :mod:`bigdl_tpu.serving.kvcache`:

- **prefill** — bucketed passes per new request through the shared
  :class:`CompileCache`.  A cold prompt runs the plain bucketed prefill
  (`_prefill_parts`); a prompt whose head is cached in the
  :class:`RadixCache` prefills only the unmatched SUFFIX against the
  cached block chain (`_prefill_suffix_parts`, one executable per
  (suffix bucket, prefix-chain bucket)); prompts longer than the
  largest bucket prefill in block-aligned CHUNKS — over-length requests
  are admitted, not rejected.
- **insert** — scatter of each chunk's k/v rows into its allocated
  blocks of the resident (L, num_blocks, H, block_len, D) arenas,
  donated so insert rewrites the resident buffers in place.
- **decode** — ONE fixed-shape executable stepping all S slots, each at
  its own position, taking a padded int32 **block-table** operand
  (S, M) (padded entries name the scratch block) — paging changes the
  operand, not the executable count — with ``donate_argnums`` on both
  arenas so the decode loop never copies HBM-resident state.

Sharing: the radix cache maps token prefixes to refcounted block
chains, so concurrent requests with a common head attend the SAME
blocks copy-free; decode always writes into a sequence's private tail
blocks (the trie only ever holds *full prompt* blocks, and generation
starts past them).  Pool pressure defers admissions (blocks free as
streams finish, and the trie LRU-evicts unreferenced tails) — only a
request whose total need exceeds the WHOLE pool is rejected, with the
typed :class:`~bigdl_tpu.serving.kvcache.RequestExceedsPool` counted
in ``serving/rejected_total``.

Correctness: a slot's token stream is the same computation offline
``generate()`` runs at batch 1 — cached prefix keys are stored
post-RoPE (rotated once at their own positions) so the suffix prefill
attends the identical valid key set through the identical attention
core, and decode masks gathered positions ``> pos`` so stale or
scratch rows are never attended.  The mixed-length soak asserts
token-exact agreement per request, greedy and sampled, sharing on.

Observability: TTFT and inter-token-latency histograms, tokens/sec,
slot occupancy (``serving/lm/*``) plus the paged-cache plane
(``kvcache/*``): block utilization, prefix hit rate, prefill tokens
saved, evictions, and the arena's HBM footprint
(``kvcache/arena_bytes``) — all in the process-wide registry, so
``ObsSummary`` and the SLO controller's headroom checks see cache
memory, not just slots.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.obs import get_registry, get_tracer
from bigdl_tpu.obs.registry import FnGauge, Histogram
from bigdl_tpu.obs.tracer import mint_request_id
from bigdl_tpu.resilience.errors import (BackendLostError,
                                         ServingDeadlineExceeded,
                                         ServingOverloaded,
                                         TransientBackendError)
from bigdl_tpu.serving.batcher import (ServingClosed, ServingQueueFull,
                                       count_rejection)
from bigdl_tpu.serving.compile_cache import CompileCache
from bigdl_tpu.serving.kvcache import (BlockPool, PoolExhausted, RadixCache,
                                       RequestExceedsPool)
from bigdl_tpu.utils.engine import select_platform

_tracer = get_tracer()
log = logging.getLogger("bigdl_tpu.serving")


def prefill_bucket_lengths(max_len: int, min_bucket: int = 8) -> tuple:
    """Power-of-two prompt-length buckets up to (and including) a
    non-power-of-two ``max_len`` cap."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    buckets = []
    b = max(1, int(min_bucket))
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_len))
    return tuple(sorted(set(buckets)))


# ---------------------------------------------------------------------- #
class StreamTruncation:
    """Typed marker for a stream the lifecycle layer ended early.

    Attached as ``LMStream.truncation`` when a mid-stream deadline
    expiry or a cooperative cancel finishes the stream: the tokens
    already emitted stay valid (and bit-exact), the stream completes
    WITHOUT an error, and the marker records why and where it stopped.
    ``reason`` is ``"deadline"`` or ``"cancelled"``."""

    __slots__ = ("reason", "at_tokens", "deadline_s")

    def __init__(self, reason: str, at_tokens: int,
                 deadline_s: Optional[float] = None):
        self.reason = str(reason)
        self.at_tokens = int(at_tokens)  # generated length at truncation
        self.deadline_s = deadline_s     # original budget, if any

    def __repr__(self):
        return (f"StreamTruncation(reason={self.reason!r}, "
                f"at_tokens={self.at_tokens})")


class LMStream:
    """Per-request handle: tokens stream in as the engine decodes them.

    ``tokens()`` iterates 1-based generated ids as they land;
    ``result()`` blocks for the full sequence (prompt + generated).
    Timing marks (submit / first token / finish) feed the TTFT and
    inter-token-latency metrics and are readable per request.

    Lifecycle: an optional wall-clock budget (``deadline_s``, armed at
    enqueue) and a public :meth:`cancel`.  Both are COOPERATIVE — the
    engine honors them at its next scheduler round, recycling the
    decode slot and KV blocks and finishing the stream with a typed
    :class:`StreamTruncation` marker (already-emitted tokens stay
    valid; ``result()`` returns them without raising).
    """

    def __init__(self, prompt_1b: np.ndarray, max_new: int,
                 request_id: Optional[str] = None,
                 deadline_s: Optional[float] = None):
        self.prompt = prompt_1b
        self.max_new = int(max_new)
        self.request_id = request_id    # trace/flight correlation handle
        self._tokens: List[int] = []
        self._cond = threading.Condition()
        self._done = False
        self._error: Optional[BaseException] = None
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # --- lifecycle ---------------------------------------------- #
        self.deadline_s = (float(deadline_s)
                           if deadline_s is not None else None)
        # absolute wall-clock deadline, minted at construction so the
        # remaining budget (not a reset one) rides every re-dispatch,
        # KV handoff, and hibernate/resume hop
        self.deadline_at = ((time.monotonic() + self.deadline_s)
                            if self.deadline_s is not None else None)
        self.truncation: Optional[StreamTruncation] = None
        self._cancel_requested = False
        self._cancel_at_gen = 0         # generated length when cancelled
        self._wake_cb = None            # engine nudge, set at enqueue

    # lifecycle ---------------------------------------------------------- #
    def cancel(self) -> bool:
        """Request cooperative cancellation (client disconnected /
        stopped caring).  Returns True if the request was still live;
        the engine honors it at the next scheduler round.  Idempotent
        and safe from any thread."""
        with self._cond:
            if self._done:
                return False
            if not self._cancel_requested:
                self._cancel_requested = True
                self._cancel_at_gen = len(self._tokens)
            cb = self._wake_cb
        if cb is not None:
            try:
                cb()
            except Exception:   # a closing engine must not fail cancel
                pass
        return True

    @property
    def cancel_requested(self) -> bool:
        with self._cond:
            return self._cancel_requested

    def expired(self, now: Optional[float] = None) -> bool:
        """True when the wall-clock budget is spent."""
        if self.deadline_at is None:
            return False
        return (now if now is not None else time.monotonic()) \
            >= self.deadline_at

    def remaining_s(self, now: Optional[float] = None) -> Optional[float]:
        """Budget left (seconds; may be negative), or None if unbounded."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - (now if now is not None
                                   else time.monotonic())

    # engine-side ------------------------------------------------------- #
    def _emit(self, token_1b: int) -> None:
        with self._cond:
            if self.first_token_at is None:
                self.first_token_at = time.perf_counter()
            self._tokens.append(int(token_1b))
            self._cond.notify_all()

    def _finish(self, error: Optional[BaseException] = None) -> None:
        with self._cond:
            if self._done:
                return
            self._done = True
            self._error = error
            self.finished_at = time.perf_counter()
            self._cond.notify_all()

    def _finish_truncated(self, reason: str) -> None:
        """Finish early with a typed truncation marker (no error): the
        tokens already emitted remain the valid, bit-exact prefix of
        what the full decode would have produced."""
        with self._cond:
            if self._done:
                return
            if self.truncation is None:
                self.truncation = StreamTruncation(
                    reason, len(self._tokens), self.deadline_s)
        self._finish()

    # client-side ------------------------------------------------------- #
    def tokens(self, timeout: Optional[float] = None):
        """Yield generated 1-based token ids as they arrive."""
        deadline = (time.perf_counter() + timeout) if timeout else None
        i = 0
        while True:
            with self._cond:
                while len(self._tokens) <= i and not self._done:
                    left = (deadline - time.perf_counter()) if deadline \
                        else None
                    if left is not None and left <= 0:
                        raise TimeoutError("LMStream.tokens timed out")
                    self._cond.wait(left)
                if len(self._tokens) > i:
                    tok = self._tokens[i]
                    i += 1
                elif self._error is not None:
                    raise self._error
                else:
                    return
            yield tok

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until done; return prompt + generated ids (1-based)."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout):
                raise TimeoutError("LMStream.result timed out")
            if self._error is not None:
                raise self._error
            gen = np.asarray(self._tokens, np.int32)
        return np.concatenate([self.prompt, gen])

    def done(self) -> bool:
        with self._cond:
            return self._done

    @property
    def generated(self) -> np.ndarray:
        with self._cond:
            return np.asarray(self._tokens, np.int32)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


# ---------------------------------------------------------------------- #
class LMMetrics:
    """Serving-LM counters; thread-safe (decode worker + callers).

    Occupancy is measured where continuous batching earns its keep: the
    fraction of slot-iterations that decoded a real request (a lockstep
    engine pays for every slot every step regardless).

    ITL is split per phase: ``itl`` stays the combined histogram every
    existing consumer (SLO controller, bench rows) reads, while
    ``itl_decode`` holds only gaps between back-to-back decode rounds
    and ``itl_prefill_gap`` the gaps a prefill (or a KV-chain adoption)
    interrupted — the head-of-line blocking disaggregation exists to
    remove, now measurable straight from the registry
    (``serving/lm/itl_decode`` vs ``serving/lm/itl_prefill_gap``)."""

    def __init__(self, slots: int, throughput_window_s: float = 60.0):
        self._lock = threading.Lock()
        self.slots = int(slots)
        self.spec = None  # SpecMetrics when the engine speculates
        self.ttft = Histogram()
        self.itl = Histogram()
        self.itl_decode = Histogram()
        self.itl_prefill_gap = Histogram()
        self.requests = 0
        self.rejected = 0
        self.completed = 0
        self.tokens = 0
        self.prefills = 0
        self.decode_steps = 0
        self.slot_steps = 0
        self.active_slot_steps = 0
        self.peak_active = 0
        self.started_at = time.perf_counter()
        self._window_s = float(throughput_window_s)
        self._recent: deque = deque()  # (t, n_tokens) per decode step

    def publish_to(self, registry,
                   prefix: str = "serving/lm/") -> "LMMetrics":
        registry.register(prefix + "ttft", self.ttft, replace=True)
        registry.register(prefix + "itl", self.itl, replace=True)
        registry.register(prefix + "itl_decode", self.itl_decode,
                          replace=True)
        registry.register(prefix + "itl_prefill_gap", self.itl_prefill_gap,
                          replace=True)
        for key in ("requests", "rejected", "completed", "tokens",
                    "prefills", "decode_steps"):
            registry.register(prefix + key,
                              FnGauge(lambda k=key: getattr(self, k)),
                              replace=True)
        registry.register(prefix + "tokens_per_s",
                          FnGauge(lambda: self.snapshot()["tokens_per_s"]),
                          replace=True)
        registry.register(
            prefix + "slot_occupancy",
            FnGauge(lambda: self.snapshot()["slot_occupancy"]),
            replace=True)
        registry.register(
            prefix + "slot_occupancy_peak",
            FnGauge(lambda: self.snapshot()["slot_occupancy_peak"]),
            replace=True)
        return self

    # -- recording ------------------------------------------------------ #
    def record_submit(self) -> None:
        with self._lock:
            self.requests += 1

    def record_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_first_token(self, ttft_s: float) -> None:
        with self._lock:
            self.prefills += 1
            self.tokens += 1
            self.ttft.observe(ttft_s)
            self._recent.append((time.perf_counter(), 1))

    def record_step(self, n_active: int, itls_s: Sequence[float],
                    prefill_interrupted: bool = False) -> None:
        with self._lock:
            now = time.perf_counter()
            self.decode_steps += 1
            self.slot_steps += self.slots
            self.active_slot_steps += n_active
            self.peak_active = max(self.peak_active, n_active)
            self.tokens += len(itls_s)
            self._recent.append((now, len(itls_s)))
            horizon = now - self._window_s
            while self._recent and self._recent[0][0] < horizon:
                self._recent.popleft()
            split = (self.itl_prefill_gap if prefill_interrupted
                     else self.itl_decode)
            for itl in itls_s:
                self.itl.observe(itl)
                split.observe(itl)

    def record_complete(self) -> None:
        with self._lock:
            self.completed += 1

    # -- reading -------------------------------------------------------- #
    def snapshot(self) -> dict:
        with self._lock:
            now = time.perf_counter()
            horizon = now - self._window_s
            while self._recent and self._recent[0][0] < horizon:
                self._recent.popleft()
            span = min(now - self.started_at, self._window_s)
            windowed = sum(n for _, n in self._recent)
            return {
                "requests": self.requests,
                "rejected": self.rejected,
                "completed": self.completed,
                "tokens": self.tokens,
                "prefills": self.prefills,
                "decode_steps": self.decode_steps,
                "tokens_per_s": (windowed / span) if span > 0 else 0.0,
                "slot_occupancy":
                    (self.active_slot_steps / self.slot_steps)
                    if self.slot_steps else None,
                "slot_occupancy_peak":
                    (self.peak_active / self.slots)
                    if self.slot_steps else None,
                "ttft": self.ttft.snapshot(),
                "itl": self.itl.snapshot(),
                "itl_decode": self.itl_decode.snapshot(),
                "itl_prefill_gap": self.itl_prefill_gap.snapshot(),
                "spec": (self.spec.snapshot()
                         if self.spec is not None else None),
            }


# ---------------------------------------------------------------------- #
class _Request:
    __slots__ = ("stream", "prompt0", "max_new", "temperature", "eos0",
                 "first_key", "step_keys", "rid")

    def __init__(self, stream, prompt0, max_new, temperature, eos0,
                 first_key, step_keys, rid):
        self.stream = stream
        self.prompt0 = prompt0          # (t,) int32, 0-based
        self.max_new = max_new
        self.temperature = temperature
        self.eos0 = eos0                # 0-based eos id or None
        self.first_key = first_key      # np (2,) uint32 or None
        self.step_keys = step_keys      # np (max_new-1, 2) or None
        self.rid = rid                  # request id (tracing/forensics)


class _Slot:
    __slots__ = ("stream", "pos_next", "last0", "remaining", "step_idx",
                 "temperature", "eos0", "step_keys", "last_emit_at",
                 "blocks", "table", "draft_ok", "demoted", "accept_ema",
                 "spec_rounds", "probe_in", "tree_rung", "rid", "replay")

    def __init__(self, req: _Request, prompt_len: int, first0: int,
                 blocks: List[int], table: np.ndarray):
        self.stream = req.stream
        self.rid = req.rid
        self.pos_next = prompt_len      # next cache position to write
        self.last0 = first0             # last emitted token, 0-based
        self.remaining = req.max_new - 1
        self.step_idx = 0               # index into step_keys
        self.temperature = req.temperature
        self.eos0 = req.eos0
        self.step_keys = req.step_keys
        self.last_emit_at = time.perf_counter()
        self.blocks = blocks            # one pool ref per block
        self.table = table              # (M,) int32, scratch-padded
        # already-emitted 0-based tokens whose KV the decode loop must
        # rebuild (payload-less resume): forced through decode without
        # re-emitting, so the rebuilt rows ride the exact path that
        # wrote the originals
        self.replay: deque = deque()
        # speculation state (spec engines only)
        self.draft_ok = False           # drafter holds this slot's KV
        self.demoted = False            # plain decode until re-probe
        self.accept_ema = None          # acceptance-rate EMA
        self.spec_rounds = 0            # rounds of EMA evidence
        self.probe_in = 0               # plain rounds until re-probe
        self.tree_rung = 0              # shape-ladder rung (tree mode)


class KVHandoff:
    """One request mid-migration between phase replicas.

    The prefill replica builds it after emitting the first token (TTFT
    belongs to the prefill side); the coordinator fills ``payload``
    (the exported block-major wire arrays — or None to re-prefill on
    the decode side) and ``matched`` (blocks the DECODE pool's radix
    already held for this prompt, retained for the adoption, so prefix
    sharing survives the hop and only the unmatched tail travels); the
    decode replica consumes it via :meth:`LMServingEngine.adopt`.
    Sampling state (``step_keys``, position, last token) crosses intact
    — the decode side continues the exact offline trajectory."""

    __slots__ = ("stream", "prompt0", "max_new", "temperature", "eos0",
                 "step_keys", "rid", "first0", "payload", "matched",
                 "src_name")

    def __init__(self, req: "_Request", first0: int, src_name: str):
        self.stream = req.stream
        self.prompt0 = req.prompt0
        self.max_new = req.max_new
        self.temperature = req.temperature
        self.eos0 = req.eos0
        self.step_keys = req.step_keys
        self.rid = req.rid
        self.first0 = int(first0)       # already emitted; never re-emit
        self.payload = None             # {"k","v","blocks"} wire or None
        self.matched = []               # decode-pool blocks, pre-retained
        self.src_name = src_name


class _Hibernated:
    """One stream swapped out of its decode slot into the host KV
    tier — the hibernation analogue of :class:`KVHandoff`.  Carries
    the full sampling/position state (``pos_next``, ``last0``,
    ``remaining``, ``step_idx``, ``step_keys``) so resume re-enters
    decode at the exact token the slot left off; the KV chain itself
    lives in the :class:`~bigdl_tpu.serving.kvtier.HostBlockStore`
    under ``("session", rid)`` until resume pops it.  ``payload`` is
    populated at resume time (and kept across a pool-pressure
    deferral, so a popped chain is never re-read or lost)."""

    __slots__ = ("stream", "rid", "pos_next", "last0", "remaining",
                 "step_idx", "temperature", "eos0", "step_keys",
                 "n_used", "payload", "fetched", "hibernated_at")

    def __init__(self, st: "_Slot", n_used: int):
        self.stream = st.stream
        self.rid = st.rid
        self.pos_next = int(st.pos_next)
        self.last0 = int(st.last0)
        self.remaining = int(st.remaining)
        self.step_idx = int(st.step_idx)
        self.temperature = st.temperature
        self.eos0 = st.eos0
        self.step_keys = st.step_keys
        self.n_used = int(n_used)       # exported blocks (written KV)
        self.payload = None             # wire payload once fetched
        self.fetched = False            # tier lookup happened
        self.hibernated_at = time.perf_counter()


class _Prefill:
    """An admitted request's in-progress (possibly chunk-interleaved)
    prefill: blocks are allocated, ``p`` tokens are in the arena."""

    __slots__ = ("req", "blocks", "slot", "p", "t", "logits", "handoff")

    def __init__(self, req: _Request, blocks: List[int], slot: int,
                 matched_len: int, handoff: Optional[KVHandoff] = None):
        self.req = req
        self.blocks = blocks
        self.slot = slot
        self.p = matched_len            # tokens already in the arena
        self.t = req.prompt0.shape[0]
        self.logits = None
        self.handoff = handoff          # set: re-prefill, don't re-emit


# ---------------------------------------------------------------------- #
class LMServingEngine:
    """Serve ``TransformerLM`` generation with continuous batching over
    a paged, prefix-shared KV cache.

    Args:
        model: a built ``TransformerLM`` (params are frozen at
            construction, like :class:`ServingEngine`).
        slots: decode batch width S — concurrent in-flight requests.
        cache_len: per-REQUEST context cap (default ``model.max_len``);
            every request needs ``prompt_len + max_new <= cache_len``.
            No longer a per-slot HBM region: KV memory is pooled.
        max_new_tokens: default generation budget per request.
        prefill_buckets: prompt-length pad buckets (default powers of
            two up to ``cache_len``); one AOT prefill executable each.
            Prompts longer than the largest bucket prefill in
            block-aligned chunks of it.
        block_len: tokens per KV block (the page size).
        num_blocks: total pool blocks including the reserved scratch
            block (default: headroom for ``slots`` worst-case requests
            plus a few radix-cached chains).
        enable_prefix_cache: radix prefix sharing on admission
            (default on; sharing never changes streamed tokens).
        temperature: default sampling temperature (0 = greedy, the
            bit-exact-vs-offline path).
        eos_id: default 1-based stop token; generation also stops at
            ``max_new``.
        max_queue: admission queue bound (``ServingQueueFull`` beyond).
        platform: optional jax platform pin.
        donate_cache: donate k/v arenas into decode/insert (the no-copy
            hot path); disable only for debugging.
        decode_attn: decode attention over the paged cache —
            "gather" (dense kc[tables] materialization, the XLA
            baseline), "paged_kernel" (the in-place Pallas block-table
            kernel, ``ops.paged_attention``), or "auto" (default): the
            kernel only when the autotune cache has measured it faster
            than the gather ON THIS device kind, the gather otherwise.
            Both produce token-identical streams.
        kv_quant: ``None`` (full-precision KV, the default) or
            ``"int8"``: the block pool stores int8 KV blocks with
            per-(position, head) f32 scales, dequantized inside the
            paged gather — ~4x KV capacity at the same HBM.  Lossy
            (streams are NOT bit-exact vs a full-precision engine);
            forces the gather decode path and excludes disaggregated
            migration (``migrate``/``adopt``).
        spec: optional :class:`~bigdl_tpu.serving.spec.SpecConfig` (or
            an int k) enabling draft-verify speculative decoding: a
            cheap drafter (the target's int8 ``quantize()`` clone by
            default) proposes k tokens per slot and ONE fixed-shape
            donated verify executable scores all k+1 candidates per
            step.  Streams stay bit-exact vs offline generate under the
            default ``"replay"`` acceptance; a per-slot acceptance EMA
            demotes collapsing slots to plain decode and re-probes.
        max_prefill_chunk_tokens: Sarathi-style chunked-prefill
            interleaving — when set, the worker advances at most ONE
            block-aligned chunk of at most this many prompt tokens
            between decode rounds, so a long prompt landing mid-decode
            bounds every active stream's inter-token gap at one chunk's
            prefill instead of the whole prompt.  Trades TTFT for ITL;
            streams stay token-identical (chunk boundaries only change
            when KV rows are written, never their values).  Default
            None keeps the run-to-completion admission prefill.
        migrate: marks this engine a PREFILL-PHASE replica: after a
            request's first token is emitted, ``migrate(handoff,
            blocks, pool)`` is called (in the worker thread; the block
            chain stays referenced for the duration of the call) and
            the request leaves this engine — the DisaggCoordinator
            exports the chain and hands it to a decode replica's
            :meth:`adopt`.  Mutually exclusive with ``spec``.
        kvtier: optional
            :class:`~bigdl_tpu.serving.kvtier.HostBlockStore` — the
            host-RAM (+ disk spill) KV tier below the HBM arena.  When
            set, radix-tail eviction DEMOTES unreferenced prefix
            blocks into it instead of dropping them (int8 pools demote
            with their scales), admission PROMOTES any surviving
            host-tier continuation of a matched prefix back into HBM
            (prefilling only past it), and :meth:`hibernate` /
            :meth:`resume` swap whole idle streams out of their decode
            slots and back, bit-exactly.
        metrics / metrics_prefix: inject a shared :class:`LMMetrics`
            (the coordinator aggregates each phase's replicas into one
            per-phase histogram set for the SLO ladders) and/or publish
            under a non-default registry prefix.
    """

    def __init__(self, model, *,
                 slots: int = 8,
                 cache_len: Optional[int] = None,
                 max_new_tokens: int = 32,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 block_len: int = 16,
                 num_blocks: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 max_queue: int = 256,
                 max_cache_entries: int = 16,
                 platform: Optional[str] = None,
                 donate_cache: bool = True,
                 decode_attn: str = "auto",
                 kv_quant: Optional[str] = None,
                 name: str = "lm",
                 placement=None,
                 tp_rules=None,
                 spec=None,
                 max_prefill_chunk_tokens: Optional[int] = None,
                 migrate=None,
                 kvtier=None,
                 honor_lifecycle: bool = True,
                 metrics: Optional[LMMetrics] = None,
                 metrics_prefix: str = "serving/lm/"):
        select_platform(platform)
        import jax
        from bigdl_tpu.models.transformer.generate import (
            _decode_step_paged, _insert_blocks, _prefill_parts,
            _prefill_suffix_parts, _tree_commit_paged,
            _tree_verify_step_paged, _verify_step_paged)
        from bigdl_tpu.quant import dequantize_entry

        model._built()
        self.model = model
        self.name = name
        self.placement = placement
        self._params = model.params
        self._buffers = model.buffers
        if placement is not None:
            # TP across the slot: Megatron layer-stacked rules; flash
            # attention does not partition under GSPMD, pin XLA first
            from bigdl_tpu.parallel.tensor_parallel import (
                pin_xla_attention, transformer_lm_tp_rules)
            from bigdl_tpu.serving.placement import shard_params_chunked
            if placement.tp > 1:
                pin_xla_attention(model)
                if tp_rules is None:
                    tp_rules = transformer_lm_tp_rules(placement.mesh)
            rules = tp_rules if tp_rules is not None else (lambda p, l: None)
            self._params = shard_params_chunked(self._params, rules,
                                                placement.mesh)
            rep = placement.replicated()
            self._buffers = jax.tree_util.tree_map(
                lambda b: jax.device_put(b, rep), self._buffers)
        self.slots = int(slots)
        self.cache_len = int(cache_len or model.max_len)
        if self.cache_len > model.max_len:
            raise ValueError(
                f"cache_len ({self.cache_len}) exceeds model.max_len "
                f"({model.max_len})")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self._max_queue = int(max_queue)

        if prefill_buckets is None:
            prefill_buckets = prefill_bucket_lengths(self.cache_len)
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in prefill_buckets)))
        if self.prefill_buckets[-1] > self.cache_len:
            raise ValueError(
                f"largest prefill bucket ({self.prefill_buckets[-1]}) "
                f"exceeds cache_len ({self.cache_len}): a bucket longer "
                "than the per-request context cap can never fill")

        self.block_len = int(block_len)
        # padded block-table width: every request's chain fits in M ids
        self.table_width = -(-self.cache_len // self.block_len)
        # over-length prompts prefill in block-aligned chunks of the
        # largest bucket; 0 means buckets are sub-block (no chunking)
        self._chunk_full = (self.prefill_buckets[-1]
                            // self.block_len) * self.block_len
        self.migrate = migrate
        self.phase = "prefill" if migrate is not None else "colocated"
        if migrate is not None and spec is not None:
            raise ValueError(
                "a prefill-phase replica (migrate=...) cannot speculate: "
                "it never decodes — speculation belongs on the decode "
                "replicas")
        if kv_quant is not None and migrate is not None:
            raise ValueError(
                "kv_quant='int8' excludes disaggregated serving: the "
                "handoff protocol carries full-precision wire payloads "
                "(the host KV tier, not the coordinator, is the "
                "quantized-chain migration path)")
        self.max_prefill_chunk_tokens = None
        self._chunk_cap = None
        if max_prefill_chunk_tokens is not None:
            if self._chunk_full == 0:
                raise ValueError(
                    "max_prefill_chunk_tokens needs at least one "
                    f"block-aligned prefill bucket (block_len "
                    f"{self.block_len}, largest bucket "
                    f"{self.prefill_buckets[-1]})")
            self.max_prefill_chunk_tokens = int(max_prefill_chunk_tokens)
            # chunk boundaries must stay block-aligned so the suffix
            # prefill's prefix_len is a whole number of blocks
            self._chunk_cap = max(
                self.block_len,
                (self.max_prefill_chunk_tokens
                 // self.block_len) * self.block_len)
        if num_blocks is None:
            # slots worst-case chains + headroom for radix-held prefixes
            num_blocks = 1 + (self.slots + 4) * self.table_width
        L, H, D = model.n_layers, model._mha.n_head, model._mha.head_dim
        dt = self._params["embed"].dtype
        self.pool = BlockPool(n_layers=L, n_heads=H, head_dim=D,
                              block_len=self.block_len,
                              num_blocks=num_blocks, dtype=dt,
                              kv_quant=kv_quant)
        self.kv_quant = self.pool.kv_quant
        _kvq = self.kv_quant is not None
        if placement is not None:
            # KV arenas live replicated on the slot: every TP device
            # attends over the full (sharded-head math happens on the
            # projections, not the cache) and the donated insert/decode
            # executables keep the committed layout
            _rep = placement.replicated()
            self.pool.k = jax.device_put(self.pool.k, _rep)
            self.pool.v = jax.device_put(self.pool.v, _rep)
            if _kvq:
                self.pool.ks = jax.device_put(self.pool.ks, _rep)
                self.pool.vs = jax.device_put(self.pool.vs, _rep)
        self.radix = RadixCache(self.pool) if enable_prefix_cache else None
        #: router-published prefix summary (see attach_radix_summary)
        self.radix_summary = None
        self.kvtier = kvtier
        if self.kvtier is not None and self.radix is not None:
            # THE demote hook: radix-tail eviction hands each victim
            # block to the host tier (with scales, when quantized)
            # instead of dropping it
            self.radix.on_evict = self._demote_block
        self._cache_dtype = dt
        # prefix-chain pad buckets (powers of two up to the table width)
        self._prefix_block_buckets = prefill_bucket_lengths(
            self.table_width, min_bucket=1)

        # -- the device programs ---------------------------------------- #
        _ptag = placement.tag if placement is not None else ""
        _out_rep = (placement.replicated()
                    if placement is not None and placement.tp > 1 else None)

        def _constrain(y):
            # TP leaves prefill logits/kv sharded mid-graph; pin every
            # output replicated so the host pull and the (replicated)
            # arena insert see one clean layout
            if _out_rep is None:
                return y
            return jax.lax.with_sharding_constraint(y, _out_rep)

        def _prefill_fn(params, buffers, x):
            del buffers  # part of the CompileCache signature only
            return _constrain(_prefill_parts(model, dequantize_entry(params),
                                             x["ids"], x["len"] - 1))

        self.prefill_cache = CompileCache(
            _prefill_fn, max_entries=max_cache_entries, placement_tag=_ptag,
            name=f"lm/{name}/prefill")

        def _prefix_prefill_fn(params, buffers, x):
            del buffers
            if _kvq:
                return _constrain(_prefill_suffix_parts(
                    model, dequantize_entry(params), x["ids"],
                    x["len"] - 1, x["prefix_len"], x["blocks"],
                    x["k"], x["v"], x["ks"], x["vs"]))
            return _constrain(_prefill_suffix_parts(
                model, dequantize_entry(params), x["ids"], x["len"] - 1,
                x["prefix_len"], x["blocks"], x["k"], x["v"]))

        self.prefix_prefill_cache = CompileCache(
            _prefix_prefill_fn, max_entries=max_cache_entries,
            placement_tag=_ptag, name=f"lm/{name}/prefix_prefill")

        if decode_attn not in ("auto", "gather", "paged_kernel"):
            raise ValueError(f"decode_attn must be 'auto', 'gather' or "
                             f"'paged_kernel', got {decode_attn!r}")
        if _kvq:
            # the Pallas paged kernel reads raw blocks — a quantized
            # pool's in-gather dequant needs the gather path
            if decode_attn == "paged_kernel":
                raise ValueError(
                    "kv_quant='int8' requires decode_attn='gather' (the "
                    "Pallas paged kernel reads raw blocks)")
            decode_attn = "gather"
        elif decode_attn == "auto":
            # the same crossover discipline as flash_attention: the
            # kernel only on tuned evidence for this device kind, the
            # proven XLA gather otherwise
            from bigdl_tpu.ops import autotune
            tuned = autotune.lookup_paged(D, self.block_len, dt)
            decode_attn = ("paged_kernel"
                           if tuned is not None and tuned.use_kernel
                           else "gather")
        self.decode_attn = decode_attn

        if _kvq:
            def _decode_fn(params, token, pos, tables, kc, vc, ks, vs):
                return _constrain(_decode_step_paged(
                    model, dequantize_entry(params), token, pos, tables,
                    kc, vc, ks, vs, attn_impl=decode_attn))

            donate = (4, 5, 6, 7) if donate_cache else ()
        else:
            def _decode_fn(params, token, pos, tables, kc, vc):
                return _constrain(_decode_step_paged(
                    model, dequantize_entry(params), token, pos, tables,
                    kc, vc, attn_impl=decode_attn))

            donate = (4, 5) if donate_cache else ()
        self._decode_jit = jax.jit(_decode_fn, donate_argnums=donate)
        self._decode_exec = None

        _insert_donate = ((0, 1, 5, 6) if _kvq else (0, 1))
        self._insert_jit = jax.jit(
            _insert_blocks,
            donate_argnums=_insert_donate if donate_cache else ())
        self._insert_execs: dict = {}

        # -- speculation (draft-verify) --------------------------------- #
        self.spec = None
        self.draft = None
        self.spec_metrics = None
        self._verify_jit = None
        self._verify_exec = None
        self._verify_compiles = 0
        if spec is not None:
            from bigdl_tpu.quant import params_dtype_tag, set_compute_mode
            from bigdl_tpu.serving.spec import (DraftModel, NgramDrafter,
                                                SpecConfig, SpecMetrics)
            if isinstance(spec, int):
                spec = SpecConfig(k=spec)
            self.spec = spec
            draft_lm = spec.draft
            if getattr(spec, "drafter_compute", None) == "ngram":
                # zero-model prompt-lookup drafter: host-side suffix
                # matching, no device programs, no arena
                self.draft = NgramDrafter(
                    model.vocab_size, slots=self.slots,
                    ngram_max=spec.ngram_max)
            elif draft_lm is None:
                # derive the default drafter: the target's int8 clone
                # (or the target itself when it is already quantized),
                # running the kernels spec.drafter_compute asks for —
                # drafter numerics only move the acceptance rate, the
                # emitted stream is the target's under "replay"
                comp = getattr(spec, "drafter_compute", "dequant")
                if params_dtype_tag(model.params) == "int8":
                    draft_lm = model
                    if comp != "dequant":
                        # aux-only rewrite: the clone shares every int8
                        # buffer with the target, only the compute tag
                        # (pytree aux) differs
                        draft_lm = model.clone_module()
                        draft_lm.params = set_compute_mode(
                            model.params, comp)
                        draft_lm.grad_params = None
                        draft_lm = draft_lm.evaluate()
                else:
                    draft_lm = model.quantize("int8", compute=comp)
            if self.draft is None:
                if draft_lm.vocab_size != model.vocab_size:
                    raise ValueError(
                        f"draft model vocab ({draft_lm.vocab_size}) "
                        f"differs from the target's ({model.vocab_size}): "
                        "drafted token ids would not be the target's "
                        "token ids")
                self.draft = DraftModel(
                    draft_lm, slots=self.slots, cache_len=self.cache_len,
                    prefill_buckets=self.prefill_buckets,
                    max_cache_entries=max_cache_entries,
                    sampling=spec.sampling, placement_tag=_ptag)
            self.spec_metrics = SpecMetrics().publish_to(get_registry())
            self.spec_metrics.compute_mode = self.draft.compute_mode
            _drep = getattr(draft_lm, "quant_report", None) or {}
            self.spec_metrics.overflow_risk = float(
                _drep.get("overflow_risk") or 0.0)

            if _kvq:
                def _verify_fn(params, tokens, pos, n_cand, tables, kc,
                               vc, ks, vs):
                    return _constrain(_verify_step_paged(
                        model, dequantize_entry(params), tokens, pos,
                        n_cand, tables, kc, vc, ks, vs))

                _vdonate = (5, 6, 7, 8)
            else:
                def _verify_fn(params, tokens, pos, n_cand, tables, kc,
                               vc):
                    return _constrain(_verify_step_paged(
                        model, dequantize_entry(params), tokens, pos,
                        n_cand, tables, kc, vc))

                _vdonate = (5, 6)
            self._verify_jit = jax.jit(
                _verify_fn,
                donate_argnums=_vdonate if donate_cache else ())

            if spec.tree:
                # one donated verify executable per ladder rung: the
                # shape's depths/ancestor matrix are static constants of
                # each trace, so mixed-rung rounds ride the round's
                # widest rung with per-slot n_cand truncation (every
                # lower rung is a prefix of it)
                self._tree_shapes = list(spec.shapes)

                def _mk_tree_verify(shp):
                    _depths = np.asarray(shp.depths, np.int32)
                    _anc = np.ascontiguousarray(shp.anc)
                    if _kvq:
                        def _fn(params, tokens, pos, n_cand, tables, kc,
                                vc, ks, vs):
                            return _constrain(_tree_verify_step_paged(
                                model, dequantize_entry(params), tokens,
                                pos, n_cand, tables, kc, vc, ks, vs,
                                depths=_depths, anc=_anc))
                    else:
                        def _fn(params, tokens, pos, n_cand, tables, kc,
                                vc):
                            return _constrain(_tree_verify_step_paged(
                                model, dequantize_entry(params), tokens,
                                pos, n_cand, tables, kc, vc,
                                depths=_depths, anc=_anc))
                    return jax.jit(
                        _fn,
                        donate_argnums=_vdonate if donate_cache else ())

                self._verify_tree_jits = [
                    _mk_tree_verify(s) for s in self._tree_shapes]
                self._verify_tree_execs: dict = {}
                # the accepted-path commit: only needed when a shape has
                # off-spine nodes, sized to the deepest alternate depth
                self._commit_dmax = max(
                    (s.max_depth for s in self._tree_shapes
                     if not s.is_chain), default=0)
                if _kvq:
                    def _commit_fn(src, pos, tables, kc, vc, ks, vs):
                        return _constrain(_tree_commit_paged(
                            src, pos, tables, kc, vc, ks, vs))

                    _cdonate = (3, 4, 5, 6)
                else:
                    def _commit_fn(src, pos, tables, kc, vc):
                        return _constrain(_tree_commit_paged(
                            src, pos, tables, kc, vc))

                    _cdonate = (3, 4)
                self._commit_jit = jax.jit(
                    _commit_fn,
                    donate_argnums=_cdonate if donate_cache else ())
                self._commit_exec = None
                self._commit_compiles = 0

        self.metrics = (metrics if metrics is not None
                        else LMMetrics(self.slots)).publish_to(
            get_registry(), prefix=metrics_prefix)
        self.metrics.spec = self.spec_metrics
        self._publish_kv_metrics(get_registry())

        # memory-ledger attribution: KV arenas (+ int8 scale arenas),
        # staged params per placement slot, and the drafter's dense
        # arena.  Providers are weakref'd — a closed, collected engine's
        # bytes drop out of the table instead of pinning the arrays.
        self._ledger_keys: List[tuple] = []
        try:
            import weakref as _weakref

            from bigdl_tpu.obs.ledger import get_ledger
            from bigdl_tpu.quant import params_dtype_tag, params_nbytes
            led = get_ledger()
            _dev = placement.tag if placement is not None else None
            _pool_ref = _weakref.ref(self.pool)

            def _kv_bytes():
                p = _pool_ref()
                return p.kv_arena_bytes if p is not None else None

            self._ledger_keys.append(led.register(
                "kvcache", f"{name}/kv_arena", _kv_bytes,
                shape=self.pool.shape, dtype=str(self.pool.dtype),
                device=_dev))
            if self.kv_quant is not None:
                def _scale_bytes():
                    p = _pool_ref()
                    return (p.scale_arena_bytes if p is not None
                            else None)

                self._ledger_keys.append(led.register(
                    "kvcache", f"{name}/scale_arena", _scale_bytes,
                    shape=self.pool.shape[:4], dtype="float32",
                    device=_dev))
            self._ledger_keys.append(led.register(
                "params", f"{name}/staged",
                params_nbytes(self._params), device=_dev,
                note=f"quant={params_dtype_tag(self._params)}"))
            if self.draft is not None and \
                    getattr(self.draft, "k", None) is not None:
                # (the n-gram drafter has no arena — nothing to attribute)
                _draft_ref = _weakref.ref(self.draft)

                def _draft_bytes():
                    d = _draft_ref()
                    return d.arena_bytes if d is not None else None

                self._ledger_keys.append(led.register(
                    "spec", f"{name}/draft_arena", _draft_bytes,
                    shape=self.draft.k.shape,
                    dtype=str(self.draft.k.dtype), device=_dev))
        except Exception:
            log.exception("memory-ledger registration failed")

        # -- scheduler state (worker thread owns the slots) ------------- #
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._adopt_q: deque = deque()       # pending KVHandoff adoptions
        self._prefilling: deque = deque()    # chunk-interleaved _Prefills
        self._prefill_since_step = False     # splits the ITL histograms
        self.migrated = 0       # prefill phase: chains handed off
        self.adopted = 0        # decode phase: chains seated
        self.re_prefills = 0    # decode phase: lost payloads recomputed
        # -- session hibernation (host KV tier) ------------------------- #
        self._hibernate_req: set = set()     # rids awaiting swap-out
        self._hibernated: dict = {}          # rid -> _Hibernated
        self._resume_q: deque = deque()      # _Hibernated awaiting seats
        self.hibernations = 0   # streams swapped out to the host tier
        self.resumes = 0        # streams seated back from hibernation
        self.resume_re_prefills = 0  # lost payloads rebuilt via replay
        # the SLO controller's decode-concurrency actuator: the decode
        # executable always steps the full S physical slots (fixed
        # shape — no recompile), but admission only fills slots up to
        # this cap, trading throughput for per-token latency live
        self._slot_limit = self.slots
        self._free = list(range(self.slots))
        self._slots: List[Optional[_Slot]] = [None] * self.slots
        self._n_active = 0
        self._closing = False
        self._abort = False
        self._lc_nudge = False    # a cancel/deadline wants a sweep
        # -- request lifecycle (deadlines / cooperative cancel) ---------- #
        # honor_lifecycle=False is the bench's ignore-everything
        # baseline: deadlines and cancels are RECORDED (so wasted
        # decode work is measurable) but never acted on.
        self.honor_lifecycle = bool(honor_lifecycle)
        self._lc_lock = threading.Lock()
        self.lifecycle = {
            "expired_preadmission": 0,   # shed before prefill
            "expired_midstream": 0,      # truncated while decoding
            "cancelled": 0,              # cooperative cancels honored
            "wasted_decode_steps": 0,    # slot-steps past cancel/deadline
        }
        _reg = get_registry()
        self._lc_counters = {
            k: _reg.counter(f"serving/lifecycle/{k}")
            for k in self.lifecycle}
        self._worker = threading.Thread(
            target=self._run, daemon=True, name=f"lm-serve-{name}")
        self._worker.start()
        # flight-recorder hookup: incident bundles capture the engine's
        # scheduler/kv state and the active request ids.  weakref'd so
        # a closed engine is collectable.
        try:
            from bigdl_tpu.obs import flight
            import weakref
            ref = weakref.ref(self)

            def _flight_state():
                eng = ref()
                return eng.stats() if eng is not None else None

            def _flight_requests():
                eng = ref()
                if eng is None:
                    return []
                with eng._cv:
                    rids = [r.rid for r in eng._queue]
                    rids += [st.rid for st in eng._slots
                             if st is not None]
                return rids

            flight.register_state(f"lm_engine/{name}", _flight_state)
            flight.register_requests(f"lm_engine/{name}",
                                     _flight_requests)
        except Exception:
            log.exception("flight-recorder registration failed")

    def _publish_kv_metrics(self, registry) -> None:
        registry.register("kvcache/block_utilization",
                          FnGauge(lambda: self.pool.utilization()),
                          replace=True)
        registry.register(
            "kvcache/prefix_hit_rate",
            FnGauge(lambda: self.radix.hit_rate()
                    if self.radix is not None else None),
            replace=True)
        registry.register(
            "kvcache/prefill_tokens_saved",
            FnGauge(lambda: self.radix.matched_tokens
                    if self.radix is not None else 0),
            replace=True)
        registry.register(
            "kvcache/evictions",
            FnGauge(lambda: self.radix.evictions
                    if self.radix is not None else 0),
            replace=True)
        registry.gauge("kvcache/arena_bytes",
                       unit="bytes").set(self.pool.arena_bytes)

    # ------------------------------------------------------------------ #
    def warmup(self) -> int:
        """AOT-compile every prefill bucket plus the decode and insert
        executables before traffic; returns the number of prefill
        executables compiled.  Warmup never executes on the resident
        arenas (it lowers against shapes), so it is safe mid-traffic."""
        import numpy as _np

        inputs = [{"ids": _np.zeros((1, b), _np.int32),
                   "len": _np.int32(b)} for b in self.prefill_buckets]
        n = self.prefill_cache.warmup_inputs(
            self._params, self._buffers, inputs)
        if self.draft is not None:
            # a spec engine decodes through the verify executable (a
            # plain-decode slot is just an n_cand=1 row); the drafter
            # warms its own prefill/decode/insert programs
            if self.spec.tree:
                # tree mode: one executable per ladder rung, plus the
                # accepted-path commit when any shape has alternates
                for r in range(len(self._tree_shapes)):
                    self._verify_tree_compiled(r)
                if self._commit_dmax:
                    self._commit_compiled()
            else:
                self._verify_compiled()
            self.draft.warmup()
        elif self.migrate is None:
            # a prefill-phase replica never decodes — its requests
            # migrate after the first token — so skip that compile
            self._decode_compiled()
        for b in self.prefill_buckets:
            self._insert_compiled(b)
        return n

    def warmup_prefix(self, suffix_lens: Optional[Sequence[int]] = None,
                      prefix_blocks: Optional[Sequence[int]] = None) -> int:
        """AOT-compile the prefix-suffix prefill executables: one per
        (suffix bucket, prefix-chain bucket) pair.  Optional — they
        also compile on first use — but a TTFT-sensitive deployment
        warms them so the first shared-prefix hit doesn't pay a
        compile.  Pass the expected unmatched-suffix lengths and cached
        prefix block counts to warm only the combinations the traffic
        will hit (the full cross product otherwise).  Returns the
        number newly compiled."""
        import numpy as _np

        if suffix_lens is not None:
            cap = self.prefill_buckets[-1]
            sb = sorted({self.bucket_for(min(int(s), cap))
                         for s in suffix_lens})
        else:
            sb = list(self.prefill_buckets)
        if prefix_blocks is not None:
            pbs = sorted({self._prefix_bucket_for(int(p))
                          for p in prefix_blocks})
        else:
            pbs = list(self._prefix_block_buckets)
        inputs = []
        for b in sb:
            for pb in pbs:
                x = {"ids": _np.zeros((1, b), _np.int32),
                     "len": _np.int32(b),
                     "prefix_len": _np.int32(pb * self.block_len),
                     "blocks": _np.zeros((pb,), _np.int32),
                     "k": self.pool.k, "v": self.pool.v}
                if self.kv_quant is not None:
                    x["ks"], x["vs"] = self.pool.ks, self.pool.vs
                inputs.append(x)
        return self.prefix_prefill_cache.warmup_inputs(
            self._params, self._buffers, inputs)

    def _decode_compiled(self):
        if self._decode_exec is None:
            import jax
            # under placement the scheduler's np operands must lower as
            # slot-replicated (an unannotated lowering would bake in the
            # default device, clashing with the slot-committed params);
            # Compiled.__call__ auto-places the uncommitted np arrays
            sh = (dict(sharding=self.placement.replicated())
                  if self.placement is not None else {})
            sds = jax.ShapeDtypeStruct
            tok = sds((self.slots,), np.int32, **sh)
            pos = sds((self.slots,), np.int32, **sh)
            tables = sds((self.slots, self.table_width), np.int32, **sh)
            args = [self._params, tok, pos, tables,
                    self.pool.k, self.pool.v]
            if self.kv_quant is not None:
                args += [self.pool.ks, self.pool.vs]
            self._decode_exec = self._decode_jit.lower(*args).compile()
            self._ledger_exec("decode", f"slots={self.slots}",
                              self._decode_exec)
        return self._decode_exec

    def _verify_compiled(self):
        """The spec engine's single verify executable: all S slots, all
        W = k+1 candidate rows, every round — k is static per engine
        and slots pad with n_cand, so like decode this lowers ONCE."""
        if self._verify_exec is None:
            import jax
            sh = (dict(sharding=self.placement.replicated())
                  if self.placement is not None else {})
            sds = jax.ShapeDtypeStruct
            w = self.spec.k + 1
            tok = sds((self.slots, w), np.int32, **sh)
            pos = sds((self.slots,), np.int32, **sh)
            ncand = sds((self.slots,), np.int32, **sh)
            tables = sds((self.slots, self.table_width), np.int32, **sh)
            args = [self._params, tok, pos, ncand, tables,
                    self.pool.k, self.pool.v]
            if self.kv_quant is not None:
                args += [self.pool.ks, self.pool.vs]
            self._verify_exec = self._verify_jit.lower(*args).compile()
            self._verify_compiles += 1
            self._ledger_exec("verify", f"slots={self.slots}",
                              self._verify_exec)
        return self._verify_exec

    def _verify_tree_compiled(self, rung: int):
        """Tree mode's bounded-executables contract: one donated verify
        per ladder rung (the shape's mask/depths are trace constants),
        counted in ``_verify_compiles`` exactly like linear verify.  A
        round lowers at its widest participating rung; narrower slots
        truncate with ``n_cand``."""
        exe = self._verify_tree_execs.get(rung)
        if exe is None:
            import jax
            sh = (dict(sharding=self.placement.replicated())
                  if self.placement is not None else {})
            sds = jax.ShapeDtypeStruct
            w = self._tree_shapes[rung].width
            tok = sds((self.slots, w), np.int32, **sh)
            pos = sds((self.slots,), np.int32, **sh)
            ncand = sds((self.slots,), np.int32, **sh)
            tables = sds((self.slots, self.table_width), np.int32, **sh)
            args = [self._params, tok, pos, ncand, tables,
                    self.pool.k, self.pool.v]
            if self.kv_quant is not None:
                args += [self.pool.ks, self.pool.vs]
            exe = self._verify_tree_jits[rung].lower(*args).compile()
            self._verify_tree_execs[rung] = exe
            self._verify_compiles += 1
            self._ledger_exec(
                "verify", f"slots={self.slots}/tree_w={w}", exe)
        return exe

    def _commit_compiled(self):
        """The accepted-path commit executable (tree mode, shapes with
        alternates only): copies each accepted off-spine node's k/v row
        from its store offset to its position offset.  One lowering —
        ``src`` is always (S, Dmax) with identity rows for slots that
        stayed on the spine."""
        if self._commit_exec is None:
            import jax
            sh = (dict(sharding=self.placement.replicated())
                  if self.placement is not None else {})
            sds = jax.ShapeDtypeStruct
            src = sds((self.slots, self._commit_dmax), np.int32, **sh)
            pos = sds((self.slots,), np.int32, **sh)
            tables = sds((self.slots, self.table_width), np.int32, **sh)
            args = [src, pos, tables, self.pool.k, self.pool.v]
            if self.kv_quant is not None:
                args += [self.pool.ks, self.pool.vs]
            self._commit_exec = self._commit_jit.lower(*args).compile()
            self._commit_compiles += 1
            self._ledger_exec(
                "verify", f"slots={self.slots}/tree_commit",
                self._commit_exec)
        return self._commit_exec

    def _insert_compiled(self, bucket: int):
        exe = self._insert_execs.get(bucket)
        if exe is None:
            import jax
            L, N, H, B, D = self.pool.shape
            nb = -(-bucket // B)
            sds = jax.ShapeDtypeStruct
            sh = (dict(sharding=self.placement.replicated())
                  if self.placement is not None else {})
            # fresh chunk rows arrive in the model's compute dtype even
            # when the pool stores int8 (_insert_blocks quantizes them)
            new = sds((L, 1, H, bucket, D), self._cache_dtype, **sh)
            args = [sds(self.pool.shape, self.pool.dtype, **sh),
                    sds(self.pool.shape, self.pool.dtype, **sh),
                    new, new, sds((nb,), np.int32, **sh)]
            if self.kv_quant is not None:
                scale = sds(self.pool.shape[:4], np.float32, **sh)
                args += [scale, scale]
            exe = self._insert_jit.lower(*args).compile()
            self._insert_execs[bucket] = exe
            self._ledger_exec("insert", f"bucket={bucket}", exe)
        return exe

    def _ledger_exec(self, which: str, key: str, exe) -> None:
        """File a directly-lowered executable's cost/memory row with
        the memory ledger (best effort — never breaks a compile)."""
        try:
            from bigdl_tpu.obs.ledger import get_ledger
            get_ledger().record_compiled(f"lm/{self.name}/{which}", key,
                                         exe)
        except Exception:
            pass

    # ------------------------------------------------------------------ #
    def bucket_for(self, prompt_len: int) -> int:
        """Smallest configured prefill bucket >= prompt_len."""
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill "
            f"bucket ({self.prefill_buckets[-1]}) and the buckets are "
            f"smaller than one KV block ({self.block_len}): chunked "
            "prefill needs at least one block-aligned bucket")

    def _prefix_bucket_for(self, n_blocks: int) -> int:
        for pb in self._prefix_block_buckets:
            if pb >= n_blocks:
                return pb
        return self._prefix_block_buckets[-1]

    def submit(self, prompt_ids, *,
               max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               rng=None) -> LMStream:
        """Enqueue one prompt ((t,) or (1, t), 1-based ids); returns an
        :class:`LMStream` of its continuation.

        ``deadline_s`` is an optional wall-clock budget minted here, at
        enqueue: a request still queued when it expires is shed before
        prefill with :class:`ServingDeadlineExceeded`; a stream past it
        mid-decode is finished with a typed truncation marker and its
        slot/blocks recycled the same scheduler round."""
        prompt = np.asarray(prompt_ids).reshape(-1).astype(np.int32)
        t = prompt.shape[0]
        if t == 0:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        if max_new <= 0:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        if t + max_new > self.cache_len:
            raise ValueError(
                f"prompt ({t}) + max_new ({max_new}) exceeds cache_len "
                f"({self.cache_len})")
        # the typed whole-pool rejection: a request that could NEVER be
        # satisfied (its total block need exceeds the pool) is shed at
        # admission and counted; anything smaller is admissible — pool
        # pressure merely defers it until streams free blocks
        need = self.pool.blocks_for(t + max_new)
        if need > self.pool.capacity:
            self.metrics.record_reject()
            count_rejection()
            raise RequestExceedsPool(
                f"request needs {need} KV blocks ({t} prompt + {max_new} "
                f"new tokens at block_len {self.block_len}); the whole "
                f"pool holds {self.pool.capacity}")
        if self._chunk_full == 0:
            self.bucket_for(t)  # sub-block buckets: no chunked prefill
        temp = float(self.temperature if temperature is None
                     else temperature)
        eos = eos_id if eos_id is not None else self.eos_id
        eos0 = (int(eos) - 1) if eos is not None else None

        first_key = step_keys = None
        if temp > 0.0:
            # replicate offline generate()'s key chain exactly: one
            # split for the first token, then max_new-1 scan keys
            import jax
            if rng is None:
                rng = jax.random.PRNGKey(0)
            elif isinstance(rng, int):
                rng = jax.random.PRNGKey(rng)
            rng, sub = jax.random.split(rng)
            first_key = np.asarray(sub)
            if max_new > 1:
                step_keys = np.asarray(jax.random.split(rng, max_new - 1))

        # chaos hook on the admission path (same contract as the
        # batcher's): an injected transient surfaces as the typed shed
        from bigdl_tpu.resilience.faults import fault_point
        try:
            fault_point("serving.enqueue", name=self.name, n=t)
        except ServingOverloaded:
            raise
        except TransientBackendError as e:
            self.metrics.record_reject()
            count_rejection()
            raise ServingOverloaded(
                f"admission shed (injected at serving.enqueue): {e}") from e

        rid = mint_request_id()
        stream = LMStream(prompt, max_new, request_id=rid,
                          deadline_s=deadline_s)
        stream._wake_cb = self._lc_wake
        if (self.honor_lifecycle and deadline_s is not None
                and float(deadline_s) <= 0.0):
            # already dead on arrival: shed synchronously, typed
            self.metrics.record_reject()
            count_rejection()
            self._lc_count("expired_preadmission")
            raise ServingDeadlineExceeded(
                f"deadline_s={deadline_s} already expired at enqueue")
        req = _Request(stream, prompt - 1, max_new, temp, eos0,
                       first_key, step_keys, rid)
        with self._cv:
            if self._closing:
                raise ServingClosed("LMServingEngine is closed")
            if len(self._queue) >= self._max_queue:
                self.metrics.record_reject()
                count_rejection()
                raise ServingQueueFull(
                    f"admission queue full ({self._max_queue})")
            self._queue.append(req)
            depth = len(self._queue)
            self._cv.notify_all()
        self.metrics.record_submit()
        if _tracer.sampled(rid):
            _tracer.instant("lm/enqueue", cat="serve", request_id=rid,
                            prompt_len=t, max_new=max_new,
                            queue_depth=depth)
        return stream

    def adopt(self, handoff: KVHandoff) -> None:
        """Accept a migrated request (decode-phase entry point): the
        handoff's KV chain — transferred wire payload plus whatever the
        local radix already held — is seated into a slot by the worker
        and decode continues from the token the prefill replica already
        emitted.  Adoptions outrank queued submissions (they are
        further along: TTFT is already paid) and defer under pool
        pressure exactly like admissions."""
        # the deadline rides the handoff on the stream itself; rebind
        # the cancel nudge so a disconnect now wakes THIS worker
        handoff.stream._wake_cb = self._lc_wake
        with self._cv:
            if self._closing:
                raise ServingClosed("LMServingEngine is closed")
            self._adopt_q.append(handoff)
            self._cv.notify_all()
        self.metrics.record_submit()
        if _tracer.sampled(handoff.rid):
            _tracer.instant("lm/adopt_enqueue", cat="serve",
                            request_id=handoff.rid,
                            src=handoff.src_name,
                            wire_blocks=(handoff.payload["blocks"]
                                         if handoff.payload else None),
                            matched_blocks=len(handoff.matched))

    # -- live control knobs (the SLO controller's actuators) ----------- #
    def set_slot_limit(self, n: int) -> int:
        """Cap decode concurrency at ``n`` of the S physical slots
        (clamped to [1, slots]).  Cheap: the fixed-shape decode
        executable is untouched; only admission stops filling slots
        beyond the cap.  In-flight requests above a lowered cap finish
        normally — the cap applies to new admissions.  Returns the
        applied value."""
        with self._cv:
            self._slot_limit = max(1, min(int(n), self.slots))
            self._cv.notify_all()
            return self._slot_limit

    @property
    def slot_limit(self) -> int:
        with self._cv:
            return self._slot_limit

    def set_max_queue(self, n: int) -> None:
        """Admission-control actuator: rebind the queue bound live
        (shed new arrivals with ServingQueueFull beyond it); queued
        requests are never dropped."""
        with self._cv:
            self._max_queue = max(0, int(n))

    @property
    def max_queue(self) -> int:
        with self._cv:
            return self._max_queue

    def generate(self, prompt_ids, *,
                 timeout: Optional[float] = None, **kw) -> np.ndarray:
        """Sync convenience: submit + wait; returns (t + generated,)
        1-based ids for one prompt."""
        return self.submit(prompt_ids, **kw).result(timeout=timeout)

    # -- sampling (host-side, replicating offline generate exactly) ---- #
    @staticmethod
    def _pick(logits_row: np.ndarray, temperature: float, key,
              clamp: bool) -> int:
        # one shared implementation with the speculative acceptance
        # path (spec/verify.py), so plain decode, verify rows, and the
        # Gumbel-coupled drafter can never drift apart
        from bigdl_tpu.serving.spec.verify import pick_token
        return pick_token(logits_row, temperature, key, clamp)

    # -- worker -------------------------------------------------------- #
    def _run(self):
        try:
            while True:
                with self._cv:
                    while (not self._queue and not self._adopt_q
                           and not self._resume_q
                           and not self._n_active and not self._prefilling
                           and not self._closing and not self._abort
                           and not self._lc_nudge):
                        if not self._cv.wait(self._lc_wait_timeout()):
                            # a holding station's deadline came due
                            # while the engine idled (e.g. a hibernated
                            # stream): run the sweep
                            self._lc_nudge = True
                    if self._abort:
                        break
                    if (self._closing and not self._queue
                            and not self._adopt_q and not self._resume_q
                            and not self._n_active
                            and not self._prefilling):
                        # break (not return): the bottom _fail_all
                        # resolves any still-hibernated streams with
                        # ServingClosed instead of leaving them hanging
                        break
                    # cancelled/expired requests leave their holding
                    # stations BEFORE this round admits anything
                    self._lifecycle_sweep_locked()
                    # in-flight = decoding + mid-prefill: both hold slots
                    inflight = self._n_active + len(self._prefilling)
                    adopts = []
                    # adoptions outrank submissions: their TTFT is paid
                    while (self._free and self._adopt_q
                           and (inflight + len(adopts)) < self._slot_limit):
                        adopts.append((self._free.pop(),
                                       self._adopt_q.popleft()))
                    # resumes rank with adoptions (same reason) but
                    # after them: a migrated chain in transit is hotter
                    # than a hibernated one at rest
                    resumes = []
                    while (self._free and self._resume_q
                           and (inflight + len(adopts) + len(resumes))
                           < self._slot_limit):
                        resumes.append((self._free.pop(),
                                        self._resume_q.popleft()))
                    admits = []
                    while (self._free and self._queue
                           and (inflight + len(adopts) + len(resumes)
                                + len(admits)) < self._slot_limit):
                        admits.append((self._free.pop(),
                                       self._queue.popleft()))
                if self.migrate is not None:
                    # prefill-phase occupancy: one sample per scheduler
                    # round (a prefill replica has no decode steps, so
                    # this is the phase's slot-utilization signal; its
                    # decode_steps gauge reads as scheduler rounds)
                    self.metrics.record_step(
                        min(self.slots,
                            inflight + len(adopts) + len(admits)), [])
                deferred_adopts = []
                for slot, h in adopts:
                    try:
                        seated = self._adopt_into(slot, h)
                    except BaseException as e:  # noqa: BLE001
                        h.stream._finish(error=e)
                        with self._cv:
                            self._free.append(slot)
                    else:
                        if not seated:
                            deferred_adopts.append((slot, h))
                deferred_resumes = []
                for slot, hib in resumes:
                    try:
                        seated = self._resume_into(slot, hib)
                    except BaseException as e:  # noqa: BLE001
                        hib.stream._finish(error=e)
                        with self._cv:
                            self._free.append(slot)
                    else:
                        if not seated:
                            deferred_resumes.append((slot, hib))
                deferred = []
                for slot, req in admits:
                    try:
                        admitted = self._admit(slot, req)
                    except BaseException as e:  # noqa: BLE001
                        req.stream._finish(error=e)
                        with self._cv:
                            self._free.append(slot)
                    else:
                        if not admitted:
                            deferred.append((slot, req))
                if deferred or deferred_adopts or deferred_resumes:
                    # pool pressure: requeue at the FRONT (FIFO order
                    # preserved) and return the slots — blocks free as
                    # active streams finish, then admission retries
                    with self._cv:
                        for slot, req in reversed(deferred):
                            self._free.append(slot)
                            self._queue.appendleft(req)
                        for slot, h in reversed(deferred_adopts):
                            self._free.append(slot)
                            self._adopt_q.appendleft(h)
                        for slot, hib in reversed(deferred_resumes):
                            self._free.append(slot)
                            self._resume_q.appendleft(hib)
                        if not self._n_active and not self._prefilling:
                            # nothing in flight to free capacity (a
                            # ledger-watermark deferral with idle
                            # slots): wait briefly instead of spinning
                            # on the retry
                            self._cv.wait(0.05)
                self._lifecycle_round()
                if self._hibernate_req:
                    self._service_hibernations()
                if self._chunk_cap is not None and self._prefilling:
                    # Sarathi interleave: ONE bounded chunk of the
                    # oldest in-progress prefill per scheduler round,
                    # then back to decoding — the decode stall per
                    # round is one chunk, not one prompt
                    pf = self._prefilling[0]
                    try:
                        if self._prefill_chunk(pf):
                            self._prefilling.popleft()
                            self._finish_prefill(pf)
                    except BaseException as e:  # noqa: BLE001
                        self._prefilling.popleft()
                        self.pool.release(pf.blocks)
                        pf.req.stream._finish(error=e)
                        with self._cv:
                            self._free.append(pf.slot)
                if self._n_active:
                    if self.draft is not None:
                        self._step_spec()
                    else:
                        self._step()
        except BaseException as e:  # noqa: BLE001
            self._fail_all(e)
            return
        self._fail_all(ServingClosed("engine closed before completion"))

    # -- request lifecycle (deadlines / cooperative cancel) ------------- #
    def _lc_wake(self):
        """Client-side nudge (installed as ``LMStream._wake_cb``): a
        cancel must wake an idle worker so it is honored at the NEXT
        scheduler round, not the next organic one."""
        with self._cv:
            self._lc_nudge = True
            self._cv.notify_all()

    def _lc_count(self, key: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lc_lock:
            self.lifecycle[key] += n
        self._lc_counters[key].add(n)

    def _lc_wait_timeout(self) -> Optional[float]:
        """Earliest pending deadline across slot-less holding stations
        (queued / adoption / resume / hibernated), as a cv-wait bound —
        an idle engine must still wake to shed an expiring hibernated
        stream.  Caller holds ``_cv``; None = no deadline pending."""
        if not self.honor_lifecycle:
            return None
        dls = [r.stream.deadline_at for r in self._queue]
        dls += [h.stream.deadline_at for h in self._adopt_q]
        dls += [h.stream.deadline_at for h in self._resume_q]
        dls += [h.stream.deadline_at for h in self._hibernated.values()]
        dls = [d for d in dls if d is not None]
        if not dls:
            return None
        return max(0.0, min(dls) - time.monotonic()) + 0.005

    def _lc_shed_queued(self, stream: LMStream, rid) -> None:
        """A queued (never-prefilled) request left the lifecycle: a
        cancel truncates quietly; a blown deadline is the typed
        pre-admission shed — counted exactly like an admission-control
        rejection (``ServingDeadlineExceeded`` is a
        ``ServingOverloaded``), so SLO/goodput accounting holds."""
        if stream.cancel_requested:
            reason = "cancelled"
            self._lc_count("cancelled")
            stream._finish_truncated("cancelled")
        else:
            reason = "deadline"
            self.metrics.record_reject()
            count_rejection()
            self._lc_count("expired_preadmission")
            stream._finish(error=ServingDeadlineExceeded(
                f"deadline ({stream.deadline_s}s) expired before "
                "prefill; request shed pre-admission"))
        if _tracer.sampled(rid):
            _tracer.instant("lm/lifecycle_shed", cat="serve",
                            request_id=rid, reason=reason,
                            station="queue")

    def _lc_truncate(self, stream: LMStream, rid, *,
                     station: str = "seated") -> None:
        """Finish a request that progressed past admission (blocks
        were allocated / tokens may have been emitted) with the typed
        truncation marker; tokens already emitted stay valid."""
        if stream.cancel_requested:
            reason = "cancelled"
            self._lc_count("cancelled")
        else:
            reason = "deadline"
            self._lc_count("expired_midstream")
        stream._finish_truncated(reason)
        self.metrics.record_complete()
        if _tracer.sampled(rid):
            _tracer.instant("lm/lifecycle_truncate", cat="serve",
                            request_id=rid, reason=reason,
                            station=station,
                            at_tokens=len(stream.generated))

    def _lifecycle_sweep_locked(self) -> None:
        """Shed cancelled/expired requests from every holding station
        that owns NO decode slot: the admission queue (the pre-prefill
        shed), the adoption queue (pre-seat; its retained decode-pool
        blocks release), the resume queue, and the hibernated set —
        hibernated streams are cancellable WITHOUT resume: the chain
        drops straight out of the host tier, no promote transfer.
        Caller holds ``_cv``."""
        self._lc_nudge = False
        if not self.honor_lifecycle:
            return
        now = time.monotonic()

        def _dead(stream):
            return stream.cancel_requested or stream.expired(now)

        if any(_dead(r.stream) for r in self._queue):
            live = []
            while self._queue:
                r = self._queue.popleft()
                if _dead(r.stream):
                    self._lc_shed_queued(r.stream, r.rid)
                else:
                    live.append(r)
            self._queue.extend(live)
        if any(_dead(h.stream) for h in self._adopt_q):
            live = []
            while self._adopt_q:
                h = self._adopt_q.popleft()
                if _dead(h.stream):
                    if h.matched:
                        self.pool.release(h.matched)
                    self._lc_truncate(h.stream, h.rid, station="adopt_q")
                else:
                    live.append(h)
            self._adopt_q.extend(live)
        if any(_dead(h.stream) for h in self._resume_q):
            live = []
            while self._resume_q:
                hib = self._resume_q.popleft()
                if _dead(hib.stream):
                    # a popped payload rides the handle; dropping the
                    # handle drops the chain
                    self._lc_truncate(hib.stream, hib.rid,
                                      station="resume_q")
                else:
                    live.append(hib)
            self._resume_q.extend(live)
        for rid in [rid for rid, hib in self._hibernated.items()
                    if _dead(hib.stream)]:
            hib = self._hibernated.pop(rid)
            try:
                if self.kvtier is not None:
                    self.kvtier.get(("session", rid), pop=True)
            except Exception:
                pass
            self._lc_truncate(hib.stream, rid, station="hibernated")

    def _lifecycle_round(self) -> None:
        """Per-round lifecycle pass over the stations that DO hold a
        decode slot.  The ``serving.cancel`` fault site crosses here —
        one crossing per seated stream per round, and an injected
        fault IS that client disconnecting (how the chaos replayer
        makes a disconnect storm); then cancelled/expired streams are
        honored same-iteration: slot recycled, blocks released,
        drafter state dropped, stream finished with the typed
        truncation marker.  With ``honor_lifecycle=False`` (the bench's
        ignore-everything baseline) nothing is freed — instead every
        dead seated slot counts one wasted decode slot-step per round,
        the work this layer exists to shed."""
        from bigdl_tpu.resilience.faults import fault_point
        with self._cv:
            seated = [st.stream for st in self._slots if st is not None]
            seated += [pf.req.stream for pf in self._prefilling]
        for s in seated:
            try:
                fault_point("serving.cancel", name=self.name,
                            rid=s.request_id)
            except (TransientBackendError, BackendLostError):
                s.cancel()
        now = time.monotonic()

        def _dead(stream):
            return stream.cancel_requested or stream.expired(now)

        if not self.honor_lifecycle:
            with self._cv:
                n_dead = sum(1 for st in self._slots
                             if st is not None and _dead(st.stream))
            self._lc_count("wasted_decode_steps", n_dead)
            return
        with self._cv:
            if any(_dead(pf.req.stream) for pf in self._prefilling):
                live = []
                while self._prefilling:
                    pf = self._prefilling.popleft()
                    if _dead(pf.req.stream):
                        self.pool.release(pf.blocks)
                        self._free.append(pf.slot)
                        self._lc_truncate(pf.req.stream, pf.req.rid,
                                          station="prefilling")
                    else:
                        live.append(pf)
                self._prefilling.extend(live)
            freed = False
            for i, st in enumerate(self._slots):
                if st is None or not _dead(st.stream):
                    continue
                s = st.stream
                # decode steps spent between the cancel landing and
                # this round honoring it were wasted: count the
                # residual so the honored arm stays honest too
                if s.cancel_requested:
                    self._lc_count(
                        "wasted_decode_steps",
                        max(0, len(s._tokens) - s._cancel_at_gen))
                # identical cleanup to the EOS free path: refcounts
                # are conserved and the slot is reusable THIS round
                self._trace_done(s, st.rid)
                self.pool.release(st.blocks)
                self._slots[i] = None
                if self.draft is not None:
                    self.draft.release(i)
                self._free.append(i)
                self._n_active -= 1
                self._lc_truncate(s, st.rid)
                freed = True
            if freed:
                self._cv.notify_all()

    def _mem_pressure_deferred(self) -> bool:
        """Byte-level admission gate: when the memory ledger reads the
        device past its used-fraction watermark, defer the admission
        exactly like pool pressure — and let the ledger dump ONE
        ``mem_pressure`` flight bundle while the attribution table can
        still be written (a RESOURCE_EXHAUSTED later could not)."""
        try:
            from bigdl_tpu.obs.ledger import get_ledger
            led = get_ledger()
            if led.over_watermark():
                led.check_pressure(
                    context={"site": f"lm_admission/{self.name}"})
                return True
        except Exception:
            pass
        return False

    def _admit(self, slot: int, req: _Request) -> bool:
        """Prefill + insert one request into ``slot``.  Returns False
        (defer) when the pool can't supply its blocks right now — even
        after evicting unreferenced radix tails — or when the memory
        ledger reports device bytes past the watermark."""
        if self._mem_pressure_deferred():
            return False
        t = req.prompt0.shape[0]
        B = self.block_len
        need_total = self.pool.blocks_for(t + req.max_new)
        matched: List[int] = []
        if self.radix is not None:
            matched = self.radix.match(req.prompt0)  # retains for us
            if self.kvtier is not None:
                # a prefix that fell out of HBM may have survived a
                # tier down: promote its continuation back and extend
                # the match (prefill only past it)
                matched = self._promote_extend(req.prompt0, matched,
                                               rid=req.rid)
        traced = _tracer.sampled(req.rid)
        if traced and self.radix is not None:
            _tracer.instant("lm/radix_match", cat="serve",
                            request_id=req.rid,
                            matched_blocks=len(matched),
                            matched_tokens=len(matched) * B,
                            prompt_len=t)
        n_new = need_total - len(matched)
        try:
            fresh = self.pool.alloc(n_new)
        except PoolExhausted:
            if self.radix is not None:
                self.radix.evict(n_new - self.pool.free_count)
            try:
                fresh = self.pool.alloc(n_new)
            except PoolExhausted:
                if matched:
                    self.pool.release(matched)
                return False
        blocks = matched + fresh
        if traced:
            # queue wait is known only now, at successful admission —
            # retroactive, the batcher's serve/queue_wait idiom
            wait = time.perf_counter() - req.stream.submitted_at
            _tracer.add_complete("lm/queue_wait",
                                 req.stream.submitted_at, wait,
                                 cat="serve",
                                 args={"request_id": req.rid, "slot": slot})
        if self._chunk_cap is not None:
            # chunk-interleaved mode: allocation happens at admission
            # (all-or-nothing, same defer semantics), but the prefill
            # itself advances one bounded chunk per scheduler round in
            # _run — decode rounds run in between
            self._prefilling.append(_Prefill(req, blocks, slot,
                                             len(matched) * B))
            return True
        try:
            self._prefill_into(req, blocks, slot, len(matched) * B)
        except BaseException:
            self.pool.release(blocks)
            raise
        return True

    def _adopt_into(self, slot: int, h: KVHandoff) -> bool:
        """Seat a migrated request into ``slot``: adopt its wire
        payload into this pool (or re-prefill locally when the payload
        was lost in transit) and enter decode at the exact position the
        prefill replica left off.  Returns False (defer) under pool
        pressure — the handoff's pre-retained ``matched`` blocks stay
        held across the deferral, same as a matched radix head."""
        t = h.prompt0.shape[0]
        B = self.block_len
        need_total = self.pool.blocks_for(t + h.max_new)
        if need_total > self.pool.capacity:
            raise RequestExceedsPool(
                f"migrated request needs {need_total} blocks; decode "
                f"pool capacity is {self.pool.capacity}")
        req = _Request(h.stream, h.prompt0, h.max_new, h.temperature,
                       h.eos0, None, h.step_keys, h.rid)
        matched = list(h.matched)
        if h.payload is None:
            # wire payload lost (backend_lost at the migrate fault
            # site): recompute the KV here.  Deterministic prefill ⇒
            # bit-identical rows; the first token is NOT re-picked or
            # re-emitted (handoff carries it), so the stream is exact.
            self.re_prefills += 1
            n_new = need_total - len(matched)
            try:
                fresh = self.pool.alloc(n_new)
            except PoolExhausted:
                if self.radix is not None:
                    self.radix.evict(n_new - self.pool.free_count)
                try:
                    fresh = self.pool.alloc(n_new)
                except PoolExhausted:
                    return False
            blocks = matched + fresh
            pf = _Prefill(req, blocks, slot, len(matched) * B, handoff=h)
            if self._chunk_cap is not None:
                self._prefilling.append(pf)
                return True
            try:
                while not self._prefill_chunk(pf):
                    pass
                self._finish_prefill(pf)
            except BaseException:
                self.pool.release(blocks)
                raise
            return True
        n_wire = int(h.payload["blocks"])
        extra = need_total - len(matched) - n_wire
        if extra < 0:
            raise ValueError(
                f"wire carries {n_wire} blocks but only "
                f"{need_total - len(matched)} are unmatched")
        try:
            fresh = self.pool.adopt_chain(
                h.payload["k"], h.payload["v"], extra_blocks=extra,
                device=self.pool.k.sharding)
        except PoolExhausted:
            if self.radix is not None:
                self.radix.evict(n_wire + extra - self.pool.free_count)
            try:
                fresh = self.pool.adopt_chain(
                    h.payload["k"], h.payload["v"], extra_blocks=extra,
                    device=self.pool.k.sharding)
            except PoolExhausted:
                return False
        blocks = matched + fresh
        self.adopted += 1
        self._prefill_since_step = True  # adoption interrupts decode
        if self.radix is not None:
            # cache the adopted prompt for future prefix hits on THIS
            # pool — sharing survives the hop in both directions
            nfull = t // B
            if nfull:
                self.radix.insert(h.prompt0[:nfull * B], blocks[:nfull])
        if _tracer.sampled(h.rid):
            _tracer.instant("lm/adopt", cat="serve", request_id=h.rid,
                            slot=slot, wire_blocks=n_wire,
                            matched_blocks=len(matched), src=h.src_name)
        self._seat(req, t, h.first0, blocks, slot)
        return True

    # -- tiered KV memory (host tier + hibernation) --------------------- #
    def _demote_block(self, path, block: int) -> None:
        """Radix ``on_evict`` hook: gather the victim block's k/v rows
        (plus scales, when quantized — atomically, same payload) and
        demote them into the host tier keyed by the block's
        token-prefix path.  Runs while the block is still allocated."""
        wire = self.pool.export_chain([block])
        entry = {kk: wire[kk] for kk in ("k", "v", "ks", "vs")
                 if kk in wire}
        self.kvtier.put(("radix",) + tuple(path), entry)
        _tracer.instant("kvtier/demote", cat="serve", block=int(block),
                        depth=len(path))

    def _promote_extend(self, prompt0, matched: List[int], *,
                        rid=None) -> List[int]:
        """Extend a radix-matched head with consecutive host-tier
        blocks: each surviving continuation block is adopted back into
        HBM (over the 32 MB chunked transfer), registered in the trie,
        and appended to the match — the admission then prefills only
        past the combined prefix.  Best-effort: pool pressure or a
        tier miss just returns the match as-is."""
        t = prompt0.shape[0]
        B = self.block_len
        cap = max(0, (t - 1) // B)
        m = len(matched)
        if m >= cap or self.radix is None:
            return matched
        from bigdl_tpu.serving.kvtier.store import block_path
        keys = block_path(prompt0, B, cap)
        payloads = []
        for i in range(m, cap):
            p = self.kvtier.get(("radix",) + keys[:i + 1])
            if p is None:
                break
            payloads.append(p)
        if not payloads:
            return matched
        quant = self.kv_quant is not None
        L, _, H, Bl, D = self.pool.shape
        if (payloads[0]["k"].shape[1:] != (L, H, Bl, D)
                or (quant and "ks" not in payloads[0])):
            # stale entries from a different geometry/precision under
            # the same store name: not promotable into this pool
            return matched
        k = np.concatenate([p["k"] for p in payloads], axis=0)
        v = np.concatenate([p["v"] for p in payloads], axis=0)
        ks = (np.concatenate([p["ks"] for p in payloads], axis=0)
              if quant else None)
        vs = (np.concatenate([p["vs"] for p in payloads], axis=0)
              if quant else None)
        nbytes = k.nbytes + v.nbytes
        if quant:
            nbytes += ks.nbytes + vs.nbytes
        rid_args = {"request_id": rid} if _tracer.sampled(rid) else {}
        t0 = time.perf_counter()
        with _tracer.span("kvtier/promote", cat="serve",
                          blocks=len(payloads), bytes=int(nbytes),
                          **rid_args):
            try:
                fresh = self.pool.adopt_chain(
                    k, v, ks, vs, extra_blocks=0,
                    device=self.pool.k.sharding)
            except PoolExhausted:
                # promotion is opportunistic — never deepen the very
                # pressure it is trying to relieve
                return matched
        self.kvtier.record_promote(nbytes, time.perf_counter() - t0)
        n_total = m + len(fresh)
        out = list(matched) + fresh
        # trie registration: future admissions share the promoted
        # blocks straight from HBM, and the trie's reference keeps
        # them demotable again once every stream lets go
        self.radix.insert(prompt0[:n_total * B], out)
        with self.radix._lock:
            # promoted blocks save suffix prefill exactly like a trie
            # hit — fold them into the same saved-tokens ledger
            self.radix.matched_tokens += len(fresh) * B
        return out

    def attach_radix_summary(self, summary) -> None:
        """Publish this engine's radix trie to the serving router: the
        summary mirrors the trie's prefix fingerprints (refreshed by
        the per-node insert/evict hooks, O(1) each), so a router can
        score this replica's cache affinity without ever touching the
        trie.  See :mod:`bigdl_tpu.serving.router.summary`."""
        if self.radix is None:
            raise ValueError(
                "attach_radix_summary requires enable_prefix_cache=True")
        self.radix.attach_summary(summary)
        self.radix_summary = summary

    def hibernate(self, stream: LMStream, *,
                  timeout: Optional[float] = 30.0) -> bool:
        """Swap an idle stream out of its decode slot: its written KV
        chain moves to the host tier (``("session", rid)``), its slot
        and every HBM block free, and its full sampling state is kept
        so :meth:`resume` continues the stream bit-exactly on the next
        token.  Blocks until the worker performs the swap (it owns the
        slots).  Returns True once hibernated; False when the stream
        is not currently seated in a decode slot (queued, mid-prefill,
        mid-replay, or already finished)."""
        if self.kvtier is None:
            raise ValueError(
                "hibernate requires a kvtier (HostBlockStore)")
        rid = stream.request_id
        with self._cv:
            if rid in self._hibernated:
                return True
            seated = any(st is not None and st.rid == rid
                         and not st.replay for st in self._slots)
            if not seated or stream.done():
                return False
            self._hibernate_req.add(rid)
            self._cv.notify_all()
            self._cv.wait_for(lambda: rid not in self._hibernate_req,
                              timeout)
            self._hibernate_req.discard(rid)
            return rid in self._hibernated

    def resume(self, stream: LMStream) -> bool:
        """Re-admit a hibernated stream: its chain promotes back into
        HBM through the chunked transfer (or, if the tier dropped the
        payload, the prompt re-prefills and the generated tokens
        replay through the decode path — bit-exact either way) and
        decode continues at the exact token it left off.  Resumes
        rank with adoptions, ahead of fresh admissions.  Returns False
        when the stream is not hibernated."""
        rid = stream.request_id
        with self._cv:
            if self._closing:
                raise ServingClosed("LMServingEngine is closed")
            hib = self._hibernated.pop(rid, None)
            if hib is None:
                return False
            self._resume_q.append(hib)
            self._cv.notify_all()
        if _tracer.sampled(rid):
            _tracer.instant("lm/resume_enqueue", cat="serve",
                            request_id=rid,
                            hibernated_s=round(
                                time.perf_counter() - hib.hibernated_at,
                                4))
        return True

    def _service_hibernations(self) -> None:
        """Worker-side swap-out: export each requested seated slot's
        written blocks to the host tier, release the chain, free the
        slot.  Requests for streams no longer seated are discarded so
        their waiters unblock."""
        with self._cv:
            todo = [(i, st) for i, st in enumerate(self._slots)
                    if st is not None and st.rid in self._hibernate_req
                    and not st.replay]
            stale = self._hibernate_req - {st.rid for _, st in todo}
            if stale:
                self._hibernate_req -= stale
                self._cv.notify_all()
        for i, st in todo:
            self._hibernate_one(i, st)

    def _hibernate_one(self, slot: int, st: _Slot) -> None:
        n_used = self.pool.blocks_for(st.pos_next)
        rid_args = ({"request_id": st.rid}
                    if _tracer.sampled(st.rid) else {})
        with _tracer.span("kvtier/hibernate", cat="serve", slot=slot,
                          blocks=n_used, **rid_args):
            wire = self.pool.export_chain(st.blocks[:n_used])
            entry = {kk: wire[kk] for kk in ("k", "v", "ks", "vs")
                     if kk in wire}
            self.kvtier.put(("session", st.rid), entry)
        hib = _Hibernated(st, n_used)
        self.pool.release(st.blocks)
        if self.draft is not None:
            # the drafter's dense per-slot cache does not hibernate;
            # the resumed stream rides plain decode (still bit-exact)
            self.draft.release(slot)
        with self._cv:
            self._slots[slot] = None
            self._free.append(slot)
            self._n_active -= 1
            self._hibernate_req.discard(st.rid)
            self._hibernated[st.rid] = hib
            self.hibernations += 1
            self._cv.notify_all()

    def _resume_into(self, slot: int, hib: _Hibernated) -> bool:
        """Seat a hibernated stream back into ``slot``.  Returns False
        (defer) under pool pressure — a popped payload stays cached on
        the handle across deferrals, never re-read or lost."""
        stream = hib.stream
        t = int(stream.prompt.shape[0])
        prompt0 = (stream.prompt.astype(np.int32) - 1)
        max_new = int(stream.max_new)
        need_total = self.pool.blocks_for(t + max_new)
        B = self.block_len
        rid_args = ({"request_id": hib.rid}
                    if _tracer.sampled(hib.rid) else {})
        req = _Request(stream, prompt0, max_new, hib.temperature,
                       hib.eos0, None, hib.step_keys, hib.rid)
        if not hib.fetched:
            hib.payload = self.kvtier.get(("session", hib.rid), pop=True)
            hib.fetched = True
        if hib.payload is not None:
            payload = hib.payload
            n_wire = int(payload["k"].shape[0])
            extra = need_total - n_wire
            nbytes = sum(int(payload[x].nbytes) for x in payload)
            t0 = time.perf_counter()
            with _tracer.span("kvtier/promote", cat="serve",
                              blocks=n_wire, bytes=int(nbytes),
                              session=1, **rid_args):
                try:
                    fresh = self.pool.adopt_chain(
                        payload["k"], payload["v"],
                        payload.get("ks"), payload.get("vs"),
                        extra_blocks=extra,
                        device=self.pool.k.sharding)
                except PoolExhausted:
                    if self.radix is not None:
                        self.radix.evict(n_wire + extra
                                         - self.pool.free_count)
                    try:
                        fresh = self.pool.adopt_chain(
                            payload["k"], payload["v"],
                            payload.get("ks"), payload.get("vs"),
                            extra_blocks=extra,
                            device=self.pool.k.sharding)
                    except PoolExhausted:
                        return False
            self.kvtier.record_promote(nbytes, time.perf_counter() - t0)
            blocks = fresh
            if self.radix is not None:
                nfull = t // B
                if nfull:
                    self.radix.insert(prompt0[:nfull * B],
                                      blocks[:nfull])
            self._seat_resumed(req, hib, blocks, slot,
                               pos_next=hib.pos_next, last0=hib.last0,
                               remaining=hib.remaining,
                               step_idx=hib.step_idx, replay=())
            _tracer.instant("lm/resume", cat="serve", slot=slot,
                            wire_blocks=n_wire, **rid_args)
            return True
        # payload lost (capacity-dropped or corrupt spill): rebuild.
        # Prompt KV recomputes through the same deterministic prefill
        # admission ran; the generated tokens' KV rebuilds by REPLAYING
        # them through the decode path that wrote the originals — both
        # legs bit-identical, no token is ever re-emitted.
        emitted0 = np.asarray(stream.generated, np.int32) - 1
        matched: List[int] = []
        if self.radix is not None:
            matched = self.radix.match(prompt0)
            matched = self._promote_extend(prompt0, matched,
                                           rid=hib.rid)
        n_new = need_total - len(matched)
        try:
            fresh = self.pool.alloc(n_new)
        except PoolExhausted:
            if self.radix is not None:
                self.radix.evict(n_new - self.pool.free_count)
            try:
                fresh = self.pool.alloc(n_new)
            except PoolExhausted:
                if matched:
                    self.pool.release(matched)
                return False
        blocks = matched + fresh
        self.resume_re_prefills += 1
        pf = _Prefill(req, blocks, slot, len(matched) * B)
        try:
            while not self._prefill_chunk(pf):
                pass
        except BaseException:
            self.pool.release(blocks)
            raise
        if self.radix is not None:
            nfull = t // B
            if nfull:
                self.radix.insert(prompt0[:nfull * B], blocks[:nfull])
        self._seat_resumed(req, hib, blocks, slot, pos_next=t,
                           last0=int(emitted0[0]),
                           remaining=max_new - 1, step_idx=0,
                           replay=tuple(int(x) for x in emitted0[1:]))
        _tracer.instant("lm/resume", cat="serve", slot=slot,
                        re_prefill=1, replay=len(emitted0) - 1,
                        **rid_args)
        return True

    def _seat_resumed(self, req: _Request, hib: _Hibernated,
                      blocks: List[int], slot: int, *, pos_next: int,
                      last0: int, remaining: int, step_idx: int,
                      replay) -> None:
        table = np.zeros((self.table_width,), np.int32)
        table[:len(blocks)] = blocks
        st = _Slot(req, pos_next, last0, blocks, table)
        st.remaining = int(remaining)
        st.step_idx = int(step_idx)
        st.replay = deque(replay)
        # resumed streams ride plain decode (draft_ok stays False) and
        # interrupt the ITL stream the way an adoption does
        self._prefill_since_step = True
        with self._cv:
            self._slots[slot] = st
            self._n_active += 1
            self.resumes += 1

    @staticmethod
    def _trace_done(stream: LMStream, rid: Optional[str]) -> None:
        """Retroactive per-request ROOT span (submit -> finish) — the
        natural parent every lm/* event of the request nests under in
        ``Tracer.span_tree``.  Recorded at completion because only then
        is the request's full extent known."""
        if not _tracer.sampled(rid):
            return
        end = stream.finished_at
        if end is None:
            end = time.perf_counter()
        _tracer.add_complete(
            "lm/request", stream.submitted_at,
            end - stream.submitted_at, cat="serve",
            args={"request_id": rid, "prompt_len": int(len(stream.prompt)),
                  "max_new": stream.max_new,
                  "emitted": len(stream._tokens)})

    def _prefill_into(self, req: _Request, blocks: List[int], slot: int,
                      matched_len: int,
                      handoff: Optional[KVHandoff] = None) -> None:
        """Run-to-completion prefill (the non-interleaved path): every
        chunk back-to-back, then finish."""
        pf = _Prefill(req, blocks, slot, matched_len, handoff)
        while not self._prefill_chunk(pf):
            pass
        self._finish_prefill(pf)

    def _prefill_chunk(self, pf: _Prefill) -> bool:
        """One bucketed prefill pass + block scatter; True when the
        whole prompt is in the arena.  Chunk sizes stay block-aligned
        (except the final remainder) so the suffix path's prefix_len is
        always a whole number of blocks; ``max_prefill_chunk_tokens``
        only lowers the per-chunk ceiling."""
        req, blocks, t = pf.req, pf.blocks, pf.t
        B = self.block_len
        largest = self.prefill_buckets[-1]
        cap = self._chunk_cap
        largest_eff = largest if cap is None else min(largest, cap)
        chunk_full = (self._chunk_full if cap is None
                      else min(self._chunk_full, cap))
        p = pf.p
        rid_args = ({"request_id": req.rid}
                    if _tracer.sampled(req.rid) else {})
        rem = t - p
        ts = rem if rem <= largest_eff else chunk_full
        bucket = self.bucket_for(ts)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :ts] = req.prompt0[p:p + ts]
        with _tracer.span("lm/prefill", cat="serve", bucket=bucket,
                          prompt_len=t, prefix_len=p, **rid_args):
            if p == 0:
                logits, k, v = self.prefill_cache(
                    self._params, self._buffers,
                    {"ids": ids, "len": np.int32(ts)})
            else:
                nbp = p // B
                pb = self._prefix_bucket_for(nbp)
                pblocks = np.zeros((pb,), np.int32)
                pblocks[:nbp] = blocks[:nbp]
                x = {"ids": ids, "len": np.int32(ts),
                     "prefix_len": np.int32(p), "blocks": pblocks,
                     "k": self.pool.k, "v": self.pool.v}
                if self.kv_quant is not None:
                    x["ks"], x["vs"] = self.pool.ks, self.pool.vs
                logits, k, v = self.prefix_prefill_cache(
                    self._params, self._buffers, x)
        # scatter the chunk's k/v into its (block-aligned) blocks;
        # bucket-padding rows land in trailing owned blocks or the
        # scratch block, always masked until overwritten
        nb_w = -(-bucket // B)
        ids_w = np.zeros((nb_w,), np.int32)
        owned = blocks[p // B:p // B + nb_w]
        ids_w[:len(owned)] = owned
        with _tracer.span("lm/insert", cat="serve", slot=pf.slot,
                          bucket=bucket, **rid_args):
            if self.kv_quant is not None:
                (self.pool.k, self.pool.v, self.pool.ks,
                 self.pool.vs) = self._insert_compiled(bucket)(
                    self.pool.k, self.pool.v, k, v, ids_w,
                    self.pool.ks, self.pool.vs)
            else:
                self.pool.k, self.pool.v = self._insert_compiled(bucket)(
                    self.pool.k, self.pool.v, k, v, ids_w)
        self._prefill_since_step = True
        pf.logits = logits
        pf.p = p + ts
        return pf.p >= t

    def _finish_prefill(self, pf: _Prefill) -> None:
        req, blocks, slot, t = pf.req, pf.blocks, pf.slot, pf.t
        B = self.block_len
        # cache the prompt's full blocks for future prefix hits (the
        # matched head is already in the trie; only novel tails add)
        if self.radix is not None:
            nfull = t // B
            if nfull:
                self.radix.insert(req.prompt0[:nfull * B], blocks[:nfull])
        if pf.handoff is not None:
            # re-prefill of a migrated request whose wire payload was
            # lost: the first token was already emitted on the prefill
            # replica — recompute the KV rows, discard the logits, and
            # seat decode exactly where the handoff says it stands
            self._seat(req, t, pf.handoff.first0, blocks, slot)
            return
        logits = np.asarray(pf.logits)  # sync; (1, V) f32
        first0 = self._pick(logits[0], req.temperature, req.first_key,
                            clamp=False)
        req.stream._emit(first0 + 1)
        self.metrics.record_first_token(
            req.stream.first_token_at - req.stream.submitted_at)
        if req.max_new == 1 or (req.eos0 is not None
                                and first0 == req.eos0):
            req.stream._finish()
            self.metrics.record_complete()
            self._trace_done(req.stream, req.rid)
            self.pool.release(blocks)
            with self._cv:
                self._free.append(slot)
            return
        if self.migrate is not None:
            # prefill-phase replica: the chain + sampling state hop to
            # a decode replica; this engine's slot and blocks free as
            # soon as the coordinator is done with them (the callback
            # runs with our references still held)
            h = KVHandoff(req, first0, self.name)
            try:
                with _tracer.span("lm/migrate", cat="serve",
                                  prompt_len=t,
                                  **({"request_id": req.rid}
                                     if _tracer.sampled(req.rid) else {})):
                    self.migrate(h, blocks, self.pool)
                self.migrated += 1
            except BaseException as e:  # noqa: BLE001
                req.stream._finish(error=e)
                self._trace_done(req.stream, req.rid)
            finally:
                self.pool.release(blocks)
                with self._cv:
                    self._free.append(slot)
            return
        self._seat(req, t, first0, blocks, slot)

    def _seat(self, req: _Request, t: int, first0: int,
              blocks: List[int], slot: int) -> None:
        table = np.zeros((self.table_width,), np.int32)
        table[:len(blocks)] = blocks
        st = _Slot(req, t, first0, blocks, table)
        if self.draft is not None:
            # drafter admission: full-prompt prefill into its dense
            # per-slot cache, first emitted token queued as pending.
            # Over-length (chunk-admitted) prompts serve plain decode.
            st.draft_ok = self.draft.can_draft(t)
            if st.draft_ok:
                self.draft.admit(slot, req.prompt0)
                self.draft.push(slot, first0)
                if self.spec.tree:
                    st.tree_rung = self.spec.init_rung
        with self._cv:
            self._slots[slot] = st
            self._n_active += 1

    def _step(self):
        token = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        tables = np.zeros((self.slots, self.table_width), np.int32)
        active = []
        for i, st in enumerate(self._slots):
            if st is not None:
                active.append((i, st))
                token[i] = st.last0
                pos[i] = st.pos_next
                tables[i] = st.table
        if not active:
            return
        t0 = time.perf_counter()
        with _tracer.span("lm/decode_step", cat="serve",
                          active=len(active)):
            if self.kv_quant is not None:
                (logits, self.pool.k, self.pool.v, self.pool.ks,
                 self.pool.vs) = self._decode_compiled()(
                    self._params, token, pos, tables, self.pool.k,
                    self.pool.v, self.pool.ks, self.pool.vs)
            else:
                logits, self.pool.k, self.pool.v = self._decode_compiled()(
                    self._params, token, pos, tables, self.pool.k,
                    self.pool.v)
            logits = np.asarray(logits)  # sync; (S, V) f32
        now = time.perf_counter()
        if _tracer.enabled:
            # per-request view of the shared batched step: one
            # retroactive span per sampled slot, all spanning [t0, now]
            for i, st in active:
                if _tracer.sampled(st.rid):
                    _tracer.add_complete(
                        "lm/decode_round", t0, now - t0, cat="serve",
                        args={"request_id": st.rid, "slot": i,
                              "step": st.step_idx})
        itls = []
        freed = []
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            if st.replay:
                # payload-less resume: this step just rebuilt last0's
                # KV row; the next token was already emitted before
                # hibernation — take it from the replay queue instead
                # of the logits (no re-emit, no ITL sample).  The
                # queue preserves the original step_keys alignment, so
                # post-replay sampling is bit-exact.
                st.last0 = st.replay.popleft()
                st.pos_next += 1
                st.step_idx += 1
                st.remaining -= 1
                continue
            nxt0 = self._pick(
                logits[i], st.temperature,
                st.step_keys[st.step_idx]
                if st.step_keys is not None else None,
                clamp=True)
            st.stream._emit(nxt0 + 1)
            itls.append(now - st.last_emit_at)
            st.last_emit_at = now
            st.last0 = nxt0
            st.pos_next += 1
            st.step_idx += 1
            st.remaining -= 1
            if st.remaining <= 0 or (st.eos0 is not None
                                     and nxt0 == st.eos0):
                st.stream._finish()
                self.metrics.record_complete()
                freed.append(i)
        self.metrics.record_step(len(active), itls,
                                 prefill_interrupted=self._prefill_since_step)
        self._prefill_since_step = False
        if freed:
            with self._cv:
                for i in freed:
                    st = self._slots[i]
                    self._trace_done(st.stream, st.rid)
                    self.pool.release(st.blocks)
                    self._slots[i] = None
                    self._free.append(i)
                    self._n_active -= 1
                self._cv.notify_all()

    def _step_spec(self):
        """One speculative round: draft k tokens per eligible slot, run
        the SINGLE fixed-shape verify executable over all k+1 candidate
        rows per slot, then walk each slot's rows host-side emitting
        the accepted prefix plus one bonus/correction token — the exact
        offline trajectory under "replay" acceptance.  Rejection is a
        pointer rewind: the slot simply doesn't advance past the last
        emitted position, and the arena rows above it stay masked until
        overwritten.  Demoted / chunk-admitted / budget-exhausted slots
        ride the same round as plain n_cand=1 rows."""
        from bigdl_tpu.resilience.faults import fault_point
        from bigdl_tpu.serving.spec.verify import accept_row

        cfg = self.spec
        if cfg.tree:
            return self._step_spec_tree()
        mode = cfg.sampling
        # -- choose who speculates this round --------------------------- #
        jobs = {}
        for i, st in enumerate(self._slots):
            if st is None or not st.draft_ok:
                continue
            if st.demoted:
                st.probe_in -= 1
                if st.probe_in > 0:
                    continue
                # re-probe: forget the collapsed EMA and try again
                st.demoted = False
                st.accept_ema = None
                st.spec_rounds = 0
                self.spec_metrics.record_reprobe()
            # never draft past the budget: the round emits at most
            # k_eff + 1 tokens, and every verify write must stay inside
            # the chain allocated for prompt + max_new at admission
            k_eff = min(cfg.k, st.remaining - 1)
            if k_eff < 1:
                continue
            keys = None
            if st.temperature > 0.0 and st.step_keys is not None:
                keys = st.step_keys[st.step_idx:st.step_idx + k_eff]
            jobs[i] = (k_eff, st.temperature, keys)
        steps_before = self.draft.steps
        drafts = self.draft.draft_round(jobs)

        # chaos hook on the verify step: an injected transient demotes
        # every speculating slot to plain decode for this round (their
        # drafts are discarded, the drafter pointer rewinds) instead of
        # killing streams; backend_lost/die keep their fatal meaning
        try:
            fault_point("serving.verify", name=self.name,
                        k=cfg.k, speculating=len(jobs))
        except TransientBackendError:
            for i in jobs:
                st = self._slots[i]
                self.draft.commit(i, 0, [])
                st.demoted = True
                st.probe_in = cfg.probe_interval
                self.spec_metrics.record_demotion(fault=True)
                if _tracer.sampled(st.rid):
                    _tracer.instant("lm/demote", cat="serve",
                                    request_id=st.rid, slot=i,
                                    reason="verify_fault")
            drafts = {}
            jobs = {}

        # -- one fixed-shape verify over every active slot -------------- #
        w = cfg.k + 1
        tokens = np.zeros((self.slots, w), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        ncand = np.zeros((self.slots,), np.int32)
        tables = np.zeros((self.slots, self.table_width), np.int32)
        active = []
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            active.append(i)
            ds, _, _ = drafts.get(i, ((), None, ()))
            tokens[i, 0] = st.last0
            for j, d in enumerate(ds):
                tokens[i, 1 + j] = d
            ncand[i] = 1 + len(ds)
            pos[i] = st.pos_next
            tables[i] = st.table
        if not active:
            return
        t0 = time.perf_counter()
        with _tracer.span("lm/verify_step", cat="serve",
                          active=len(active), speculating=len(jobs)):
            if self.kv_quant is not None:
                (logits, self.pool.k, self.pool.v, self.pool.ks,
                 self.pool.vs) = self._verify_compiled()(
                    self._params, tokens, pos, ncand, tables,
                    self.pool.k, self.pool.v, self.pool.ks, self.pool.vs)
            else:
                logits, self.pool.k, self.pool.v = self._verify_compiled()(
                    self._params, tokens, pos, ncand, tables,
                    self.pool.k, self.pool.v)
            logits = np.asarray(logits)  # sync; (S, W, V) f32
        now = time.perf_counter()
        if _tracer.enabled:
            for i in active:
                st = self._slots[i]
                if _tracer.sampled(st.rid):
                    _tracer.add_complete(
                        "lm/verify_round", t0, now - t0, cat="serve",
                        args={"request_id": st.rid, "slot": i,
                              "step": st.step_idx,
                              "speculating": i in jobs})
        itls = []
        freed = []
        n_emitted = 0
        for i in active:
            st = self._slots[i]
            if st.replay:
                # payload-less resume riding a spec round as a plain
                # n_cand=1 row: the verify kernel rebuilt last0's KV;
                # the next token replays instead of sampling (resumed
                # slots have draft_ok=False, so no draft state exists)
                st.last0 = st.replay.popleft()
                st.pos_next += 1
                st.step_idx += 1
                st.remaining -= 1
                continue
            ds, qrows, _ = drafts.get(i, ((), None, ()))
            k_eff = len(ds)
            emitted = []
            accepted = 0
            finished = False
            for j in range(k_eff + 1):
                key = (st.step_keys[st.step_idx]
                       if st.step_keys is not None else None)
                e = accept_row(logits[i, j],
                               ds[j] if j < k_eff else None,
                               st.temperature, key, mode,
                               qrows[j] if qrows is not None
                               and j < k_eff else None)
                emitted.append(e)
                st.stream._emit(e + 1)
                itls.append(now - st.last_emit_at)
                st.last_emit_at = now
                st.last0 = e
                st.pos_next += 1
                st.step_idx += 1
                st.remaining -= 1
                if st.remaining <= 0 or (st.eos0 is not None
                                         and e == st.eos0):
                    finished = True
                    break
                if j >= k_eff or ds[j] != e:
                    break
                accepted += 1
            n_emitted += len(emitted)
            if k_eff:
                self.spec_metrics.record_round(k_eff, accepted)
                rate = accepted / k_eff
                st.accept_ema = (rate if st.accept_ema is None
                                 else cfg.ema_alpha * rate
                                 + (1.0 - cfg.ema_alpha) * st.accept_ema)
                st.spec_rounds += 1
                if (not finished and st.spec_rounds >= cfg.min_rounds
                        and st.accept_ema < cfg.demote_below):
                    st.demoted = True
                    st.probe_in = cfg.probe_interval
                    self.spec_metrics.record_demotion()
                    if _tracer.sampled(st.rid):
                        _tracer.instant("lm/demote", cat="serve",
                                        request_id=st.rid, slot=i,
                                        reason="acceptance_collapse",
                                        accept_ema=round(st.accept_ema, 4))
            if finished:
                st.stream._finish()
                self.metrics.record_complete()
                freed.append(i)
            elif st.draft_ok:
                if k_eff:
                    self.draft.commit(i, accepted, emitted)
                else:
                    self.draft.push(i, emitted[0])
        self.spec_metrics.record_verify_round(
            bool(jobs), n_emitted, self.draft.steps - steps_before)
        self.metrics.record_step(len(active), itls,
                                 prefill_interrupted=self._prefill_since_step)
        self._prefill_since_step = False
        if freed:
            with self._cv:
                for i in freed:
                    st = self._slots[i]
                    self._trace_done(st.stream, st.rid)
                    self.pool.release(st.blocks)
                    self._slots[i] = None
                    if self.draft is not None:
                        self.draft.release(i)
                    self._free.append(i)
                    self._n_active -= 1
                self._cv.notify_all()

    def _step_spec_tree(self):
        """One TREE-speculative round (replay acceptance only): each
        eligible slot picks a ladder rung (its adaptive ``tree_rung``,
        clamped down so the shape fits its remaining budget), the
        drafter proposes the spine plus ranked runner-up alternates at
        zero extra steps, and ONE pre-lowered verify executable — the
        round's widest participating rung, narrower slots truncated via
        ``n_cand`` — scores every node against the paged arenas.  The
        host then walks each slot's tree root-down, emitting the offline
        ``pick_token`` draw at every accepted node, so the stream is the
        exact offline trajectory whichever branch carried it.  A slot
        that accepted an ALTERNATE has that node's k/v committed down to
        its position offset afterwards (``_tree_commit_paged``, skipped
        entirely on spine-only rounds); rejected rows stay as masked
        garbage above the rewound pointer, same as linear verify.

        The acceptance EMA drives three nested responses: rung
        promotion at ``promote_above`` (speculate deeper/wider), rung
        step-down at ``stepdown_below``, and full demotion to plain
        decode below ``demote_below`` with the same re-probe lifecycle
        as linear mode — a re-probed slot restarts at ``init_rung``."""
        from bigdl_tpu.resilience.faults import fault_point
        from bigdl_tpu.serving.spec.verify import pick_token

        cfg = self.spec
        shapes = self._tree_shapes
        top = len(shapes) - 1
        # -- choose who speculates, and at which rung ------------------- #
        jobs: dict = {}
        for i, st in enumerate(self._slots):
            if st is None or not st.draft_ok:
                continue
            if st.demoted:
                st.probe_in -= 1
                if st.probe_in > 0:
                    continue
                # re-probe: forget the collapsed EMA, restart the ladder
                st.demoted = False
                st.accept_ema = None
                st.spec_rounds = 0
                st.tree_rung = cfg.init_rung
                self.spec_metrics.record_reprobe()
            # budget clamp: the shape stores nodes at pos .. pos+W-1 and
            # emits at most max_depth+1 <= W tokens, so W <= remaining
            # keeps every write and every emission inside the chain
            # allocated at admission
            rung = min(st.tree_rung, top)
            while rung >= 0 and shapes[rung].width > st.remaining:
                rung -= 1
            if rung < 0:
                continue        # remaining == 1: ride as a plain row
            jobs[i] = rung
        djobs = {}
        for i, rung in jobs.items():
            st = self._slots[i]
            shp = shapes[rung]
            keys = None
            if st.temperature > 0.0 and st.step_keys is not None:
                keys = st.step_keys[st.step_idx:st.step_idx + shp.spine]
            djobs[i] = (shp.spine, st.temperature, keys, shp.alt_counts)
        steps_before = self.draft.steps
        drafts = self.draft.draft_round(djobs)

        # same chaos site as linear verify — tree rounds demote
        # identically: drafts discarded, round served plain, streams
        # stay bit-exact
        try:
            fault_point("serving.verify", name=self.name,
                        k=cfg.k, speculating=len(jobs), tree=True)
        except TransientBackendError:
            for i in jobs:
                st = self._slots[i]
                self.draft.commit(i, 0, [])
                st.demoted = True
                st.probe_in = cfg.probe_interval
                self.spec_metrics.record_demotion(fault=True)
                if _tracer.sampled(st.rid):
                    _tracer.instant("lm/demote", cat="serve",
                                    request_id=st.rid, slot=i,
                                    reason="verify_fault")
            drafts = {}
            jobs = {}

        # -- one verify at the round's widest rung ---------------------- #
        round_rung = max(jobs.values(), default=0)
        shp_round = shapes[round_rung]
        w = shp_round.width
        tokens = np.zeros((self.slots, w), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        ncand = np.zeros((self.slots,), np.int32)
        tables = np.zeros((self.slots, self.table_width), np.int32)
        active = []
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            active.append(i)
            tokens[i, 0] = st.last0
            pos[i] = st.pos_next
            tables[i] = st.table
            if i in jobs:
                shp = shapes[jobs[i]]
                ds, _, alts = drafts[i]
                for j in range(1, shp.width):
                    p = shp.parents[j]
                    if j <= shp.spine:
                        tokens[i, j] = ds[j - 1]
                    else:
                        ranked = alts[p] if p < len(alts) else ()
                        r = shp.alt_rank[j]
                        # an unfillable alternate keeps token 0: under
                        # replay it accepts only if 0 IS the offline
                        # emission, which is a legitimate accept
                        if r < len(ranked):
                            tokens[i, j] = ranked[r]
                ncand[i] = shp.width
            else:
                ncand[i] = 1
        if not active:
            return
        t0 = time.perf_counter()
        with _tracer.span("lm/verify_step", cat="serve",
                          active=len(active), speculating=len(jobs),
                          tree_w=w):
            if self.kv_quant is not None:
                (logits, self.pool.k, self.pool.v, self.pool.ks,
                 self.pool.vs) = self._verify_tree_compiled(round_rung)(
                    self._params, tokens, pos, ncand, tables,
                    self.pool.k, self.pool.v, self.pool.ks, self.pool.vs)
            else:
                (logits, self.pool.k,
                 self.pool.v) = self._verify_tree_compiled(round_rung)(
                    self._params, tokens, pos, ncand, tables,
                    self.pool.k, self.pool.v)
            logits = np.asarray(logits)  # sync; (S, W, V) f32
        now = time.perf_counter()
        if _tracer.enabled:
            for i in active:
                st = self._slots[i]
                if _tracer.sampled(st.rid):
                    _tracer.add_complete(
                        "lm/verify_round", t0, now - t0, cat="serve",
                        args={"request_id": st.rid, "slot": i,
                              "step": st.step_idx,
                              "speculating": i in jobs})
        itls = []
        freed = []
        n_emitted = 0
        commit_src = None     # lazily built: only alternate accepts move
        for i in active:
            st = self._slots[i]
            if st.replay:
                # payload-less resume riding the round as a plain row
                st.last0 = st.replay.popleft()
                st.pos_next += 1
                st.step_idx += 1
                st.remaining -= 1
                continue
            shp = shapes[jobs[i]] if i in jobs else None
            emitted = []
            node = 0
            accepted = 0
            spine_ok = 0
            alt_ok = 0
            finished = False
            while True:
                key = (st.step_keys[st.step_idx]
                       if st.step_keys is not None else None)
                e = pick_token(logits[i, node], st.temperature, key,
                               clamp=True)
                emitted.append(e)
                st.stream._emit(e + 1)
                itls.append(now - st.last_emit_at)
                st.last_emit_at = now
                st.last0 = e
                st.pos_next += 1
                st.step_idx += 1
                st.remaining -= 1
                if st.remaining <= 0 or (st.eos0 is not None
                                         and e == st.eos0):
                    finished = True
                    break
                nxt = None
                if shp is not None:
                    for c in shp.children[node]:
                        if int(tokens[i, c]) == e:
                            nxt = c
                            break
                if nxt is None:
                    break
                accepted += 1
                if nxt <= shp.spine:
                    spine_ok += 1
                else:
                    # the accepted path left the spine: schedule this
                    # node's k/v copy-down (alternates are leaves, so at
                    # most one move per slot per round)
                    alt_ok += 1
                    if commit_src is None:
                        commit_src = np.tile(
                            np.arange(1, self._commit_dmax + 1,
                                      dtype=np.int32),
                            (self.slots, 1))
                    commit_src[i, accepted - 1] = nxt
                node = nxt
            n_emitted += len(emitted)
            if shp is not None:
                self.spec_metrics.record_round(shp.width - 1, accepted)
                self.spec_metrics.record_tree_slot(
                    shp.max_depth, shp.width, len(emitted), alt_ok)
                rate = accepted / shp.max_depth
                st.accept_ema = (rate if st.accept_ema is None
                                 else cfg.ema_alpha * rate
                                 + (1.0 - cfg.ema_alpha) * st.accept_ema)
                st.spec_rounds += 1
                if (not finished and st.spec_rounds >= cfg.min_rounds
                        and st.accept_ema < cfg.demote_below):
                    st.demoted = True
                    st.probe_in = cfg.probe_interval
                    self.spec_metrics.record_demotion()
                    if _tracer.sampled(st.rid):
                        _tracer.instant("lm/demote", cat="serve",
                                        request_id=st.rid, slot=i,
                                        reason="acceptance_collapse",
                                        accept_ema=round(st.accept_ema, 4))
                elif st.accept_ema >= cfg.promote_above:
                    st.tree_rung = min(st.tree_rung + 1, top)
                elif st.accept_ema < cfg.stepdown_below:
                    st.tree_rung = max(st.tree_rung - 1, 0)
            if finished:
                st.stream._finish()
                self.metrics.record_complete()
                freed.append(i)
            elif st.draft_ok:
                if shp is not None:
                    # the drafter's cache tracks only the SPINE: rewind
                    # past accepted spine drafts, catch up on the rest
                    self.draft.commit(i, spine_ok, emitted)
                else:
                    self.draft.push(i, emitted[0])
        if commit_src is not None:
            with _tracer.span("lm/tree_commit", cat="serve"):
                if self.kv_quant is not None:
                    (self.pool.k, self.pool.v, self.pool.ks,
                     self.pool.vs) = self._commit_compiled()(
                        commit_src, pos, tables,
                        self.pool.k, self.pool.v,
                        self.pool.ks, self.pool.vs)
                else:
                    self.pool.k, self.pool.v = self._commit_compiled()(
                        commit_src, pos, tables,
                        self.pool.k, self.pool.v)
        self.spec_metrics.record_verify_round(
            bool(jobs), n_emitted, self.draft.steps - steps_before)
        self.metrics.record_step(len(active), itls,
                                 prefill_interrupted=self._prefill_since_step)
        self._prefill_since_step = False
        if freed:
            with self._cv:
                for i in freed:
                    st = self._slots[i]
                    self._trace_done(st.stream, st.rid)
                    self.pool.release(st.blocks)
                    self._slots[i] = None
                    if self.draft is not None:
                        self.draft.release(i)
                    self._free.append(i)
                    self._n_active -= 1
                self._cv.notify_all()

    def _fail_all(self, error: BaseException) -> None:
        with self._cv:
            pending = [r.stream for r in self._queue]
            self._queue.clear()
            pending.extend(h.stream for h in self._adopt_q)
            for h in self._adopt_q:
                if h.matched:
                    self.pool.release(h.matched)
            self._adopt_q.clear()
            for pf in self._prefilling:
                pending.append(pf.req.stream)
                self.pool.release(pf.blocks)
                self._free.append(pf.slot)
            self._prefilling.clear()
            for i, st in enumerate(self._slots):
                if st is not None:
                    pending.append(st.stream)
                    self.pool.release(st.blocks)
                    self._slots[i] = None
                    self._free.append(i)
            self._n_active = 0
            if self.draft is not None:
                self.draft.release_all()
            # hibernated / resuming streams hold no pool blocks (their
            # chains live in the host tier), but their clients are
            # still waiting — resolve them too
            pending.extend(h.stream for h in self._hibernated.values())
            self._hibernated.clear()
            pending.extend(h.stream for h in self._resume_q)
            self._resume_q.clear()
            self._hibernate_req.clear()
            self._cv.notify_all()
        for s in pending:
            s._finish(error=error)

    # ------------------------------------------------------------------ #
    def kvcache_stats(self) -> dict:
        """Pool + radix state, for stats() and headroom checks."""
        out = self.pool.stats()
        out["table_width"] = self.table_width
        out["prefix_cache"] = (self.radix.stats()
                               if self.radix is not None else None)
        return out

    def kvcache_headroom(self) -> int:
        """How many additional WORST-CASE requests (a full
        ``cache_len`` context each) the pool can hold right now.  The
        SLO controller's scale-up check gates on this so added decode
        slots are backed by cache memory, not just scheduler entries."""
        return self.pool.free_count // self.table_width

    def stats(self) -> dict:
        with self._cv:
            queued = len(self._queue)
            active = self._n_active
            slot_limit = self._slot_limit
            max_queue = self._max_queue
            prefilling = len(self._prefilling)
            adopt_q = len(self._adopt_q)
            hibernated = len(self._hibernated)
        return {
            "name": self.name,
            "slots": self.slots,
            "slot_limit": slot_limit,
            "max_queue": max_queue,
            "active": active,
            "queued": queued,
            "phase": self.phase,
            "prefilling": prefilling,
            "adopt_queue": adopt_q,
            "max_prefill_chunk_tokens": self._chunk_cap,
            "migrated": self.migrated,
            "adopted": self.adopted,
            "re_prefills": self.re_prefills,
            "cache_len": self.cache_len,
            "block_len": self.block_len,
            "decode_attn": self.decode_attn,
            "placement": (self.placement.describe()
                          if self.placement is not None else None),
            "prefill_buckets": list(self.prefill_buckets),
            "prefill_cache": self.prefill_cache.stats(),
            "prefix_prefill_cache": self.prefix_prefill_cache.stats(),
            "kvcache": self.kvcache_stats(),
            "kvtier": (self.kvtier.stats()
                       if self.kvtier is not None else None),
            "radix_summary": (self.radix_summary.stats()
                              if self.radix_summary is not None else None),
            "hibernated": hibernated,
            "hibernations": self.hibernations,
            "resumes": self.resumes,
            "resume_re_prefills": self.resume_re_prefills,
            "honor_lifecycle": self.honor_lifecycle,
            "lifecycle": self.lifecycle_stats(),
            "metrics": self.metrics.snapshot(),
            "spec": self._spec_stats(),
        }

    def lifecycle_stats(self) -> dict:
        with self._lc_lock:
            return dict(self.lifecycle)

    def _spec_stats(self) -> Optional[dict]:
        if self.spec is None:
            return None
        with self._cv:
            demoted = sum(1 for s in self._slots
                          if s is not None and s.demoted)
        out = self.spec.describe()
        out["demoted_slots"] = demoted
        out["draft"] = self.draft.describe()
        out["verify_compiles"] = self._verify_compiles
        if self.spec.tree:
            out["commit_compiles"] = self._commit_compiles
            with self._cv:
                out["slot_rungs"] = [s.tree_rung if s is not None else None
                                     for s in self._slots]
        out.update(self.spec_metrics.snapshot())
        return out

    def cache_buffer_pointers(self) -> tuple:
        """Device buffer addresses of the resident k/v arenas (donation
        regression hook: stable across decode steps)."""

        def ptr(a):
            try:
                return a.unsafe_buffer_pointer()
            except AttributeError:
                bufs = getattr(a, "device_buffers", None)
                return bufs[0].unsafe_buffer_pointer() if bufs else None

        return ptr(self.pool.k), ptr(self.pool.v)

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Drain: stop admitting, finish queued + in-flight requests;
        after ``timeout`` the remainder resolve with ServingClosed."""
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        self._worker.join(timeout)
        if self._worker.is_alive():
            with self._cv:
                self._abort = True
                self._cv.notify_all()
            self._worker.join(5.0)
            self._fail_all(ServingClosed("engine closed before "
                                         "completion"))
        # drop this engine's memory-ledger attributions (the weakref
        # providers would go stale anyway; explicit release keeps the
        # table clean for the next engine)
        try:
            from bigdl_tpu.obs.ledger import get_ledger
            led = get_ledger()
            for sub, nm in getattr(self, "_ledger_keys", []):
                led.release(sub, nm)
        except Exception:
            pass

    def __enter__(self) -> "LMServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
