"""bigdl_tpu.serving.kvtier — tiered KV memory below the HBM arena.

The memory hierarchy for transformer KV state, in the Spark
BlockManager spill lineage: the HBM :class:`BlockPool` arena on top,
a capacity-bounded host-RAM :class:`HostBlockStore` under it, and an
optional disk spill directory at the bottom.  Radix-tail eviction
DEMOTES unreferenced prefix blocks down a tier instead of dropping
them; admission PROMOTES surviving prefixes back into HBM through the
32 MB chunked transfer discipline; and ``LMServingEngine.hibernate``
swaps an idle stream's whole chain out of its decode slot and resumes
it bit-exactly later.

Quickstart::

    from bigdl_tpu.serving import LMServingEngine
    from bigdl_tpu.serving.kvtier import HostBlockStore

    tier = HostBlockStore(host_bytes=256 << 20, spill_dir="/tmp/kv")
    eng = LMServingEngine(model, kvtier=tier)
    st = eng.submit(prompt)
    ...                       # read a few tokens
    eng.hibernate(st.stream)  # slot + HBM freed; chain in host tier
    eng.resume(st.stream)     # bit-exact continuation
"""
from bigdl_tpu.serving.kvtier.store import HostBlockStore, block_path

__all__ = ["HostBlockStore", "block_path"]
