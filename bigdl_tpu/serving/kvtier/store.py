"""Host-tier KV block store: the memory level below the HBM arena.

HBM bounds live sessions to whatever one :class:`BlockPool` arena
holds, but chat traffic is dominated by *idle* sessions whose prefixes
will return — and until now the radix cache simply dropped
unreferenced tails, so a returning session paid full re-prefill.
``HostBlockStore`` is the Spark BlockManager memory->disk spill
lineage mapped onto transformer KV state: evicted blocks DEMOTE into
host RAM (and optionally spill on to a disk directory) instead of
vanishing, and an admission whose prefix survived in any tier PROMOTES
it back into HBM through the 32 MB chunked transfer discipline.

The hierarchy:

    HBM arena (BlockPool)  —  hot: decoding + radix-shared prefixes
        | demote (radix on_evict / session hibernation)
        v
    host RAM (this store)  —  capacity-bounded, LRU within the tier
        | spill (host tier full, spill_dir configured)
        v
    disk (.npz per entry)  —  capacity-bounded; beyond it, drop

Entries are block-major wire payloads in the ``export_chain`` layout —
``{"k","v": (n, L, H, block_len, D)}`` plus ``"ks"/"vs"`` scale arrays
for int8 pools (a quantized block demotes WITH its per-(position,
head) scales, so the host tier is ~4x denser and a promoted block is
bit-identical to the demoted one).  Keys are arbitrary hashable tuples:
the radix demotion hook keys single blocks by their token-prefix path
(content-addressed — any future prompt sharing the prefix can find
them), session hibernation keys whole chains by request id.

Observability: hit/miss/demote/promote counters and per-tier byte
gauges publish into the process-wide metric registry under
``kvtier/<name>/``; every disk read verifies a CRC recorded at spill
time, and a corrupted or lost spill file raises a flight-recorder
incident and degrades to a miss — tiered memory must never feed a
stream wrong KV rows.

Thread model: the serving worker is the only writer on the hot path,
but stats/metrics read from other threads, so every mutation holds the
store lock.  Device work never happens here — the store moves host
numpy arrays only; staging back to HBM belongs to the pool's
``adopt_chain`` (which rides ``chunked_device_put``).
"""
from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, Optional, Tuple

log = logging.getLogger("bigdl_tpu.serving")

#: payload arrays every entry must carry; scale arrays are optional
#: (present exactly when the source pool is quantized)
_DATA_KEYS = ("k", "v")
_SCALE_KEYS = ("ks", "vs")


def _payload_bytes(payload: dict) -> int:
    return sum(int(payload[key].nbytes)
               for key in (*_DATA_KEYS, *_SCALE_KEYS) if key in payload)


class _Entry:
    __slots__ = ("payload", "nbytes", "where", "path", "crcs", "n_blocks")

    def __init__(self, payload: dict):
        self.payload = payload          # None while spilled to disk
        self.nbytes = _payload_bytes(payload)
        self.n_blocks = int(payload["k"].shape[0])
        self.where = "host"
        self.path: Optional[str] = None
        self.crcs: Optional[Dict[str, int]] = None


class HostBlockStore:
    """Capacity-bounded host-RAM KV tier with optional disk spill.

    Args:
        host_bytes: budget for payloads resident in host RAM.  When an
            insert would exceed it, LRU entries spill to disk (if
            ``spill_dir`` is set) or drop, oldest first.
        spill_dir: directory for the disk tier (created on demand).
            ``None`` disables spilling — host-tier overflow drops.
        disk_bytes: budget for the spill files; beyond it the oldest
            spilled entries are deleted.  Default: 4x ``host_bytes``.
        name: registry namespace — metrics land under
            ``kvtier/<name>/``.
    """

    def __init__(self, *, host_bytes: int, spill_dir: Optional[str] = None,
                 disk_bytes: Optional[int] = None, name: str = "default"):
        if host_bytes < 1:
            raise ValueError(f"host_bytes must be >= 1, got {host_bytes}")
        self.host_bytes = int(host_bytes)
        self.spill_dir = spill_dir
        self.disk_bytes = (int(disk_bytes) if disk_bytes is not None
                           else 4 * self.host_bytes)
        self.name = name
        self._lock = threading.Lock()
        # MRU at the end; one OrderedDict spans both tiers (an entry's
        # ``where`` says which) so LRU age is global, matching the
        # BlockManager's single LRU over memory+disk levels
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._host_used = 0
        self._disk_used = 0
        # counters (registry-published live objects)
        from bigdl_tpu.obs import get_registry
        from bigdl_tpu.obs.registry import Counter, FnGauge
        reg = get_registry()
        p = f"kvtier/{name}/"
        # private Counter objects registered with replace=True (the
        # LMMetrics idiom): a fresh store starts at zero even when an
        # earlier store used the same name in this process
        self.demotions = Counter()
        self.promotions = Counter()
        self.hits = Counter()
        self.misses = Counter()
        self.spills = Counter()
        self.drops = Counter()
        self.corrupt_reads = Counter()
        self.demoted_bytes = Counter(unit="bytes")
        self.promoted_bytes = Counter(unit="bytes")
        for cname in ("demotions", "promotions", "hits", "misses",
                      "spills", "drops", "corrupt_reads",
                      "demoted_bytes", "promoted_bytes"):
            reg.register(p + cname, getattr(self, cname), replace=True)
        reg.register(p + "host_bytes",
                     FnGauge(lambda: self._host_used), replace=True)
        reg.register(p + "disk_bytes",
                     FnGauge(lambda: self._disk_used), replace=True)
        reg.register(p + "entries",
                     FnGauge(lambda: len(self._entries)), replace=True)
        self._promote_s = 0.0    # cumulative promote host-read seconds
        # memory-ledger attribution: host-tier residency + the
        # cumulative promotion traffic the HBM side re-admitted
        try:
            import weakref

            from bigdl_tpu.obs.ledger import get_ledger
            led = get_ledger()
            ref = weakref.ref(self)

            def _host_resident():
                s = ref()
                return s._host_used if s is not None else None

            def _promoted():
                s = ref()
                return (int(s.promoted_bytes.get()[0])
                        if s is not None else None)

            led.register("kvtier", f"{name}/host_resident",
                         _host_resident, note="host RAM tier payloads")
            led.register("kvtier", f"{name}/promoted_bytes", _promoted,
                         note="cumulative tier->HBM promotion traffic")
        except Exception:
            pass

    # -- demotion (pool -> host tier) ----------------------------------- #
    def put(self, key: tuple, payload: dict) -> None:
        """Demote an exported payload into the host tier under ``key``
        (re-putting refreshes content and recency).  Oversized single
        payloads that exceed the whole host budget go straight to the
        disk tier (or drop) rather than flushing everything else."""
        import numpy as np
        for dk in _DATA_KEYS:
            if dk not in payload:
                raise ValueError(f"payload missing {dk!r}")
        has_scales = all(sk in payload for sk in _SCALE_KEYS)
        if any(sk in payload for sk in _SCALE_KEYS) and not has_scales:
            raise ValueError("payload carries one scale array but not "
                             "the other — scales demote atomically")
        clean = {dk: np.ascontiguousarray(payload[dk])
                 for dk in _DATA_KEYS}
        if has_scales:
            for sk in _SCALE_KEYS:
                clean[sk] = np.ascontiguousarray(payload[sk])
        entry = _Entry(clean)
        with self._lock:
            self._forget(key)
            self._entries[key] = entry
            self._host_used += entry.nbytes
            self.demotions.add(1)
            self.demoted_bytes.add(entry.nbytes)
            self._enforce_host()

    # -- promotion (host tier -> caller, who adopts into the pool) ------ #
    def get(self, key: tuple, *, pop: bool = False) -> Optional[dict]:
        """Look up ``key``; a hit returns the payload (rehydrated from
        disk when spilled) and refreshes recency; ``pop=True`` removes
        the entry (session hibernation consumes its chain on resume).
        A corrupted or lost spill file records a flight incident and
        reads as a miss.  The caller is responsible for calling
        :meth:`record_promote` once the payload actually lands in HBM.
        """
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses.add(1)
                return None
            if entry.where == "disk":
                payload = self._read_spill(key, entry)
                if payload is None:      # corrupt/lost: already counted
                    self._forget(key)
                    self.misses.add(1)
                    return None
                entry.payload = payload
                entry.where = "host"
                entry.path = None
                entry.crcs = None
                self._disk_used -= entry.nbytes
                self._host_used += entry.nbytes
            self._entries.move_to_end(key)
            self.hits.add(1)
            payload = entry.payload
            if pop:
                self._forget(key)
            self._enforce_host()
            return payload

    def contains(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries

    def record_promote(self, nbytes: int, seconds: float) -> None:
        """Account one successful re-admission to HBM (called by the
        engine after ``adopt_chain`` returns) — feeds the promote
        counter and the bandwidth gauge."""
        with self._lock:
            self.promotions.add(1)
            self.promoted_bytes.add(int(nbytes))
            self._promote_s += max(0.0, float(seconds))

    # -- capacity enforcement (callers hold the lock) ------------------- #
    def _enforce_host(self) -> None:
        # oldest-first over entries currently resident in host RAM
        while self._host_used > self.host_bytes:
            victim = next((k for k, e in self._entries.items()
                           if e.where == "host"), None)
            if victim is None:
                break
            entry = self._entries[victim]
            if self.spill_dir is not None:
                self._spill(victim, entry)
            else:
                self._forget(victim)
                self.drops.add(1)
        while self._disk_used > self.disk_bytes:
            victim = next((k for k, e in self._entries.items()
                           if e.where == "disk"), None)
            if victim is None:
                break
            self._forget(victim)
            self.drops.add(1)

    def _forget(self, key: tuple) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        if entry.where == "host":
            self._host_used -= entry.nbytes
        else:
            self._disk_used -= entry.nbytes
            if entry.path:
                try:
                    os.unlink(entry.path)
                except OSError:
                    pass

    # -- disk tier ------------------------------------------------------ #
    def _spill_path(self, key: tuple) -> str:
        digest = hashlib.sha1(repr(key).encode()).hexdigest()[:24]
        return os.path.join(self.spill_dir, f"kvtier-{digest}.npz")

    def _spill(self, key: tuple, entry: _Entry) -> None:
        import numpy as np
        os.makedirs(self.spill_dir, exist_ok=True)
        path = self._spill_path(key)
        try:
            np.savez(path, **entry.payload)
        except OSError:
            # disk unwritable: degrade to a drop, never wedge eviction
            log.exception("kvtier spill write failed (%s)", path)
            self._forget(key)
            self.drops.add(1)
            return
        # CRC over the raw array bytes, recorded at spill time and
        # verified on every read — a torn or tampered file must surface
        # as an incident + miss, not as wrong KV rows in a stream
        entry.crcs = {name: zlib.crc32(arr.tobytes())
                      for name, arr in entry.payload.items()}
        entry.path = path
        entry.payload = None
        entry.where = "disk"
        self._host_used -= entry.nbytes
        self._disk_used += entry.nbytes
        self.spills.add(1)

    def _read_spill(self, key: tuple, entry: _Entry) -> Optional[dict]:
        import numpy as np
        try:
            with np.load(entry.path) as z:
                payload = {name: z[name] for name in z.files}
            for name, crc in (entry.crcs or {}).items():
                if name not in payload or \
                        zlib.crc32(payload[name].tobytes()) != crc:
                    raise ValueError(f"CRC mismatch on {name!r}")
        except BaseException as e:  # noqa: BLE001 — lost OR corrupt
            self.corrupt_reads.add(1)
            try:
                from bigdl_tpu.obs.flight import get_flight_recorder
                get_flight_recorder().record(
                    "kvtier_spill_corrupt",
                    {"store": self.name, "path": entry.path,
                     "key": repr(key), "error": repr(e)},
                    key=f"kvtier/{self.name}")
            except Exception:
                log.exception("flight incident for corrupt spill failed")
            log.warning("kvtier spill read failed (%s): %r", entry.path, e)
            return None
        return payload

    # -- introspection -------------------------------------------------- #
    def promote_bandwidth_mbs(self) -> Optional[float]:
        """Mean promote bandwidth (MB/s) over the store's lifetime."""
        with self._lock:
            if self._promote_s <= 0.0:
                return None
            return (self.promoted_bytes.get()[0] / self._promote_s
                    / (1 << 20))

    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "entries": len(self._entries),
                "host_used_bytes": self._host_used,
                "host_capacity_bytes": self.host_bytes,
                "disk_used_bytes": self._disk_used,
                "disk_capacity_bytes": (self.disk_bytes
                                        if self.spill_dir else 0),
                "spill_dir": self.spill_dir,
                "demotions": self.demotions.get()[0],
                "promotions": self.promotions.get()[0],
                "hits": self.hits.get()[0],
                "misses": self.misses.get()[0],
                "hit_rate": (self.hits.get()[0]
                             / (self.hits.get()[0] + self.misses.get()[0])
                             if (self.hits.get()[0]
                                 + self.misses.get()[0]) else None),
                "spills": self.spills.get()[0],
                "drops": self.drops.get()[0],
                "corrupt_reads": self.corrupt_reads.get()[0],
                "promote_bandwidth_mbs": (
                    (self.promoted_bytes.get()[0] / self._promote_s
                     / (1 << 20)) if self._promote_s > 0 else None),
            }


def block_path(tokens0, block_len: int, n_blocks: int
               ) -> Tuple[Tuple[int, ...], ...]:
    """The radix-style token-key path of the first ``n_blocks`` full
    blocks of ``tokens0`` — the content address demoted prefix blocks
    are stored (and re-found) under.  Matches ``RadixCache``'s node
    keys exactly, so the demotion hook's paths and the promotion
    probe's paths can never drift apart."""
    B = int(block_len)
    return tuple(tuple(int(x) for x in tokens0[i * B:(i + 1) * B])
                 for i in range(int(n_blocks)))
