"""Radix prefix cache: token-prefix trie over refcounted block chains.

Chat-style production traffic repeats prompt heads constantly (system
prompts, few-shot preambles, multi-turn history).  The SGLang insight
(RadixAttention, 2023) is that a paged KV cache already stores every
prompt's k/v in shareable units — so keep a trie from token prefixes to
block chains, and admission can reuse the longest cached prefix
copy-free, prefilling only the unmatched suffix.

The trie here is **block-granular**: one node per full block of
``block_len`` tokens (the node key is that block's token tuple), so a
match is always a whole number of blocks and the reused chain can be
handed straight to the fixed-shape block-table programs.  Matching is
capped at ``(t - 1) // block_len`` blocks — the final prompt token is
always prefilled so the request has logits to sample its first token
from, exactly like a cold prefill.

Reference protocol (one pool refcount per holder):

- ``match`` retains every matched block on behalf of the caller (the
  admitted sequence); the caller releases them with the rest of its
  table when the stream finishes.
- ``insert`` retains each block it adopts into a NEW node.  A prompt
  whose prefix already exists in the trie keeps its duplicate private
  blocks — the trie never swaps a live sequence's storage.
- ``evict`` releases blocks whose ONLY reference is the trie itself
  (refcount 1), LRU-first, leaves-first — a chain referenced by any
  live sequence can never evict, and interior nodes only become
  candidates once their subtree is gone.

Every eviction funnels through ONE path: the optional ``on_evict``
callback fires per victim with ``(path, block)`` — ``path`` being the
tuple of block token-keys from the root down to the victim — BEFORE
the block is released, so a demotion hook (the host KV tier), a plain
drop, and test instrumentation all observe the identical sequence of
events.  The block is still allocated while the callback runs (its
k/v rows are gatherable); a callback that raises is logged and the
eviction proceeds — a flaky demotion target must not wedge the pool.

Thread model: the serving worker is the only mutator; counters are
lock-guarded so stats/metrics reads from other threads are consistent.
Eviction rescans the trie per freed block — fine at serving scale
(trie size is bounded by the pool's block count).
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from bigdl_tpu.serving.kvcache.blocks import BlockPool

log = logging.getLogger("bigdl_tpu.serving")

# Prefix fingerprints: every trie node carries a 64-bit FNV-1a chain
# hash of its full root->node block-key path.  The router's per-replica
# summary is just the SET of these sigs — membership of sig_i means "a
# chain covering blocks [0, i] of some prompt is cached here" — so a
# foreign router can measure longest-prefix overlap without walking (or
# even seeing) the trie.  The hash is deterministic across processes
# (no PYTHONHASHSEED dependence: plain int arithmetic).
_SIG_ROOT = 0xCBF29CE484222325     # FNV-1a 64-bit offset basis
_FNV_PRIME = 0x100000001B3
_U64 = (1 << 64) - 1


def _sig_extend(sig: int, key: Tuple[int, ...]) -> int:
    """Fold one block's token tuple into a cumulative prefix sig.
    Block keys have fixed length (``block_len``), so the chain hash is
    unambiguous without separators."""
    h = sig
    for tok in key:
        h = ((h ^ (int(tok) & _U64)) * _FNV_PRIME) & _U64
    return h


def prefix_signatures(tokens0, block_len: int,
                      cap: Optional[int] = None) -> List[int]:
    """Cumulative block-prefix sigs for a prompt (0-based ids):
    ``out[i]`` fingerprints blocks ``[0, i]``.  ``cap`` defaults to the
    same ``(t - 1) // block_len`` bound :meth:`RadixCache.match` uses —
    the last prompt token is always prefilled, never matched."""
    t = len(tokens0)
    n = max(0, (t - 1) // block_len)
    if cap is not None:
        n = min(n, int(cap))
    out: List[int] = []
    sig = _SIG_ROOT
    for i in range(n):
        key = tuple(int(x) for x in tokens0[i * block_len:
                                            (i + 1) * block_len])
        sig = _sig_extend(sig, key)
        out.append(sig)
    return out


class _Node:
    __slots__ = ("key", "block", "children", "parent", "last_used", "sig")

    def __init__(self, key: Optional[Tuple[int, ...]], block: Optional[int],
                 parent: Optional["_Node"], last_used: int,
                 sig: int = _SIG_ROOT):
        self.key = key
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = last_used
        self.sig = sig


class RadixCache:
    """Longest-prefix block reuse over a :class:`BlockPool`."""

    def __init__(self, pool: BlockPool,
                 on_evict: Optional[Callable[[Tuple[Tuple[int, ...], ...],
                                              int], None]] = None):
        self.pool = pool
        self.block_len = pool.block_len
        #: the single eviction funnel: called as ``on_evict(path,
        #: block)`` per victim, before release, while the block is
        #: still allocated.  Reassignable live (the engine wires the
        #: host-tier demotion hook here).
        self.on_evict = on_evict
        #: optional router summary observer: ``on_insert(sig)`` /
        #: ``on_evict(sig)`` fire synchronously under the trie lock on
        #: every node add/drop, so the summary can never claim a chain
        #: the trie just evicted (the router-staleness hazard).  Wire it
        #: with :meth:`attach_summary`; independent of the block-level
        #: ``on_evict`` demotion funnel above.
        self.summary = None
        self._lock = threading.Lock()
        self._root = _Node(None, None, None, 0)
        self._clock = 0
        self.nodes = 0
        self.lookups = 0
        self.hits = 0
        self.matched_tokens = 0   # == prefill tokens saved
        self.inserted_blocks = 0
        self.evictions = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _block_key(self, tokens0, i: int) -> Tuple[int, ...]:
        B = self.block_len
        return tuple(int(x) for x in tokens0[i * B:(i + 1) * B])

    # -- lookup ---------------------------------------------------------- #
    def match(self, tokens0) -> List[int]:
        """Longest cached prefix of ``tokens0`` (0-based token ids), in
        whole blocks, capped so at least the last prompt token is left
        to prefill.  Matched blocks are retained for the caller."""
        t = len(tokens0)
        cap = max(0, (t - 1) // self.block_len)
        out: List[int] = []
        with self._lock:
            self.lookups += 1
            node = self._root
            now = self._tick()
            for i in range(cap):
                child = node.children.get(self._block_key(tokens0, i))
                if child is None:
                    break
                child.last_used = now
                out.append(child.block)
                node = child
            if out:
                self.hits += 1
                self.matched_tokens += len(out) * self.block_len
                self.pool.retain(out)
        return out

    # -- admission ------------------------------------------------------- #
    def insert(self, tokens0, blocks: List[int]) -> int:
        """Register a prefilled chain: ``blocks[i]`` holds tokens
        ``[i*B, (i+1)*B)`` of ``tokens0``.  Existing nodes are kept
        (their blocks stay authoritative; the caller's duplicates stay
        private to it); new tails are adopted with one trie reference.
        Returns the number of nodes added."""
        added = 0
        with self._lock:
            node = self._root
            now = self._tick()
            for i, blk in enumerate(blocks):
                key = self._block_key(tokens0, i)
                child = node.children.get(key)
                if child is None:
                    child = _Node(key, int(blk), node, now,
                                  sig=_sig_extend(node.sig, key))
                    node.children[key] = child
                    self.pool.retain([int(blk)])
                    self.nodes += 1
                    self.inserted_blocks += 1
                    added += 1
                    if self.summary is not None:
                        self.summary.on_insert(child.sig)
                else:
                    child.last_used = now
                node = child
        return added

    # -- eviction -------------------------------------------------------- #
    def _leaves(self) -> List[_Node]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    @staticmethod
    def _path_of(node: _Node) -> Tuple[Tuple[int, ...], ...]:
        """Block token-keys from the root down to ``node`` — the
        tier-store identity of the node's block (content-addressed by
        its full prefix, so a demoted block is re-findable by any
        future prompt sharing that prefix)."""
        keys: List[Tuple[int, ...]] = []
        while node.key is not None:
            keys.append(node.key)
            node = node.parent
        return tuple(reversed(keys))

    def _evict_node(self, v: _Node) -> None:
        """THE eviction path — every drop goes through here.  Fires
        ``on_evict`` (demotion hook / instrumentation) while the block
        is still allocated, then releases the trie's reference."""
        hook = self.on_evict
        if hook is not None:
            try:
                hook(self._path_of(v), int(v.block))
            except Exception:  # noqa: BLE001 — a failing demotion
                # target degrades the eviction to a plain drop
                log.exception("radix on_evict hook failed; dropping "
                              "block %d", v.block)
        del v.parent.children[v.key]
        self.pool.release([v.block])
        self.nodes -= 1
        self.evictions += 1
        if self.summary is not None:
            self.summary.on_evict(v.sig)

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by dropping LRU leaf
        nodes whose block has no holder but the trie (refcount 1).
        Returns how many blocks were actually freed."""
        target = max(1, int(n_blocks))
        freed = 0
        with self._lock:
            while freed < target:
                victims = [n for n in self._leaves()
                           if self.pool.refcount(n.block) == 1]
                if not victims:
                    break
                self._evict_node(min(victims, key=lambda n: n.last_used))
                freed += 1
        return freed

    # -- router summary -------------------------------------------------- #
    def attach_summary(self, summary) -> None:
        """Attach a router prefix summary (``on_insert(sig)`` /
        ``on_evict(sig)``) and replay the current trie into it — one
        walk at attach time; every later refresh is the O(1) per-node
        hook above, never another walk."""
        with self._lock:
            self.summary = summary
            stack = list(self._root.children.values())
            while stack:
                n = stack.pop()
                summary.on_insert(n.sig)
                stack.extend(n.children.values())

    # -- introspection --------------------------------------------------- #
    def hit_rate(self) -> Optional[float]:
        with self._lock:
            return (self.hits / self.lookups) if self.lookups else None

    def stats(self) -> dict:
        with self._lock:
            return {
                "nodes": self.nodes,
                "lookups": self.lookups,
                "hits": self.hits,
                "hit_rate": (self.hits / self.lookups
                             if self.lookups else None),
                "prefill_tokens_saved": self.matched_tokens,
                "inserted_blocks": self.inserted_blocks,
                "evictions": self.evictions,
            }
