"""Paged KV memory: one HBM-resident block arena + host-side free list.

The slot engine of PR 5 gave every decode slot a private contiguous
``(cache_len,)`` KV region: admission had to reject any prompt longer
than one region, and identical prompt prefixes were recomputed and
stored once per request.  ``BlockPool`` is the vLLM-style alternative
(PagedAttention, SOSP'23), TPU-native: KV memory is ONE device array of
fixed-size blocks

    k, v : (L, num_blocks, H, block_len, D)

and a *sequence* is a host-side list of block ids (its block table).
The device arrays never change shape — prefill scatters rows into
blocks, decode gathers by a padded int32 block-table operand — so the
AOT executables of the serving engine survive untouched and donation
keeps the arena resident.  Everything dynamic (allocation, refcounts,
sharing) lives on the host in this class, where it costs nothing per
token.

Block 0 is reserved as a **scratch** block: padded table entries and
padded scatter targets point at it, so fixed-shape gathers/scatters
never need a validity operand — garbage lands in (or comes from)
scratch and is always masked by the position mask.  It is never
allocated and never freed.

Refcounts make chains shareable copy-free: a block referenced by two
live sequences (or a sequence and the radix cache) is freed only when
the last holder releases it.  ``alloc`` hands out blocks at refcount 1;
``retain``/``release`` move them between holders.

Exhaustion is two distinct conditions with two distinct types:

- :class:`RequestExceedsPool` (a ``ValueError``): the request could
  NEVER fit — its total block need exceeds the whole pool.  Raised at
  admission, counted in ``serving/rejected_total``.
- :class:`PoolExhausted` (a ``RuntimeError``): the pool is full *right
  now*.  Transient by construction — blocks free as streams finish —
  so the engine defers the request instead of failing it.
"""
from __future__ import annotations

import threading
from typing import List, Sequence

SCRATCH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Transient: no free blocks at this instant; retry after streams
    complete or the radix cache evicts unreferenced tails."""


class RequestExceedsPool(ValueError):
    """Permanent: the request's total KV need (prompt + generation
    budget, in blocks) exceeds the whole pool — it can never be
    admitted.  Counted in ``serving/rejected_total``."""


class BlockPool:
    """Refcounted free-list allocator over one paged k/v arena.

    Args:
        n_layers / n_heads / head_dim: model geometry (L, H, D).
        block_len: tokens per block (the page size).
        num_blocks: total blocks INCLUDING the reserved scratch block 0;
            usable capacity is ``num_blocks - 1``.
        dtype: cache dtype (defaults to f32; the engine passes the
            params' embed dtype).

    The jnp arenas are held as ``self.k`` / ``self.v``; callers that
    run donated executables over them reassign the attributes with the
    donated outputs (same contract as the slot engine's resident
    caches).
    """

    def __init__(self, *, n_layers: int, n_heads: int, head_dim: int,
                 block_len: int, num_blocks: int, dtype=None):
        import jax.numpy as jnp

        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got "
                f"{num_blocks}")
        self.block_len = int(block_len)
        self.num_blocks = int(num_blocks)
        self.shape = (int(n_layers), self.num_blocks, int(n_heads),
                      self.block_len, int(head_dim))
        dt = dtype if dtype is not None else jnp.float32
        self.k = jnp.zeros(self.shape, dt)
        self.v = jnp.zeros(self.shape, dt)
        self.dtype = self.k.dtype
        self._lock = threading.Lock()
        # pop() from the tail hands out ascending ids first
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._ref = [0] * self.num_blocks

    # -- capacity -------------------------------------------------------- #
    @property
    def capacity(self) -> int:
        """Usable blocks (scratch excluded)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_count(self) -> int:
        return self.capacity - self.free_count

    @property
    def arena_bytes(self) -> int:
        """HBM footprint of the k + v arenas."""
        return 2 * self.k.size * self.k.dtype.itemsize

    def utilization(self) -> float:
        return self.used_count / self.capacity if self.capacity else 0.0

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return -(-int(n_tokens) // self.block_len)

    # -- alloc / refcount ------------------------------------------------ #
    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks at refcount 1; all-or-nothing."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(
                    f"need {n} blocks, {len(self._free)} free "
                    f"(capacity {self.capacity})")
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
        return out

    def retain(self, blocks: Sequence[int]) -> None:
        """Add one reference to each (already-live) block."""
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise ValueError(f"retain of free block {b}")
                self._ref[b] += 1

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference; a block at zero returns to the free
        list."""
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise ValueError(f"release of free block {b}")
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    self._free.append(b)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref[block]

    # -- introspection --------------------------------------------------- #
    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
        return {
            "num_blocks": self.num_blocks,
            "block_len": self.block_len,
            "capacity": self.capacity,
            "free_blocks": free,
            "used_blocks": self.capacity - free,
            "utilization": ((self.capacity - free) / self.capacity
                            if self.capacity else 0.0),
            "arena_bytes": self.arena_bytes,
        }
