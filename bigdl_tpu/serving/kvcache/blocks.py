"""Paged KV memory: one HBM-resident block arena + host-side free list.

The slot engine of PR 5 gave every decode slot a private contiguous
``(cache_len,)`` KV region: admission had to reject any prompt longer
than one region, and identical prompt prefixes were recomputed and
stored once per request.  ``BlockPool`` is the vLLM-style alternative
(PagedAttention, SOSP'23), TPU-native: KV memory is ONE device array of
fixed-size blocks

    k, v : (L, num_blocks, H, block_len, D)

and a *sequence* is a host-side list of block ids (its block table).
The device arrays never change shape — prefill scatters rows into
blocks, decode gathers by a padded int32 block-table operand — so the
AOT executables of the serving engine survive untouched and donation
keeps the arena resident.  Everything dynamic (allocation, refcounts,
sharing) lives on the host in this class, where it costs nothing per
token.

Block 0 is reserved as a **scratch** block: padded table entries and
padded scatter targets point at it, so fixed-shape gathers/scatters
never need a validity operand — garbage lands in (or comes from)
scratch and is always masked by the position mask.  It is never
allocated and never freed.

Refcounts make chains shareable copy-free: a block referenced by two
live sequences (or a sequence and the radix cache) is freed only when
the last holder releases it.  ``alloc`` hands out blocks at refcount 1;
``retain``/``release`` move them between holders.

Exhaustion is two distinct conditions with two distinct types:

- :class:`RequestExceedsPool` (a ``ValueError``): the request could
  NEVER fit — its total block need exceeds the whole pool.  Raised at
  admission, counted in ``serving/rejected_total``.
- :class:`PoolExhausted` (a ``RuntimeError``): the pool is full *right
  now*.  Transient by construction — blocks free as streams finish —
  so the engine defers the request instead of failing it.

Migration (disaggregated prefill/decode serving): a finished prefill's
block chain moves between pools as a **block-major wire payload**
``(n, L, H, block_len, D)`` — ``export_chain`` gathers it to the host
in bounded slices, ``adopt_chain`` allocates destination blocks
all-or-nothing and scatters the payload back in over
:func:`~bigdl_tpu.utils.transfer.chunked_device_put` (the 32 MB
chunking rule: the round-4 relay died on one ~154 MB buffer, and a
chain near ``cache_len`` at production geometry is that order of
magnitude).  Block-major layout is deliberate: the wire's leading dim
is the one both the d2h slicer and ``chunked_device_put`` chunk along,
so no single slice ever exceeds the ceiling regardless of L.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

SCRATCH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Transient: no free blocks at this instant; retry after streams
    complete or the radix cache evicts unreferenced tails."""


class RequestExceedsPool(ValueError):
    """Permanent: the request's total KV need (prompt + generation
    budget, in blocks) exceeds the whole pool — it can never be
    admitted.  Counted in ``serving/rejected_total``."""


class BlockPool:
    """Refcounted free-list allocator over one paged k/v arena.

    Args:
        n_layers / n_heads / head_dim: model geometry (L, H, D).
        block_len: tokens per block (the page size).
        num_blocks: total blocks INCLUDING the reserved scratch block 0;
            usable capacity is ``num_blocks - 1``.
        dtype: cache dtype (defaults to f32; the engine passes the
            params' embed dtype).
        kv_quant: ``None`` (full-precision arenas) or ``"int8"`` —
            int8 block arenas plus per-(position, head) f32 scale
            arenas ``self.ks`` / ``self.vs`` shaped (L, N, H, B).  The
            paged gather dequantizes in-flight (see
            ``generate._decode_step_paged``); storage drops ~4x minus
            the 1/D scale overhead.  Lossy: streams are NOT bit-exact
            vs a full-precision pool.  Chain export/adopt bundles the
            scale arrays atomically with the data (the host KV tier
            and hibernation ride this); disaggregated serving still
            keeps full-precision pools.

    The jnp arenas are held as ``self.k`` / ``self.v`` (plus
    ``self.ks`` / ``self.vs`` when quantized); callers that run donated
    executables over them reassign the attributes with the donated
    outputs (same contract as the slot engine's resident caches).
    """

    def __init__(self, *, n_layers: int, n_heads: int, head_dim: int,
                 block_len: int, num_blocks: int, dtype=None,
                 kv_quant: Optional[str] = None):
        import jax.numpy as jnp

        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got "
                f"{num_blocks}")
        if kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {kv_quant!r}")
        self.block_len = int(block_len)
        self.num_blocks = int(num_blocks)
        self.shape = (int(n_layers), self.num_blocks, int(n_heads),
                      self.block_len, int(head_dim))
        self.kv_quant = kv_quant
        if kv_quant == "int8":
            self.k = jnp.zeros(self.shape, jnp.int8)
            self.v = jnp.zeros(self.shape, jnp.int8)
            # per-(position, head) scales, block-major like the arenas
            self.ks = jnp.zeros(self.shape[:4], jnp.float32)
            self.vs = jnp.zeros(self.shape[:4], jnp.float32)
        else:
            dt = dtype if dtype is not None else jnp.float32
            self.k = jnp.zeros(self.shape, dt)
            self.v = jnp.zeros(self.shape, dt)
            self.ks = self.vs = None
        self.dtype = self.k.dtype
        self._lock = threading.Lock()
        # pop() from the tail hands out ascending ids first
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._ref = [0] * self.num_blocks
        self._adopt_jits: dict = {}  # padded wire width -> donated scatter

    # -- capacity -------------------------------------------------------- #
    @property
    def capacity(self) -> int:
        """Usable blocks (scratch excluded)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_count(self) -> int:
        return self.capacity - self.free_count

    @property
    def kv_arena_bytes(self) -> int:
        """HBM footprint of the k + v data arenas alone."""
        return 2 * self.k.size * self.k.dtype.itemsize

    @property
    def scale_arena_bytes(self) -> int:
        """HBM footprint of the int8 per-(position, head) scale arenas
        (0 for a full-precision pool) — ledgered separately from the
        data arenas so quantized capacity planning sees the overhead."""
        if self.ks is None:
            return 0
        return 2 * self.ks.size * self.ks.dtype.itemsize

    @property
    def arena_bytes(self) -> int:
        """HBM footprint of the k + v arenas (+ scale arenas when
        quantized)."""
        return self.kv_arena_bytes + self.scale_arena_bytes

    def utilization(self) -> float:
        return self.used_count / self.capacity if self.capacity else 0.0

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return -(-int(n_tokens) // self.block_len)

    # -- alloc / refcount ------------------------------------------------ #
    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks at refcount 1; all-or-nothing."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(
                    f"need {n} blocks, {len(self._free)} free "
                    f"(capacity {self.capacity})")
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
        return out

    def retain(self, blocks: Sequence[int]) -> None:
        """Add one reference to each (already-live) block."""
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise ValueError(f"retain of free block {b}")
                self._ref[b] += 1

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference; a block at zero returns to the free
        list."""
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise ValueError(f"release of free block {b}")
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    self._free.append(b)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref[block]

    # -- migration (disaggregated prefill/decode) ------------------------ #
    @property
    def block_bytes(self) -> int:
        """Bytes of one block's k (== v) rows across all layers — the
        wire unit both chunkers slice on."""
        L, _, H, B, D = self.shape
        return L * H * B * D * self.dtype.itemsize

    @property
    def scale_block_bytes(self) -> int:
        """Bytes of one block's k (== v) per-(position, head) scale
        rows; 0 for full-precision pools."""
        if self.ks is None:
            return 0
        L, _, H, B, _ = self.shape
        return L * H * B * self.ks.dtype.itemsize

    @property
    def wire_block_bytes(self) -> int:
        """Per-block wire bytes of one k (== v) leg INCLUDING its scale
        rows — the unit the chunkers budget on, so a quantized block's
        scales count against the same 32 MB transfer ceiling as its
        data."""
        return self.block_bytes + self.scale_block_bytes

    def export_chain(self, blocks: Sequence[int], *,
                     chunk_bytes: Optional[int] = None) -> dict:
        """Gather ``blocks``' k/v rows to the host as a block-major
        wire payload ``{"k", "v": (n, L, H, block_len, D) np, "blocks": n}``.

        A quantized pool (``kv_quant="int8"``) exports its
        per-(position, head) scales ATOMICALLY with the data — the
        payload gains ``"ks"`` / ``"vs"`` arrays shaped ``(n, L, H,
        block_len)`` f32, and scale bytes count against the chunk
        budget — so an adopted block is bit-identical to the exported
        one, never data without its dequantization state.

        Device->host moves in slices of at most ``chunk_bytes`` (the
        shared 32 MB transfer ceiling by default) along the block dim,
        one in flight at a time — the same discipline as
        ``chunked_device_put``, mirrored for the download leg.  The
        caller keeps its references; exporting never touches refcounts.
        """
        import jax.numpy as jnp
        import numpy as np

        from bigdl_tpu.utils.transfer import DEFAULT_CHUNK_BYTES
        cb = int(chunk_bytes) if chunk_bytes else DEFAULT_CHUNK_BYTES
        n = len(blocks)
        L, _, H, B, D = self.shape
        quant = self.kv_quant is not None
        host_k = np.empty((n, L, H, B, D), self.dtype)
        host_v = np.empty((n, L, H, B, D), self.dtype)
        host_ks = np.empty((n, L, H, B), np.float32) if quant else None
        host_vs = np.empty((n, L, H, B), np.float32) if quant else None
        if n:
            idx = jnp.asarray(list(blocks), jnp.int32)
            # device-side gather + transpose to block-major wire layout
            kc = jnp.moveaxis(self.k[:, idx], 0, 1)
            vc = jnp.moveaxis(self.v[:, idx], 0, 1)
            if quant:
                ksc = jnp.moveaxis(self.ks[:, idx], 0, 1)
                vsc = jnp.moveaxis(self.vs[:, idx], 0, 1)
            rows = max(1, cb // max(1, self.wire_block_bytes))
            for i in range(0, n, rows):
                host_k[i:i + rows] = np.asarray(kc[i:i + rows])
                host_v[i:i + rows] = np.asarray(vc[i:i + rows])
                if quant:
                    host_ks[i:i + rows] = np.asarray(ksc[i:i + rows])
                    host_vs[i:i + rows] = np.asarray(vsc[i:i + rows])
        out = {"k": host_k, "v": host_v, "blocks": n}
        if quant:
            out["ks"] = host_ks
            out["vs"] = host_vs
        return out

    def _adopt_scatter(self, width: int):
        """Donated scatter of a ``width``-block wire payload into the
        arenas; one executable per padded wire width (powers of two),
        padded entries target the scratch block with zero rows.  A
        quantized pool's scatter writes data and scale arenas in ONE
        executable — a block can never land without its scales."""
        exe = self._adopt_jits.get(width)
        if exe is None:
            import jax
            import jax.numpy as jnp

            if self.kv_quant is not None:
                def _scatter_q(k, v, ks, vs, kw, vw, ksw, vsw, ids):
                    k = k.at[:, ids].set(jnp.moveaxis(kw, 0, 1))
                    v = v.at[:, ids].set(jnp.moveaxis(vw, 0, 1))
                    ks = ks.at[:, ids].set(jnp.moveaxis(ksw, 0, 1))
                    vs = vs.at[:, ids].set(jnp.moveaxis(vsw, 0, 1))
                    return k, v, ks, vs

                exe = jax.jit(_scatter_q, donate_argnums=(0, 1, 2, 3))
            else:
                def _scatter(k, v, kw, vw, ids):
                    k = k.at[:, ids].set(jnp.moveaxis(kw, 0, 1))
                    v = v.at[:, ids].set(jnp.moveaxis(vw, 0, 1))
                    return k, v

                exe = jax.jit(_scatter, donate_argnums=(0, 1))
            self._adopt_jits[width] = exe
        return exe

    def warmup_adopt(self, widths: Sequence[int]) -> int:
        """Pre-compile AND prime the donated adopt scatters for the
        given padded wire widths, so the first real migration doesn't
        pay a mid-traffic compile.  Runs each executable once with a
        zero payload aimed entirely at the scratch block — garbage
        there is always masked — which also keeps the arenas resident
        through the donation."""
        import jax.numpy as jnp
        import numpy as np
        n = 0
        for w in widths:
            w = int(w)
            if w < 1:
                continue
            kw = jnp.zeros((w, self.shape[0]) + self.shape[2:],
                           self.dtype)
            if getattr(self.k, "sharding", None) is not None:
                import jax
                kw = jax.device_put(kw, self.k.sharding)
            idx = np.full((w,), SCRATCH_BLOCK, np.int32)
            if self.kv_quant is not None:
                sw = jnp.zeros((w,) + self.shape[:1] + self.shape[2:4],
                               jnp.float32)
                if getattr(self.ks, "sharding", None) is not None:
                    import jax
                    sw = jax.device_put(sw, self.ks.sharding)
                (self.k, self.v, self.ks,
                 self.vs) = self._adopt_scatter(w)(
                    self.k, self.v, self.ks, self.vs, kw, kw, sw, sw, idx)
            else:
                self.k, self.v = self._adopt_scatter(w)(
                    self.k, self.v, kw, kw, idx)
            n += 1
        return n

    def adopt_chain(self, k_wire, v_wire, ks_wire=None, vs_wire=None, *,
                    extra_blocks: int = 0, device=None,
                    chunk_bytes: Optional[int] = None) -> List[int]:
        """Adopt an exported chain into THIS pool: allocate
        ``n_wire + extra_blocks`` blocks (all-or-nothing — a partial
        adoption would strand a half-migrated sequence), stage the wire
        payload over ``chunked_device_put`` and scatter it into the
        first ``n_wire`` of them.  Returns the new block ids, each at
        refcount 1 (the adopting sequence's references).

        A quantized pool (``kv_quant="int8"``) REQUIRES the matching
        scale arrays ``ks_wire`` / ``vs_wire`` (shape ``(n, L, H,
        block_len)``) from :meth:`export_chain` — data and scales land
        through one donated scatter, and the data legs' chunk budget is
        shrunk by the scale share so data + scales together respect the
        32 MB transfer ceiling.  The adopted block is bit-identical to
        the exported one.

        ``extra_blocks`` reserves the generation tail in the same
        atomic allocation.  ``device`` is the arena's committed
        sharding/device (a placement slice's replicated sharding).  On
        transfer failure every allocated block is released before the
        error propagates — the pool is left exactly as found.
        :class:`PoolExhausted` propagates untouched so callers keep the
        typed defer path.
        """
        import numpy as np

        from bigdl_tpu.utils.transfer import (DEFAULT_CHUNK_BYTES,
                                              chunked_device_put)
        k_wire = np.asarray(k_wire)
        v_wire = np.asarray(v_wire)
        n = int(k_wire.shape[0]) if k_wire.ndim else 0
        if v_wire.shape != k_wire.shape:
            raise ValueError(
                f"k/v wire shapes differ: {k_wire.shape} vs {v_wire.shape}")
        quant = self.kv_quant is not None
        if quant and n and (ks_wire is None or vs_wire is None):
            raise ValueError(
                "adopting into a quantized pool (kv_quant='int8') "
                "requires the ks/vs scale arrays exported with the "
                "chain — data without scales cannot dequantize")
        if not quant and (ks_wire is not None or vs_wire is not None):
            raise ValueError(
                "scale arrays supplied for a full-precision pool")
        if quant and n:
            ks_wire = np.asarray(ks_wire, np.float32)
            vs_wire = np.asarray(vs_wire, np.float32)
            want = (n,) + self.shape[:1] + self.shape[2:4]
            if ks_wire.shape != want or vs_wire.shape != want:
                raise ValueError(
                    f"scale wire shapes {ks_wire.shape} / "
                    f"{vs_wire.shape} do not match blocks {want}")
        ids = self.alloc(n + max(0, int(extra_blocks)))
        if n == 0:
            return ids
        cb = int(chunk_bytes) if chunk_bytes else DEFAULT_CHUNK_BYTES
        # scale bytes ride the same budget: a data slice plus its scale
        # slice together stay under ``cb``
        data_cb = max(1, cb * self.block_bytes
                      // max(1, self.wire_block_bytes))
        try:
            kw = chunked_device_put(k_wire, self.dtype,
                                    chunk_bytes=data_cb, device=device)
            vw = chunked_device_put(v_wire, self.dtype,
                                    chunk_bytes=data_cb, device=device)
            if quant:
                scale_cb = max(1, cb - data_cb)
                ksw = chunked_device_put(ks_wire, np.float32,
                                         chunk_bytes=scale_cb,
                                         device=device)
                vsw = chunked_device_put(vs_wire, np.float32,
                                         chunk_bytes=scale_cb,
                                         device=device)
            # pad the wire to a power-of-two width so the donated
            # scatter compiles once per bucket; padded rows are zeros
            # aimed at the scratch block (garbage there is masked)
            width = 1
            while width < n:
                width *= 2
            if width > n:
                import jax.numpy as jnp
                pad = jnp.zeros((width - n,) + kw.shape[1:], kw.dtype)
                if device is not None:
                    import jax
                    pad = jax.device_put(pad, device)
                kw = jnp.concatenate([kw, pad], axis=0)
                vw = jnp.concatenate([vw, pad], axis=0)
                if quant:
                    spad = jnp.zeros((width - n,) + ksw.shape[1:],
                                     ksw.dtype)
                    if device is not None:
                        import jax
                        spad = jax.device_put(spad, device)
                    ksw = jnp.concatenate([ksw, spad], axis=0)
                    vsw = jnp.concatenate([vsw, spad], axis=0)
            idx = np.full((width,), SCRATCH_BLOCK, np.int32)
            idx[:n] = ids[:n]
            if quant:
                (self.k, self.v, self.ks,
                 self.vs) = self._adopt_scatter(width)(
                    self.k, self.v, self.ks, self.vs, kw, vw, ksw, vsw,
                    idx)
            else:
                self.k, self.v = self._adopt_scatter(width)(
                    self.k, self.v, kw, vw, idx)
        except BaseException:
            self.release(ids)
            raise
        return ids

    # -- introspection --------------------------------------------------- #
    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
        return {
            "num_blocks": self.num_blocks,
            "block_len": self.block_len,
            "capacity": self.capacity,
            "free_blocks": free,
            "used_blocks": self.capacity - free,
            "utilization": ((self.capacity - free) / self.capacity
                            if self.capacity else 0.0),
            "arena_bytes": self.arena_bytes,
            "kv_quant": self.kv_quant or "none",
        }
