"""Paged KV cache for LM serving: block arena + radix prefix sharing.

- :class:`BlockPool` — one fixed-shape HBM k/v arena of
  ``(L, num_blocks, H, block_len, D)`` blocks, host-side free list,
  refcounted so block chains are shared copy-free.
- :class:`RadixCache` — token-prefix trie over block chains with LRU
  eviction of unreferenced tails; admission reuses the longest cached
  prefix and prefills only the suffix.
- :class:`RequestExceedsPool` / :class:`PoolExhausted` — the permanent
  vs transient exhaustion types (reject vs defer).
"""
from bigdl_tpu.serving.kvcache.blocks import (SCRATCH_BLOCK, BlockPool,
                                              PoolExhausted,
                                              RequestExceedsPool)
from bigdl_tpu.serving.kvcache.radix import RadixCache

__all__ = ["BlockPool", "RadixCache", "PoolExhausted",
           "RequestExceedsPool", "SCRATCH_BLOCK"]
