"""Serving metrics: latency histograms, throughput, batch occupancy.

Per-request latency is split where a serving engineer needs it split —
queue wait (batching-policy cost) vs device time (model cost) — each a
log-spaced histogram with percentile estimation, plus counters for
throughput, batch occupancy (how full the padded bucket actually was)
and the compile-cache hit rate.  ``export_to_summary`` writes the
snapshot through the existing ``visualization`` tfevents writers, so
serving dashboards land next to the training ones.

The histogram class lives in :mod:`bigdl_tpu.obs.registry` (it is the
registry's generic log-bucket ``Histogram``); ``LatencyHistogram``
stays importable from here for compatibility.  ``publish_to`` exposes
an engine's live histograms/counters in the process-wide registry.

``throughput_eps`` is computed over a sliding window (default 60s), so
an idle gap stops depressing the number the moment traffic resumes;
the lifetime average — the old semantics, examples since engine start —
is kept under ``throughput_eps_lifetime``.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from bigdl_tpu.obs.registry import (_EDGES, FnGauge,  # noqa: F401
                                    Histogram as LatencyHistogram,
                                    MetricRegistry)


class ServingMetrics:
    """One engine's counters; thread-safe (batcher worker + callers)."""

    def __init__(self, throughput_window_s: float = 60.0):
        self._lock = threading.Lock()
        self.queue_wait = LatencyHistogram()
        self.device_time = LatencyHistogram()
        self.total_latency = LatencyHistogram()
        self.requests = 0          # accepted submissions
        self.rejected = 0          # backpressure rejections
        self.examples = 0          # examples completed
        self.batches = 0           # device dispatches
        self.batch_examples = 0    # real examples across dispatches
        self.padded_examples = 0   # bucket slots across dispatches
        self.started_at = time.perf_counter()
        self._window_s = float(throughput_window_s)
        self._recent: deque = deque()  # (t_done, n_examples) per dispatch

    # -- registry wiring ------------------------------------------------ #
    def publish_to(self, registry: MetricRegistry,
                   prefix: str = "serving/") -> "ServingMetrics":
        """Register the live histograms and computed counters in the
        process-wide registry (latest engine wins the names)."""
        registry.register(prefix + "queue_wait", self.queue_wait,
                          replace=True)
        registry.register(prefix + "device_time", self.device_time,
                          replace=True)
        registry.register(prefix + "total_latency", self.total_latency,
                          replace=True)
        for key in ("requests", "rejected", "examples", "batches"):
            registry.register(prefix + key,
                              FnGauge(lambda k=key: getattr(self, k)),
                              replace=True)
        registry.register(prefix + "throughput_eps",
                          FnGauge(lambda: self.snapshot()["throughput_eps"]),
                          replace=True)
        return self

    # -- recording ------------------------------------------------------ #
    def record_submit(self) -> None:
        with self._lock:
            self.requests += 1

    def record_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_batch(self, n_examples: int, bucket: int,
                     queue_waits_s, device_s: float) -> None:
        with self._lock:
            now = time.perf_counter()
            self.batches += 1
            self.examples += n_examples
            self.batch_examples += n_examples
            self.padded_examples += bucket
            self._recent.append((now, n_examples))
            self._evict(now)
            self.device_time.observe(device_s)
            for w in queue_waits_s:
                self.queue_wait.observe(w)

    def record_done(self, total_s: float) -> None:
        with self._lock:
            self.total_latency.observe(total_s)

    def _evict(self, now: float) -> None:
        horizon = now - self._window_s
        while self._recent and self._recent[0][0] < horizon:
            self._recent.popleft()

    # -- reading -------------------------------------------------------- #
    def snapshot(self, cache_stats: Optional[dict] = None) -> dict:
        with self._lock:
            now = time.perf_counter()
            elapsed = now - self.started_at
            self._evict(now)
            # sliding-window rate: examples completed in the last
            # window, over the window actually covered (a young engine
            # divides by its age, not the full window)
            span = min(elapsed, self._window_s)
            windowed = sum(n for _, n in self._recent)
            snap = {
                "requests": self.requests,
                "rejected": self.rejected,
                "examples": self.examples,
                "batches": self.batches,
                "throughput_eps": (windowed / span) if span > 0 else 0.0,
                "throughput_window_s": self._window_s,
                "throughput_eps_lifetime":
                    (self.examples / elapsed) if elapsed > 0 else 0.0,
                "batch_occupancy": (self.batch_examples / self.padded_examples)
                                   if self.padded_examples else None,
                "mean_batch_size": (self.batch_examples / self.batches)
                                   if self.batches else None,
                "queue_wait": self.queue_wait.snapshot(),
                "device_time": self.device_time.snapshot(),
                "total_latency": self.total_latency.snapshot(),
            }
        if cache_stats is not None:
            snap["compile_cache"] = dict(cache_stats)
        return snap

    def export_to_summary(self, summary, step: int,
                          cache_stats: Optional[dict] = None) -> None:
        """Write the scalar snapshot through a ``visualization.Summary``
        (tfevents) writer under ``Serving/*`` tags."""
        snap = self.snapshot(cache_stats)
        flat: Dict[str, Optional[float]] = {
            "Serving/Requests": snap["requests"],
            "Serving/Rejected": snap["rejected"],
            "Serving/ThroughputEPS": snap["throughput_eps"],
            "Serving/ThroughputEPSLifetime": snap["throughput_eps_lifetime"],
            "Serving/BatchOccupancy": snap["batch_occupancy"],
            "Serving/QueueWaitP50": snap["queue_wait"]["p50_s"],
            "Serving/QueueWaitP99": snap["queue_wait"]["p99_s"],
            "Serving/DeviceTimeP50": snap["device_time"]["p50_s"],
            "Serving/DeviceTimeP99": snap["device_time"]["p99_s"],
            "Serving/LatencyP50": snap["total_latency"]["p50_s"],
            "Serving/LatencyP99": snap["total_latency"]["p99_s"],
        }
        cache = snap.get("compile_cache") or {}
        if cache.get("hit_rate") is not None:
            flat["Serving/CacheHitRate"] = cache["hit_rate"]
        for tag, value in flat.items():
            if value is not None:
                summary.add_scalar(tag, float(value), step)
        summary.flush()
