"""Serving metrics: latency histograms, throughput, batch occupancy.

Per-request latency is split where a serving engineer needs it split —
queue wait (batching-policy cost) vs device time (model cost) — each a
log-spaced histogram with percentile estimation, plus counters for
throughput, batch occupancy (how full the padded bucket actually was)
and the compile-cache hit rate.  ``export_to_summary`` writes the
snapshot through the existing ``visualization`` tfevents writers, so
serving dashboards land next to the training ones.
"""
from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional


def _log_edges() -> List[float]:
    # 10us .. ~100s, ~7% geometric steps: fine enough for p99 on a
    # millisecond-scale serving path, small enough to snapshot cheaply
    edges = []
    v = 1e-5
    while v < 100.0:
        edges.append(v)
        v *= 1.07
    return edges


_EDGES = _log_edges()


class LatencyHistogram:
    """Fixed log-bucket histogram over seconds, with percentile
    estimation (upper bucket edge — a conservative answer for a p99
    SLO check)."""

    def __init__(self):
        self._counts = [0] * (len(_EDGES) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        self._counts[bisect.bisect_left(_EDGES, seconds)] += 1
        self.count += 1
        self.sum += seconds
        if seconds > self.max:
            self.max = seconds

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None when empty."""
        if not self.count:
            return None
        rank = max(1, int(round(self.count * p / 100.0)))
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                return _EDGES[i] if i < len(_EDGES) else self.max
        return self.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_s": (self.sum / self.count) if self.count else None,
            "p50_s": self.percentile(50),
            "p99_s": self.percentile(99),
            "max_s": self.max if self.count else None,
        }


class ServingMetrics:
    """One engine's counters; thread-safe (batcher worker + callers)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queue_wait = LatencyHistogram()
        self.device_time = LatencyHistogram()
        self.total_latency = LatencyHistogram()
        self.requests = 0          # accepted submissions
        self.rejected = 0          # backpressure rejections
        self.examples = 0          # examples completed
        self.batches = 0           # device dispatches
        self.batch_examples = 0    # real examples across dispatches
        self.padded_examples = 0   # bucket slots across dispatches
        self.started_at = time.perf_counter()

    # -- recording ------------------------------------------------------ #
    def record_submit(self) -> None:
        with self._lock:
            self.requests += 1

    def record_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_batch(self, n_examples: int, bucket: int,
                     queue_waits_s, device_s: float) -> None:
        with self._lock:
            self.batches += 1
            self.examples += n_examples
            self.batch_examples += n_examples
            self.padded_examples += bucket
            self.device_time.observe(device_s)
            for w in queue_waits_s:
                self.queue_wait.observe(w)

    def record_done(self, total_s: float) -> None:
        with self._lock:
            self.total_latency.observe(total_s)

    # -- reading -------------------------------------------------------- #
    def snapshot(self, cache_stats: Optional[dict] = None) -> dict:
        with self._lock:
            elapsed = time.perf_counter() - self.started_at
            snap = {
                "requests": self.requests,
                "rejected": self.rejected,
                "examples": self.examples,
                "batches": self.batches,
                "throughput_eps": (self.examples / elapsed) if elapsed > 0 else 0.0,
                "batch_occupancy": (self.batch_examples / self.padded_examples)
                                   if self.padded_examples else None,
                "mean_batch_size": (self.batch_examples / self.batches)
                                   if self.batches else None,
                "queue_wait": self.queue_wait.snapshot(),
                "device_time": self.device_time.snapshot(),
                "total_latency": self.total_latency.snapshot(),
            }
        if cache_stats is not None:
            snap["compile_cache"] = dict(cache_stats)
        return snap

    def export_to_summary(self, summary, step: int,
                          cache_stats: Optional[dict] = None) -> None:
        """Write the scalar snapshot through a ``visualization.Summary``
        (tfevents) writer under ``Serving/*`` tags."""
        snap = self.snapshot(cache_stats)
        flat: Dict[str, Optional[float]] = {
            "Serving/Requests": snap["requests"],
            "Serving/Rejected": snap["rejected"],
            "Serving/ThroughputEPS": snap["throughput_eps"],
            "Serving/BatchOccupancy": snap["batch_occupancy"],
            "Serving/QueueWaitP50": snap["queue_wait"]["p50_s"],
            "Serving/QueueWaitP99": snap["queue_wait"]["p99_s"],
            "Serving/DeviceTimeP50": snap["device_time"]["p50_s"],
            "Serving/DeviceTimeP99": snap["device_time"]["p99_s"],
            "Serving/LatencyP50": snap["total_latency"]["p50_s"],
            "Serving/LatencyP99": snap["total_latency"]["p99_s"],
        }
        cache = snap.get("compile_cache") or {}
        if cache.get("hit_rate") is not None:
            flat["Serving/CacheHitRate"] = cache["hit_rate"]
        for tag, value in flat.items():
            if value is not None:
                summary.add_scalar(tag, float(value), step)
        summary.flush()
