"""DisaggCoordinator: phase-dedicated replica pools with KV-chain
migration between them.

Prefill and decode have opposite hardware appetites — prefill is one
big compute-bound matmul per request, decode is a memory-bound gather
over the KV arena per token — yet a co-located engine interleaves them
on the same slots, so every large prompt stalls every in-flight decode
(the ITL spike BENCH_LM_SERVE shows under prefill-heavy load).
Disaggregation (DistServe, OSDI'24; Splitwise, ISCA'24) runs the two
phases on *separate replicas* so the SLOs decouple: TTFT is the
prefill pool's problem, ITL the decode pool's.

The coordinator owns both pools and the hop between them:

- **prefill replicas** are plain :class:`LMServingEngine` instances
  constructed with ``migrate=<coordinator callback>``: they bucket-
  prefill, emit the FIRST token (TTFT is paid where the prompt is
  computed), then hand the request off instead of seating a decode
  slot.  They never compile or run the decode executable.
- **decode replicas** are untouched engines; they receive migrated
  requests via :meth:`LMServingEngine.adopt` and run the donated
  fixed-shape decode executable over chains they adopted rather than
  prefilled.
- **the hop** is :meth:`BlockPool.export_chain` on the prefill side →
  :meth:`BlockPool.adopt_chain` on the decode side, over
  ``chunked_device_put`` (the 32 MB rule).  Before exporting, the
  coordinator matches the DECODE replica's radix cache against the
  prompt (the trie is lock-guarded, so the cross-thread match from the
  prefill worker is safe): blocks the decode pool already holds do not
  travel — prefix sharing survives the hop — and only the unmatched
  tail is wired across.
- **faults**: the export runs under ``with_backoff`` around the
  ``serving.migrate`` fault site.  A transient retries; exhausted
  retries (``BackendLostError``) drop the payload and the decode
  replica RE-PREFILLS the prompt locally — deterministic prefill makes
  the recomputed KV bit-identical and the already-emitted first token
  is never re-picked, so the accepted stream completes exactly
  (counted in ``re_prefills``, never lost).
- **independent scaling**: :meth:`try_scale_up` adds a replica to ONE
  phase, gated on the :class:`PlacementPolicy`'s phase-tagged slots;
  :meth:`slo_controllers` wires two ladders — TTFT → prefill pool,
  ITL → decode pool — over the per-phase metrics the pools publish at
  ``serving/lm/prefill/*`` and ``serving/lm/decode/*``.

BigDL lineage: the original framework separated functional roles
across identical workers on one cluster (arXiv 1804.05839), and BigDL
2.0 ran heterogeneous pipelines side by side on shared infrastructure
(arXiv 2204.01715); phase-dedicated pools are that separation applied
to the two halves of autoregressive generation.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from bigdl_tpu.obs import get_registry, get_tracer
from bigdl_tpu.resilience.errors import BackendLostError
from bigdl_tpu.resilience.faults import fault_point
from bigdl_tpu.resilience.retry import with_backoff
from bigdl_tpu.serving.lm_engine import (KVHandoff, LMMetrics,
                                         LMServingEngine, LMStream)

_tracer = get_tracer()
log = logging.getLogger("bigdl_tpu.serving")


class DisaggCoordinator:
    """Run prefill and decode on separate replica pools of one model.

    Args:
        model: a built ``TransformerLM`` — shared by every replica
            (params are read-only at serve time).
        prefill_replicas / decode_replicas: initial pool sizes.
        placement: optional
            :class:`~bigdl_tpu.serving.placement.PlacementPolicy`;
            when given, every replica acquires a phase-tagged mesh
            slot (``acquire(phase=...)``) and scale-up is refused once
            the device set is full.  Without it replicas share the
            default device (the CPU test/bench posture).
        max_replicas_per_phase: scale-up ceiling per phase when no
            placement policy bounds it.
        migrate_retries / migrate_base_delay_s: ``with_backoff``
            parameters for the chain export at the ``serving.migrate``
            fault site.
        name: prefix for replica engine names
            (``<name>-prefill0``, ``<name>-decode0``, ...).
        spec: optional speculative-decoding config — applied to DECODE
            replicas only (a prefill replica never decodes).
        **engine_kwargs: forwarded to every
            :class:`LMServingEngine` (slots, cache_len, block_len,
            num_blocks, temperature, eos_id, ...).

    Each phase publishes ONE shared :class:`LMMetrics` (all replicas
    of a phase record into the same histograms) under
    ``serving/lm/prefill/`` and ``serving/lm/decode/`` — the two SLO
    ladders each watch their own phase's latency, which is the whole
    point of disaggregating.
    """

    def __init__(self, model, *,
                 prefill_replicas: int = 1,
                 decode_replicas: int = 1,
                 placement=None,
                 max_replicas_per_phase: int = 4,
                 migrate_retries: int = 2,
                 migrate_base_delay_s: float = 0.05,
                 name: str = "disagg",
                 spec=None,
                 **engine_kwargs):
        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError("each phase needs at least one replica")
        self.model = model
        self.name = name
        self.placement = placement
        self.max_replicas_per_phase = int(max_replicas_per_phase)
        self.migrate_retries = int(migrate_retries)
        self.migrate_base_delay_s = float(migrate_base_delay_s)
        self._spec = spec
        self._kw = dict(engine_kwargs)
        slots = int(self._kw.get("slots", 8))
        self._prefill_metrics = LMMetrics(slots * prefill_replicas)
        self._decode_metrics = LMMetrics(slots * decode_replicas)
        self._lock = threading.Lock()
        self._slices: Dict[str, object] = {}   # engine name -> MeshSlice
        self._rr = 0                           # round-robin submit cursor
        self.migrations = 0
        self.migrated_blocks = 0
        self.lost_payloads = 0
        self._closing = False
        # decode pool first: the migrate callback needs a live target
        # before any prefill replica can finish its first request
        self.decode: List[LMServingEngine] = [
            self._make_engine("decode", i) for i in range(decode_replicas)]
        self.prefill: List[LMServingEngine] = [
            self._make_engine("prefill", i) for i in range(prefill_replicas)]

    # -- replica construction ------------------------------------------- #
    def _make_engine(self, phase: str, idx: int) -> LMServingEngine:
        ename = f"{self.name}-{phase}{idx}"
        slot = None
        if self.placement is not None:
            slot = self.placement.acquire(phase=phase)
            if slot is None:
                raise RuntimeError(
                    f"no free placement slot for {ename} "
                    f"({self.placement!r})")
        kw = dict(self._kw)
        if phase == "prefill":
            kw["migrate"] = self._migrate
            metrics, prefix = self._prefill_metrics, "serving/lm/prefill/"
        else:
            if self._spec is not None:
                kw["spec"] = self._spec
            metrics, prefix = self._decode_metrics, "serving/lm/decode/"
        try:
            eng = LMServingEngine(self.model, name=ename, placement=slot,
                                  metrics=metrics, metrics_prefix=prefix,
                                  **kw)
        except BaseException:
            if slot is not None:
                self.placement.release(slot)
            raise
        if slot is not None:
            self._slices[ename] = slot
        # a decode replica is indistinguishable from a co-located engine
        # from the inside (migrate=None); the pool it serves is not
        eng.phase = phase
        return eng

    # -- the migration hop ---------------------------------------------- #
    def _pick_decode(self) -> LMServingEngine:
        """Least-loaded decode replica (active + pending adoptions)."""
        return min(self.decode,
                   key=lambda e: (e._n_active + len(e._adopt_q)
                                  + len(e._prefilling)))

    def _migrate(self, h: KVHandoff, blocks, src_pool) -> None:
        """Prefill-engine callback (runs in ITS worker thread, with the
        chain's references still held by the caller): pick a decode
        replica, dedupe against its radix, wire the unmatched tail
        across, enqueue the adoption."""
        eng = self._pick_decode()
        t = int(h.prompt0.shape[0])
        n_prompt = src_pool.blocks_for(t)
        matched: List[int] = []
        if eng.radix is not None:
            # lock-guarded trie: safe from this (foreign) thread.
            # Matched blocks are retained in the DECODE pool for the
            # adoption — they are the part of the chain that does not
            # need to travel.
            matched = eng.radix.match(h.prompt0)
        tail = list(blocks[len(matched):n_prompt])

        def _export():
            fault_point("serving.migrate", rid=h.rid, src=h.src_name,
                        dst=eng.name, blocks=len(tail))
            return src_pool.export_chain(tail)

        try:
            h.payload = with_backoff(
                _export, retries=self.migrate_retries,
                base_delay_s=self.migrate_base_delay_s,
                label=f"{self.name}.migrate")
        except BackendLostError:
            # the wire is gone mid-hop; the chain still exists only on
            # the (about-to-release) prefill side, so the decode
            # replica recomputes it.  Deterministic prefill + the
            # carried first token keep the stream exact.
            log.warning("%s: migrate payload lost for %s; decode "
                        "replica %s will re-prefill", self.name, h.rid,
                        eng.name)
            h.payload = None
        h.matched = matched
        try:
            eng.adopt(h)
        except BaseException:
            if matched:
                eng.pool.release(matched)
            raise
        with self._lock:
            self.migrations += 1
            if h.payload is None:
                self.lost_payloads += 1
            else:
                self.migrated_blocks += int(h.payload["blocks"])

    # -- client API ------------------------------------------------------ #
    def submit(self, prompt_ids, *, max_new_tokens=None, temperature=None,
               eos_id=None, rng=None) -> LMStream:
        """Enqueue one prompt on a prefill replica (round-robin); the
        returned stream completes on whichever decode replica adopts
        the chain — the client never sees the hop."""
        with self._lock:
            if self._closing:
                from bigdl_tpu.serving.batcher import ServingClosed
                raise ServingClosed("DisaggCoordinator is closed")
            eng = self.prefill[self._rr % len(self.prefill)]
            self._rr += 1
        return eng.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, eos_id=eos_id, rng=rng)

    def warmup(self) -> int:
        """AOT-compile every replica's executables — including the
        decode pools' adopt scatters for every power-of-two wire width
        a migration can arrive at, so the first hop never pays a
        mid-traffic compile.  Returns the executable count."""
        n = 0
        for eng in self.prefill + self.decode:
            n += eng.warmup()
        for eng in self.decode:
            widths, w = [], 1
            while w < eng.table_width:
                widths.append(w)
                w *= 2
            widths.append(w)
            n += eng.pool.warmup_adopt(widths)
        return n

    # -- independent phase scaling --------------------------------------- #
    def try_scale_up(self, phase: str) -> bool:
        """Add one replica to ``phase`` ("prefill" | "decode").  Returns
        False — without side effects — when the phase is at its ceiling
        or the placement policy has no free slot; truthiness is the
        :class:`SLOController` scale-actuator contract (falsy ⇒ the
        ladder falls through to admission control)."""
        if phase not in ("prefill", "decode"):
            raise ValueError(f"unknown phase {phase!r}")
        with self._lock:
            if self._closing:
                return False
            pool = self.prefill if phase == "prefill" else self.decode
            idx = len(pool)
            if idx >= self.max_replicas_per_phase:
                return False
            if self.placement is not None and self.placement.headroom() == 0:
                return False
            try:
                eng = self._make_engine(phase, idx)
            except RuntimeError:
                return False   # raced out of the last placement slot
            pool.append(eng)
        metrics = (self._prefill_metrics if phase == "prefill"
                   else self._decode_metrics)
        with metrics._lock:
            metrics.slots += eng.slots
        log.info("%s: scaled %s pool to %d replicas", self.name, phase,
                 idx + 1)
        _tracer.instant("disagg/scale_up", cat="serve", phase=phase,
                        replicas=idx + 1)
        return True

    def slo_controllers(self, *, ttft_target_s: float, itl_target_s: float,
                        **ctl_kwargs):
        """Two independent ladders over the per-phase histograms:
        windowed TTFT p99 grows the PREFILL pool, windowed decode-ITL
        p99 grows the DECODE pool.  Extra kwargs go to both
        :class:`~bigdl_tpu.traffic.slo.SLOController` constructors.
        Returned un-started; callers tick or ``start()`` them."""
        from bigdl_tpu.traffic.slo import SLOController
        ttft_ctl = SLOController(
            histogram=self._prefill_metrics.ttft,
            target_p99_s=ttft_target_s,
            scale_up=lambda: self.try_scale_up("prefill"),
            **ctl_kwargs)
        itl_ctl = SLOController(
            histogram=self._decode_metrics.itl_decode,
            target_p99_s=itl_target_s,
            scale_up=lambda: self.try_scale_up("decode"),
            **ctl_kwargs)
        return ttft_ctl, itl_ctl

    # -- observability ---------------------------------------------------- #
    @property
    def prefill_metrics(self) -> LMMetrics:
        return self._prefill_metrics

    @property
    def decode_metrics(self) -> LMMetrics:
        return self._decode_metrics

    @property
    def metrics(self) -> LMMetrics:
        """Engine-compat alias (bench stage helpers read
        ``eng.metrics``): the DECODE pool's metrics — the client-visible
        token cadence (ITL, tokens/sec, completions) lives where decode
        runs; TTFT is client-measured and ``prefill_metrics`` holds the
        server-side view."""
        return self._decode_metrics

    @property
    def decode_attn(self) -> str:
        return self.decode[0].decode_attn

    def stats(self) -> dict:
        with self._lock:
            out = {
                "name": self.name,
                "prefill_replicas": len(self.prefill),
                "decode_replicas": len(self.decode),
                "migrations": self.migrations,
                "migrated_blocks": self.migrated_blocks,
                "lost_payloads": self.lost_payloads,
            }
        out["re_prefills"] = sum(e.re_prefills for e in self.decode)
        out["adopted"] = sum(e.adopted for e in self.decode)
        out["phase_counts"] = (self.placement.phase_counts()
                               if self.placement is not None else None)
        out["prefill"] = self._prefill_metrics.snapshot()
        out["decode"] = self._decode_metrics.snapshot()
        out["engines"] = {e.name: e.stats()
                          for e in self.prefill + self.decode}
        return out

    # -- lifecycle -------------------------------------------------------- #
    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Drain prefill replicas first (their last requests migrate
        out), then decode replicas, then release placement slots."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        for eng in self.prefill:
            eng.close(timeout)
        for eng in self.decode:
            eng.close(timeout)
        if self.placement is not None:
            for ename, slot in self._slices.items():
                try:
                    self.placement.release(slot)
                except Exception:
                    log.exception("releasing %s's slot failed", ename)
            self._slices.clear()

    def __enter__(self) -> "DisaggCoordinator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
