"""Disaggregated prefill/decode serving: phase-dedicated replica
pools, KV-chain migration over the chunked transfer path, and
independent per-phase SLO scaling.  See ``coordinator.py`` for the
design notes.
"""
from bigdl_tpu.serving.disagg.coordinator import DisaggCoordinator

__all__ = ["DisaggCoordinator"]
