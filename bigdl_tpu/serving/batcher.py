"""Dynamic request batcher: bounded queue, max-batch/max-wait policy,
power-of-two shape buckets, backpressure.

BigDL's serving story (arXiv 1804.05839) is batched forward passes over
a shared immutable model; on JAX/XLA the extra constraint is that every
novel batch shape is a fresh compile, so the batcher rounds every
dispatch UP to a configured bucket (powers of two by default) and the
compile cache stays small and warm.  Policy knobs follow the classic
serving trade-off: ``max_batch_size`` bounds device latency,
``max_wait_ms`` bounds queueing latency (a lone request is flushed when
its wait expires — the empty-queue timeout flush), and the bounded
queue rejects with an error instead of growing without bound when the
device falls behind (backpressure beats OOM).

Ordering is deterministic: responses complete in submission order —
one worker drains the FIFO queue and resolves futures sequentially.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

import numpy as np

from bigdl_tpu.obs.tracer import (clear_request_context, get_tracer,
                                  mint_request_id, set_request_context)
from bigdl_tpu.resilience.errors import (ServingDeadlineExceeded,
                                         ServingOverloaded,
                                         TransientBackendError)

_tracer = get_tracer()


class ServingQueueFull(ServingOverloaded):
    """Backpressure rejection: the bounded request queue is full.
    A :class:`~bigdl_tpu.resilience.errors.ServingOverloaded`, so the
    taxonomy classifies it transient — retry once load drains."""


class ServingClosed(RuntimeError):
    """The batcher/engine was closed; the request was not served."""


def count_rejection() -> None:
    """Process-wide typed-shed accounting: every ServingOverloaded
    raised at an admission seam (batcher, LM engine, SLO admission
    control) lands here, on top of the per-engine ``serving/rejected``
    / ``serving/lm/rejected`` gauges — one counter the SLO controller
    and the goodput metric can read without knowing which engine shed.
    Also the shed-burst incident seam: the flight recorder counts
    sheds here and dumps ONE correlated bundle when a burst crosses
    its threshold (no-op while the recorder is disarmed)."""
    from bigdl_tpu.obs import get_registry
    get_registry().counter("serving/rejected_total", unit="requests").add(1)
    try:
        from bigdl_tpu.obs import flight
        flight.note_shed()
    except Exception:
        pass  # forensics must never turn a shed into a crash


def power_of_two_buckets(max_batch_size: int) -> tuple:
    """1, 2, 4, ... up to (and always including) max_batch_size."""
    buckets = []
    b = 1
    while b < max_batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch_size)
    return tuple(buckets)


def _tree_np(y):
    """Pull a model output — a single array or any pytree of arrays
    (multi-headed models, Tables) — to host numpy, leaf-wise."""
    if hasattr(y, "shape"):
        return np.asarray(y)
    import jax
    return jax.tree_util.tree_map(np.asarray, y)


def _tree_slice(y, lo: int, hi: int):
    """Row-slice every leaf: the per-request slice-back."""
    if hasattr(y, "shape"):
        return y[lo:hi]
    import jax
    return jax.tree_util.tree_map(lambda a: a[lo:hi], y)


def _tree_concat(parts: list):
    """Concatenate chunked outputs leaf-wise along the batch dim."""
    if hasattr(parts[0], "shape"):
        return np.concatenate(parts, 0)
    import jax
    return jax.tree_util.tree_map(
        lambda *leaves: np.concatenate(leaves, 0), *parts)


class _Request:
    __slots__ = ("x", "n", "future", "t_enqueue", "rid", "deadline_at")

    def __init__(self, x, n: int, future: Future, rid: str,
                 deadline_at: Optional[float] = None):
        self.x = x
        self.n = n
        self.future = future
        self.t_enqueue = time.perf_counter()
        self.rid = rid
        # absolute monotonic deadline, minted at enqueue (None = no
        # budget): checked when the batch is ASSEMBLED, so an expired
        # request is shed before it costs a device dispatch
        self.deadline_at = deadline_at


def _safe_resolve(future: Future, *, result=None, exc=None) -> None:
    """Resolve a future exactly once, tolerating cancellation and the
    close()-timeout sweep racing a late worker (InvalidStateError)."""
    if future.cancelled():
        return
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
    except Exception:
        pass  # already resolved by the other side of the race


class DynamicBatcher:
    """Gathers requests into bucket-padded batches for ``run_batch``.

    ``run_batch(x_padded) -> y_padded`` sees only bucket-shaped arrays
    (leading dim in ``buckets``); the batcher pads with zero rows and
    slices the per-request outputs back out.  The output may be a
    single array or any pytree of arrays (multi-headed models, Tables)
    whose every leaf carries the batch dim first — slice-back and
    oversized-chunk reassembly are leaf-wise.  A single request larger
    than ``max_batch_size`` is served alone, chunked into
    ``max_batch_size`` slices (each slice still bucket-shaped).
    """

    def __init__(self, run_batch: Callable, *,
                 max_batch_size: int = 32,
                 max_wait_ms: float = 5.0,
                 max_queue: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 metrics=None,
                 pool=None):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._run = run_batch
        self._max_batch = int(max_batch_size)
        self._max_wait = max(0.0, float(max_wait_ms)) / 1000.0
        self._max_queue = int(max_queue)
        self.buckets = tuple(sorted(set(int(b) for b in (
            buckets if buckets is not None
            else power_of_two_buckets(max_batch_size)))))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError("buckets must be positive ints")
        self._metrics = metrics
        self._queue: "deque[_Request]" = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._inflight: list = []  # requests inside the current dispatch
        self._worker_done = Future()
        if pool is not None:
            # reuse the shared Engine host pool (one long-running slot)
            pool.invoke([self._loop_guard])
        else:
            threading.Thread(target=self._loop_guard, daemon=True,
                             name="bigdl-tpu-batcher").start()
        # flight-recorder hookup (latest batcher wins the key; weakref
        # so the provider never keeps a closed batcher alive)
        try:
            from bigdl_tpu.obs import flight
            import weakref
            wself = weakref.ref(self)

            def _active_rids():
                b = wself()
                if b is None:
                    return []
                with b._cv:
                    return ([r.rid for r in b._queue]
                            + [r.rid for r in b._inflight])
            flight.register_requests("batcher", _active_rids)
        except Exception:
            pass

    # ------------------------------------------------------------------ #
    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n (n must fit the largest)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"no bucket holds {n} rows "
                         f"(largest is {self.buckets[-1]})")

    def submit(self, x, n: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Future:
        """Enqueue a request of ``n`` examples (leading dim of ``x``);
        raises ServingQueueFull (a ServingOverloaded) when the bounded
        queue is full.

        ``deadline_s`` is an optional wall-clock budget minted here:
        a request still queued when it expires is shed at batch
        assembly (before any device work) with the typed
        :class:`~bigdl_tpu.resilience.errors.ServingDeadlineExceeded`.
        Cancelling the returned future before dispatch is likewise
        honored at assembly: the request never reaches the device."""
        x = np.asarray(x)
        if n is None:
            n = int(x.shape[0]) if x.ndim else 1
        if deadline_s is not None and float(deadline_s) <= 0.0:
            if self._metrics is not None:
                self._metrics.record_reject()
            count_rejection()
            raise ServingDeadlineExceeded(
                f"deadline_s={deadline_s} already expired at enqueue")
        # resilience hook: chaos exercises the admission path here.  An
        # injected transient is surfaced as the SAME typed shed a real
        # overload produces, so clients and the loadgen account for it
        # identically; backend_lost passes through unconverted.
        from bigdl_tpu.resilience.faults import fault_point
        try:
            fault_point("serving.enqueue", n=n)
        except ServingOverloaded:
            raise
        except TransientBackendError as e:
            count_rejection()
            raise ServingOverloaded(
                f"admission shed (injected at serving.enqueue): {e}") from e
        fut: Future = Future()
        rid = mint_request_id()
        with self._cv:
            if self._stop:
                raise ServingClosed("batcher is closed")
            if len(self._queue) >= self._max_queue:
                if self._metrics is not None:
                    self._metrics.record_reject()
                count_rejection()
                raise ServingQueueFull(
                    f"request queue full ({self._max_queue} pending); "
                    "retry later or raise max_queue")
            self._queue.append(_Request(
                x, n, fut, rid,
                deadline_at=(time.monotonic() + float(deadline_s)
                             if deadline_s is not None else None)))
            depth = len(self._queue)
            self._cv.notify()
        fut.request_id = rid  # clients correlate responses with traces
        if self._metrics is not None:
            self._metrics.record_submit()
        if _tracer.sampled(rid):
            _tracer.instant("serve/enqueue", cat="serve", n=n,
                            queue_depth=depth, request_id=rid)
        else:
            _tracer.instant("serve/enqueue", cat="serve", n=n,
                            queue_depth=depth)
        return fut

    def pending(self) -> int:
        with self._cv:
            return len(self._queue)

    def set_max_queue(self, n: int) -> None:
        """Admission-control actuator: rebind the queue bound live.  The
        SLO controller shrinks it when saturated (shed instead of queue
        collapse) and restores it once p99 recovers; already-queued
        requests are never dropped, only new arrivals see the bound."""
        with self._cv:
            self._max_queue = max(0, int(n))

    @property
    def max_queue(self) -> int:
        with self._cv:
            return self._max_queue

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stop accepting requests, drain what is queued, join the
        worker.  GUARANTEE: no accepted request's future is left
        hanging — if the worker cannot finish the drain inside
        ``timeout`` (e.g. the device call is wedged against a dead
        tunnel), every still-unresolved queued AND in-flight future is
        failed with :class:`ServingClosed` before close returns."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        try:
            self._worker_done.result(timeout=timeout)
        except Exception:
            # drain timed out: sweep everything still unresolved.  A
            # late worker completion races these sets; both sides go
            # through _safe_resolve, so whichever lands first wins and
            # the loser is a no-op.
            with self._cv:
                leftovers = list(self._queue) + list(self._inflight)
                self._queue.clear()
            for r in leftovers:
                _safe_resolve(r.future, exc=ServingClosed(
                    "batcher closed before this request was served"))

    # ------------------------------------------------------------------ #
    def _loop_guard(self) -> None:
        try:
            self._loop()
        finally:
            # requests that raced past the close gate still get answers
            with self._cv:
                leftovers = list(self._queue)
                self._queue.clear()
            for r in leftovers:
                _safe_resolve(r.future,
                              exc=ServingClosed("batcher closed"))
            try:
                self._worker_done.set_result(None)
            except Exception:
                pass  # a crashed-and-restarted guard already resolved it

    def _shed_dead(self, r: _Request) -> bool:
        """Lifecycle gate at batch assembly: a cancelled future or a
        blown deadline never reaches the device.  Returns True when
        the request was consumed (shed) here."""
        if r.future.cancelled():
            from bigdl_tpu.obs import get_registry
            get_registry().counter("serving/lifecycle/cancelled").add(1)
            if _tracer.sampled(r.rid):
                _tracer.instant("serve/lifecycle_shed", cat="serve",
                                request_id=r.rid, reason="cancelled")
            return True
        if r.deadline_at is not None and time.monotonic() >= r.deadline_at:
            if self._metrics is not None:
                self._metrics.record_reject()
            count_rejection()
            from bigdl_tpu.obs import get_registry
            get_registry().counter(
                "serving/lifecycle/expired_preadmission").add(1)
            _safe_resolve(r.future, exc=ServingDeadlineExceeded(
                "deadline expired while queued; request shed before "
                "dispatch"))
            if _tracer.sampled(r.rid):
                _tracer.instant("serve/lifecycle_shed", cat="serve",
                                request_id=r.rid, reason="deadline")
            return True
        return False

    def _take_batch(self) -> Optional[list]:
        """Block for the first request, then gather until the batch is
        full or the oldest request's wait budget expires.  Requests
        whose future was cancelled or whose deadline expired while
        queued are shed here, before any device work."""
        with self._cv:
            while True:
                while not self._queue:
                    if self._stop:
                        return None
                    self._cv.wait(timeout=0.05)
                first = self._queue.popleft()
                if not self._shed_dead(first):
                    break
            if first.n >= self._max_batch:
                return [first]  # full (or oversized: served alone, chunked)
            batch, total = [first], first.n
            deadline = first.t_enqueue + self._max_wait
            while total < self._max_batch:
                if self._queue:
                    nxt = self._queue[0]
                    if total + nxt.n > self._max_batch:
                        break  # never split a request across batches
                    r = self._queue.popleft()
                    if self._shed_dead(r):
                        continue
                    batch.append(r)
                    total += r.n
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._stop:
                    break  # timeout flush (possibly a partial batch)
                self._cv.wait(timeout=min(remaining, 0.05))
            return batch

    def _dispatch(self, xs: list, bucket: int, rids=()):
        """Pad a concatenated batch to ``bucket`` rows and run it.
        ``rids`` (the batch's request ids) ride the batch-level spans
        and — via the request context — reach layers below ``run_batch``
        (the ReplicaSet failover hop) that only see a padded array."""
        total = sum(int(x.shape[0]) for x in xs)
        traced = [r for r in rids if _tracer.sampled(r)]
        with _tracer.span("serve/assemble", cat="serve",
                          requests=len(xs), rows=total, bucket=bucket,
                          **({"request_ids": traced} if traced else {})):
            parts = list(xs)
            if bucket > total:
                parts.append(np.zeros(
                    (bucket - total,) + tuple(xs[0].shape[1:]),
                    xs[0].dtype))
            joined = parts[0] if len(parts) == 1 else np.concatenate(parts, 0)
        set_request_context(rids)
        try:
            with _tracer.span("serve/device", cat="serve", bucket=bucket,
                              **({"request_ids": traced} if traced
                                 else {})):
                return self._run(joined)
        finally:
            clear_request_context()

    def _serve_batch(self, batch: list) -> None:
        t_start = time.perf_counter()
        waits = [t_start - r.t_enqueue for r in batch]
        total = sum(r.n for r in batch)
        rids = [r.rid for r in batch]
        if _tracer.enabled:
            # queue-wait spans are known only now — record retroactively
            # from each request's enqueue timestamp
            for r, w in zip(batch, waits):
                args = {"n": r.n}
                if _tracer.sampled(r.rid):
                    args["request_id"] = r.rid
                _tracer.add_complete("serve/queue_wait", r.t_enqueue, w,
                                     cat="serve", args=args)
        try:
            if total > self._max_batch:
                # one oversized request: chunk through max-size slices
                (req,) = batch
                outs = []
                for i in range(0, req.n, self._max_batch):
                    piece = req.x[i:i + self._max_batch]
                    b = self.bucket_for(int(piece.shape[0]))
                    y = _tree_np(self._dispatch([piece], b, rids))
                    outs.append(_tree_slice(y, 0, int(piece.shape[0])))
                result = _tree_concat(outs)
                bucket_rows = sum(
                    self.bucket_for(min(self._max_batch, req.n - i))
                    for i in range(0, req.n, self._max_batch))
                ys = [result]
            else:
                bucket_rows = self.bucket_for(total)
                y = _tree_np(self._dispatch([r.x for r in batch],
                                            bucket_rows, rids))
                ys, off = [], 0
                for r in batch:
                    ys.append(_tree_slice(y, off, off + r.n))
                    off += r.n
        except Exception as e:
            for r in batch:
                _safe_resolve(r.future, exc=e)
            return
        device_s = time.perf_counter() - t_start
        if self._metrics is not None:
            self._metrics.record_batch(total, bucket_rows, waits, device_s)
        with _tracer.span("serve/slice_back", cat="serve",
                          requests=len(batch), rows=total):
            done = time.perf_counter()
            for r, yr in zip(batch, ys):  # submission order -> response order
                _safe_resolve(r.future, result=yr)
                if self._metrics is not None:
                    self._metrics.record_done(done - r.t_enqueue)
        if _tracer.enabled:
            # the per-request ROOT span (enqueue -> resolved): every
            # phase above nests inside it by interval containment, so
            # span_tree() gets its one top-level node for free
            for r in batch:
                if _tracer.sampled(r.rid):
                    _tracer.add_complete(
                        "serve/request", r.t_enqueue,
                        done - r.t_enqueue, cat="serve",
                        args={"request_id": r.rid, "n": r.n})

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            with self._cv:
                self._inflight = list(batch)
            try:
                self._serve_batch(batch)
            finally:
                with self._cv:
                    self._inflight = []
