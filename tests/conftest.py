"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised without TPU pods (the analog of the reference's
simulated-multinode trick: DistriOptimizerSpec runs 4 "nodes" as 4
partitions in one local[1] JVM, optim/DistriOptimizerSpec.scala:39-43).

Note: the environment's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, so env vars are too late here — we switch platform via
jax.config before the first backend use instead.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_engine():
    from bigdl_tpu.utils.engine import Engine
    Engine.reset()
    os.environ["BIGDL_TPU_CHECK_SINGLETON"] = "0"
    yield


@pytest.fixture
def rng():
    return jax.random.PRNGKey(42)


@pytest.fixture
def nprng():
    return np.random.RandomState(42)
