"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised without TPU pods (the analog of the reference's
simulated-multinode trick: DistriOptimizerSpec runs 4 "nodes" as 4
partitions in one local[1] JVM, optim/DistriOptimizerSpec.scala:39-43).

Note: the environment's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, so env vars are too late here — we switch platform via
jax.config before the first backend use instead.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--full", action="store_true", default=False,
        help="CI-full mode: run the slow tests too (multihost subprocess "
             "jobs, exhaustive torch oracles)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, skipped unless --full or "
        "BIGDL_TPU_FULL_TESTS=1 (driver windows need the default run "
        "under ~8 minutes; full coverage stays one flag away)")
    config.addinivalue_line(
        "markers", "faults: deterministic fault-injection matrix "
        "(bigdl_tpu.resilience) — fast, tier-1, CPU-only; selectable "
        "alone via -m faults as the CI resilience gate")


def pytest_collection_modifyitems(config, items):
    full = (config.getoption("--full")
            or os.environ.get("BIGDL_TPU_FULL_TESTS") == "1"
            or (config.getoption("-m") and "slow" in config.getoption("-m")))
    if full:
        return
    skip = pytest.mark.skip(
        reason="slow: run with --full or BIGDL_TPU_FULL_TESTS=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _reset_engine():
    from bigdl_tpu.utils.engine import Engine
    Engine.reset()
    os.environ["BIGDL_TPU_CHECK_SINGLETON"] = "0"
    yield


@pytest.fixture(scope="session")
def fake_mesh():
    """The 8-virtual-device CPU mesh this conftest forces via XLA_FLAGS
    — the shared fixture for every multi-chip test (placement, tensor
    parallel, grad accum).  Returns the device tuple; skips (instead of
    silently passing on one device) when the flag did not take, e.g.
    when a backend was initialized before conftest ran."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip(f"needs the 8-device CPU mesh, got {len(devs)} "
                    "device(s) (XLA_FLAGS applied too late?)")
    return tuple(devs[:8])


@pytest.fixture
def rng():
    return jax.random.PRNGKey(42)


@pytest.fixture
def nprng():
    return np.random.RandomState(42)


def corrupt_variants(good: bytes, n_trials: int, seed: int = 0):
    """Yield (trial, corrupted_bytes) for reader fuzz tests: truncations,
    header-region bit flips, and garbage tails — one shared mutation
    schedule so the t7 and seqfile fuzz tests cannot drift."""
    rng = np.random.RandomState(seed)
    for trial in range(n_trials):
        data = bytearray(good)
        mode = trial % 3
        if mode == 0:
            data = data[: rng.randint(1, len(data))]
        elif mode == 1:
            data[rng.randint(0, min(64, len(data)))] ^= 0xFF
        else:
            data = data[: rng.randint(8, len(data))] + bytes(
                rng.randint(0, 256, size=16, dtype=np.uint8))
        yield trial, bytes(data)
