"""End-to-end example program tests (ref example/imageclassification/
ImagePredictor.scala, example/loadmodel/ModelValidator.scala)."""
import os
import struct

import numpy as np
import pytest


def _write_mnist_idx(folder, n=32, train=False):
    """Tiny valid IDX pair with a learnable label<->pixel pattern."""
    prefix = "train" if train else "t10k"
    rng = np.random.RandomState(0)
    images = rng.randint(0, 50, size=(n, 28, 28)).astype(np.uint8)
    labels = (np.arange(n) % 10).astype(np.uint8)
    for i in range(n):
        images[i, labels[i] * 2:labels[i] * 2 + 3, :] += 150
    with open(os.path.join(folder, f"{prefix}-images-idx3-ubyte"), "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with open(os.path.join(folder, f"{prefix}-labels-idx1-ubyte"), "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return images, labels


@pytest.fixture(scope="module")
def lenet_file(tmp_path_factory):
    """A briefly-trained LeNet saved to disk."""
    from bigdl_tpu import nn
    from bigdl_tpu.models.lenet import LeNet5

    model = LeNet5(10).build(seed=1)
    path = str(tmp_path_factory.mktemp("models") / "lenet.bin")
    model.save(path, overwrite=True)
    return path


class TestLoadModelExample:
    def test_bigdl_model_on_mnist(self, lenet_file, tmp_path, capsys):
        from bigdl_tpu.example.load_model import main

        _write_mnist_idx(str(tmp_path))
        main(["--modelType", "bigdl", "--model", lenet_file,
              "-f", str(tmp_path), "--dataset", "mnist", "-b", "16"])
        out = capsys.readouterr().out
        assert "Top1Accuracy" in out and "Top5Accuracy" in out

    def test_torch_model_roundtrip(self, tmp_path, capsys):
        from bigdl_tpu import nn
        from bigdl_tpu.example.load_model import main

        model = nn.Sequential(nn.Reshape((784,)), nn.Linear(784, 10),
                              nn.LogSoftMax()).build(seed=3)
        t7 = str(tmp_path / "model.t7")
        model.save_torch(t7, overwrite=True)
        _write_mnist_idx(str(tmp_path))
        main(["--modelType", "torch", "--model", t7,
              "-f", str(tmp_path), "--dataset", "mnist", "-b", "16"])
        assert "Top1Accuracy" in capsys.readouterr().out

    def test_caffe_requires_factory(self, lenet_file, tmp_path):
        from bigdl_tpu.example.load_model import main

        with pytest.raises(SystemExit):
            main(["--modelType", "caffe", "--model", lenet_file,
                  "-f", str(tmp_path)])


class TestImageClassificationExample:
    @pytest.fixture
    def image_folder(self, tmp_path):
        PIL = pytest.importorskip("PIL")
        from PIL import Image

        rng = np.random.RandomState(1)
        for cls in ["cat", "dog"]:
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                arr = rng.randint(0, 255, size=(40, 40, 3)).astype(np.uint8)
                Image.fromarray(arr).save(str(d / f"{cls}{i}.png"))
        return str(tmp_path)

    def test_predict_folder_lenet(self, lenet_file, image_folder, capsys):
        from bigdl_tpu.example.image_classification import main

        main(["--model", lenet_file, "-f", image_folder,
              "--modelType", "lenet", "-b", "4", "--topN", "2"])
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 4  # 2 classes x 2 images
        # each line: "<path>: <c1> <c2>" with 1-based classes
        for line in out:
            classes = line.split(": ")[1].split()
            assert len(classes) == 2
            assert all(1 <= int(c) <= 10 for c in classes)

    def test_grey_from_bgr(self):
        from bigdl_tpu.dataset.image import GreyFromBGR
        from bigdl_tpu.dataset.types import LabeledImage

        img = LabeledImage(np.ones((3, 4, 4), np.float32) * 100, 1.0)
        grey = GreyFromBGR().transform_one(img)
        assert grey.data.shape == (1, 4, 4)
        np.testing.assert_allclose(grey.data, 100.0, rtol=1e-5)
