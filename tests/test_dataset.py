"""Data pipeline tests (ref dataset/ transformer specs)."""
import numpy as np
import pytest

from bigdl_tpu.dataset import (
    DataSet, MiniBatch, Sample, ByteRecord, cifar, mnist,
)
from bigdl_tpu.dataset.dataset import DistributedDataSet, LocalArrayDataSet
from bigdl_tpu.dataset.seqfile import read_shard, write_shard, write_sharded
from bigdl_tpu.dataset.transformer import FuncTransformer, Prefetcher, SampleToBatch
from bigdl_tpu.dataset import image, text
from bigdl_tpu.dataset.types import LabeledImage, LabeledSentence


class TestDataSetCore:
    def test_local_array_infinite_train(self):
        ds = DataSet.array([1, 2, 3])
        it = ds.data(train=True)
        got = [next(it) for _ in range(7)]
        assert len(got) == 7 and set(got) <= {1, 2, 3}

    def test_eval_one_pass(self):
        ds = DataSet.array([1, 2, 3])
        assert list(ds.data(train=False)) == [1, 2, 3]

    def test_shuffle_changes_order(self):
        ds = DataSet.array(list(range(100)))
        ds.shuffle()
        it = ds.data(train=True)
        first_pass = [next(it) for _ in range(100)]
        assert first_pass != list(range(100))
        assert sorted(first_pass) == list(range(100))

    def test_transform_chain(self):
        ds = DataSet.array([Sample(np.ones(3) * i, np.asarray(i)) for i in range(10)])
        batched = ds >> SampleToBatch(4)
        batches = list(batched.data(train=False))
        assert len(batches) == 3
        assert batches[0].data.shape == (4, 3)
        assert batches[2].data.shape == (2, 3)

    def test_distributed_sharding(self):
        ds = DistributedDataSet(list(range(10)), process_index=1, process_count=4)
        assert ds.size() == 10
        assert sorted(ds.local.records) == [1, 5, 9]


class TestSampleToBatch:
    def test_padding(self):
        samples = [Sample(np.ones(n), np.ones(n)) for n in (3, 5, 2)]
        tr = SampleToBatch(3, feature_padding=0.0, label_padding=-1.0)
        (b,) = list(tr(iter(samples)))
        assert b.data.shape == (3, 5)
        assert b.labels.shape == (3, 5)
        assert b.data[2, 2] == 0.0 and b.labels[2, 2] == -1.0

    def test_fixed_length(self):
        samples = [Sample(np.ones(3), np.ones(1)) for _ in range(2)]
        tr = SampleToBatch(2, feature_padding=0.0, label_padding=0.0, fixed_length=8)
        (b,) = list(tr(iter(samples)))
        assert b.data.shape == (2, 8)

    def test_prefetcher_preserves_stream(self):
        src = list(range(50))
        out = list(Prefetcher(4)(iter(src)))
        assert out == src


class TestSeqFile:
    def test_roundtrip(self, tmp_path):
        recs = [ByteRecord(bytes([i] * 10), float(i)) for i in range(20)]
        p = str(tmp_path / "shard-0")
        n = write_shard(p, recs)
        assert n == 20
        back = list(read_shard(p))
        assert len(back) == 20
        assert back[3].data == bytes([3] * 10) and back[3].label == 3.0

    def test_sharded(self, tmp_path):
        recs = [ByteRecord(b"x" * 5, float(i)) for i in range(10)]
        paths = write_sharded(str(tmp_path / "part"), recs, 3)
        total = sum(len(list(read_shard(p))) for p in paths)
        assert total == 10

    def test_bad_magic(self, tmp_path):
        p = tmp_path / "bad"
        p.write_bytes(b"NOTAMAGIC")
        with pytest.raises(ValueError):
            list(read_shard(str(p)))

    def test_index_cache_reuse_and_invalidation(self, tmp_path):
        """Epoch re-reads hit the index cache (no re-validation), but a
        rewritten file must be re-indexed — stale indexes silently
        serving wrong slices would corrupt training data."""
        recs = [ByteRecord(bytes([i] * 10), float(i)) for i in range(5)]
        p = str(tmp_path / "shard-c")
        write_shard(p, recs)
        first = list(read_shard(p))
        second = list(read_shard(p))  # cache hit (same mtime_ns/size)
        assert [r.data for r in first] == [r.data for r in second]
        # rewrite with different content AND size: must re-index
        recs2 = [ByteRecord(bytes([9 - i] * 24), float(i)) for i in range(7)]
        write_shard(p, recs2)
        third = list(read_shard(p))
        assert len(third) == 7 and third[0].data == bytes([9] * 24)
        # SAME-SIZE rewrite (coarse-mtime filesystems can't tell):
        # the content windows in the signature must catch it
        recs3 = [ByteRecord(bytes([i + 40] * 24), float(i + 1))
                 for i in range(7)]
        write_shard(p, recs3)
        fourth = list(read_shard(p))
        assert fourth[0].data == bytes([40] * 24)
        assert fourth[0].label == 1.0
        # corrupt the payload of an already-cached path: signature
        # changes => revalidation => ValueError, not silent bad data
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        with open(p, "wb") as f:
            f.write(raw + b"\x00")  # size change forces signature miss
        with pytest.raises(ValueError):
            list(read_shard(p))


class TestImageTransformers:
    def test_bytes_to_grey(self):
        rec = ByteRecord(np.arange(784, dtype=np.uint8).tobytes(), 3.0)
        img = image.BytesToGreyImg(28, 28).transform_one(rec)
        assert img.data.shape == (1, 28, 28) and img.label == 3.0

    def test_normalizer(self):
        img = LabeledImage(np.full((1, 4, 4), 10.0, dtype=np.float32), 1.0)
        out = image.GreyImgNormalizer(10.0, 2.0).transform_one(img)
        np.testing.assert_allclose(out.data, 0.0)

    def test_bgr_normalizer(self):
        img = LabeledImage(np.ones((3, 4, 4), dtype=np.float32), 1.0)
        out = image.BGRImgNormalizer((1, 1, 1), (2, 2, 2)).transform_one(img)
        np.testing.assert_allclose(out.data, 0.0)

    def test_cropper(self):
        img = LabeledImage(np.arange(3 * 8 * 8, dtype=np.float32).reshape(3, 8, 8), 1.0)
        out = image.BGRImgCropper(4, 4).transform_one(img)
        assert out.data.shape == (3, 4, 4)
        out = image.BGRImgRdmCropper(5, 5).transform_one(img)
        assert out.data.shape == (3, 5, 5)

    def test_hflip(self):
        img = LabeledImage(np.arange(4, dtype=np.float32).reshape(1, 1, 4), 1.0)
        flipped = image.HFlip(threshold=1.1).transform_one(img)  # always flips
        np.testing.assert_allclose(flipped.data[0, 0], [3, 2, 1, 0])

    def test_grey_to_batch(self):
        imgs = [LabeledImage(np.ones((1, 5, 5), dtype=np.float32), float(i)) for i in range(4)]
        batches = list(image.GreyImgToBatch(2)(iter(imgs)))
        assert len(batches) == 2
        assert batches[0].data.shape == (2, 1, 5, 5)
        assert batches[0].labels.shape == (2,)

    def test_lighting_and_jitter_shapes(self):
        img = LabeledImage(np.ones((3, 6, 6), dtype=np.float32), 1.0)
        assert image.Lighting().transform_one(img).data.shape == (3, 6, 6)
        assert image.ColorJitter().transform_one(img).data.shape == (3, 6, 6)


class TestTextTransformers:
    def test_pipeline(self):
        docs = ["Hello world. This is a test!", "Another doc here."]
        sentences = list(text.SentenceSplitter()(iter(docs)))
        assert len(sentences) == 3
        tokens = list(text.SentenceTokenizer()(iter(sentences)))
        assert tokens[0] == ["hello", "world", "."]
        padded = list(text.SentenceBiPadding()(iter(tokens)))
        assert padded[0][0] == text.SENTENCE_START and padded[0][-1] == text.SENTENCE_END

    def test_dictionary(self):
        d = text.Dictionary([["a", "b", "a"], ["a", "c"]], vocab_size=2)
        assert d.get_index("a") == 0  # most frequent
        assert d.get_index("zzz") == d._unk_index
        assert d.vocab_size() == 3

    def test_dictionary_save_load(self, tmp_path):
        d = text.Dictionary([["x", "y"]], vocab_size=10)
        p = str(tmp_path / "vocab.json")
        d.save(p)
        d2 = text.Dictionary.load(p)
        assert d2.get_index("x") == d.get_index("x")

    def test_labeled_sentence_to_sample(self):
        d = text.Dictionary([["a", "b", "c"]], vocab_size=5)
        ls = text.TextToLabeledSentence(d).transform_one(["a", "b", "c"])
        assert len(ls.data) == 2 and len(ls.label) == 2
        s = text.LabeledSentenceToSample(d.vocab_size(), fixed_length=4).transform_one(ls)
        assert s.feature.shape == (4, d.vocab_size())
        assert s.label.shape == (4,)
        assert s.label[0] == ls.label[0] + 1  # 1-based


class TestDocumentPacker:
    def test_dense_windows(self):
        d = text.Dictionary([["a", "b", "c", "d", "e"]], vocab_size=10)
        toks = [["a", "b", "c"], ["d", "e", "a", "b"], ["c", "d"]]
        windows = list(text.DocumentPacker(d, seq_length=4)(iter(toks)))
        stream = [d.get_index(t) for doc in toks for t in doc]  # 9 ids
        # window k covers stream[k*4 : k*4+5]; only 1 full window (needs 5
        # ids; the second would need ids 4..8 -> fits! 9 ids -> windows at
        # offset 0 and 4)
        assert len(windows) == 2
        for k, w in enumerate(windows):
            np.testing.assert_array_equal(w.data, stream[k * 4:k * 4 + 4])
            np.testing.assert_array_equal(w.label,
                                          stream[k * 4 + 1:k * 4 + 5])

    def test_stride_overlap(self):
        d = text.Dictionary([["a", "b", "c", "d"]], vocab_size=10)
        toks = [["a", "b", "c", "d", "a", "b", "c"]]
        windows = list(text.DocumentPacker(d, seq_length=4,
                                           stride=2)(iter(toks)))
        assert len(windows) == 2  # offsets 0 and 2 (7 ids: both need 5)
        stream = [d.get_index(t) for t in toks[0]]
        np.testing.assert_array_equal(windows[1].data, stream[2:6])
        np.testing.assert_array_equal(windows[1].label, stream[3:7])

    def test_packed_dataset_shapes_and_epoch_size(self):
        from bigdl_tpu.models.utils import lm_corpus, lm_dataset

        raw = "the quick brown fox jumps over the lazy dog. " * 20
        token_lists, d = lm_corpus(raw, vocab_size=50)
        ds = lm_dataset(token_lists, d, seq_length=8, batch_size=4,
                        packed=True)
        total_tokens = sum(len(t) for t in token_lists)
        # epoch accounting: size() counts WINDOWS (max_epoch and the
        # every_epoch triggers depend on it), not sentences
        assert ds.size() == (total_tokens - 1) // 8
        batch = next(ds.data(train=False))
        assert batch.data.shape == (4, 8)
        assert batch.labels.shape == (4, 8)
        # dense: inputs shifted by one against labels within the stream
        # (both are 1-based: feature = id+1, label = next id+1)
        np.testing.assert_array_equal(batch.data[0, 1:],
                                      batch.labels[0, :-1])

    def test_packed_too_small_corpus_fails_loudly(self):
        from bigdl_tpu.models.utils import lm_corpus, lm_dataset

        token_lists, d = lm_corpus("tiny corpus.", vocab_size=50)
        with pytest.raises(SystemExit, match="seqLength"):
            lm_dataset(token_lists, d, seq_length=4096, batch_size=4,
                       packed=True)


class TestSyntheticData:
    def test_mnist_synthetic(self):
        recs = mnist.synthetic(32)
        assert len(recs) == 32
        img = image.BytesToGreyImg(28, 28).transform_one(recs[0])
        assert img.data.shape == (1, 28, 28)
        assert 1.0 <= recs[5].label <= 10.0

    def test_cifar_synthetic(self):
        recs = cifar.synthetic(16)
        assert recs[0].data.shape == (3, 32, 32)


def test_prefetcher_stops_worker_when_consumer_closes():
    """An abandoned Prefetcher must stop its worker thread (a worker that
    keeps producing into native code during interpreter shutdown
    segfaults the process — round-3 regression)."""
    import threading
    import time

    from bigdl_tpu.dataset.transformer import Prefetcher

    produced = []
    alive = threading.Event()

    def source():
        i = 0
        while True:
            produced.append(i)
            alive.set()
            yield i
            i += 1

    stream = Prefetcher(depth=2)(source())
    assert next(stream) == 0
    stream.close()  # consumer goes away
    alive.clear()
    time.sleep(0.5)  # worker has 0.1s poll interval; give it a few
    count_after_close = len(produced)
    time.sleep(0.5)
    assert len(produced) == count_after_close, "worker kept producing"
