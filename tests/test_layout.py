"""NHWC/NCHW layout parity: the channels-last fast path must compute the
same function as the Torch-parity NCHW path (weights are OIHW in both, so
the same param pytree drives both layouts)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import ResNet


def to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


@pytest.mark.parametrize("stride,pad,group", [(1, 1, 1), (2, 3, 1), (1, 0, 2)])
def test_conv_layout_parity(nprng, stride, pad, group):
    x = jnp.asarray(nprng.randn(2, 4, 11, 9).astype(np.float32))
    m_nchw = nn.SpatialConvolution(4, 8, 3, 3, stride, stride, pad, pad,
                                   n_group=group).build(seed=3)
    m_nhwc = nn.SpatialConvolution(4, 8, 3, 3, stride, stride, pad, pad,
                                   n_group=group, data_format="NHWC")
    y_ref = m_nchw.forward(x)
    y_fast = m_nhwc.f(m_nchw.params, to_nhwc(x))
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_dilated_conv_layout_parity(nprng):
    x = jnp.asarray(nprng.randn(2, 3, 12, 12).astype(np.float32))
    m_nchw = nn.SpatialDilatedConvolution(3, 5, 3, 3, 1, 1, 2, 2,
                                          dilation_w=2, dilation_h=2).build(seed=0)
    m_nhwc = nn.SpatialDilatedConvolution(3, 5, 3, 3, 1, 1, 2, 2,
                                          dilation_w=2, dilation_h=2,
                                          data_format="NHWC")
    y_ref = m_nchw.forward(x)
    y_fast = m_nhwc.f(m_nchw.params, to_nhwc(x))
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("ceil_mode", [False, True])
def test_maxpool_layout_parity(nprng, ceil_mode):
    x = jnp.asarray(nprng.randn(2, 3, 11, 13).astype(np.float32))
    m_nchw = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
    m_nhwc = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1, data_format="NHWC")
    if ceil_mode:
        m_nchw.ceil()
        m_nhwc.ceil()
    y_ref = m_nchw.f({}, x)
    y_fast = m_nhwc.f({}, to_nhwc(x))
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref))


def test_avgpool_layout_parity(nprng):
    x = jnp.asarray(nprng.randn(2, 3, 8, 8).astype(np.float32))
    m_nchw = nn.SpatialAveragePooling(2, 2, 2, 2)
    m_nhwc = nn.SpatialAveragePooling(2, 2, 2, 2, data_format="NHWC")
    y_ref = m_nchw.f({}, x)
    y_fast = m_nhwc.f({}, to_nhwc(x))
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref),
                               rtol=1e-6, atol=1e-6)


def test_batchnorm_layout_parity(nprng):
    x = jnp.asarray(nprng.randn(4, 6, 5, 5).astype(np.float32))
    m_nchw = nn.SpatialBatchNormalization(6).build(seed=7)
    m_nhwc = nn.SpatialBatchNormalization(6, data_format="NHWC")
    y_ref, buf_ref = m_nchw.apply(m_nchw.params, x,
                                  buffers=m_nchw.init_buffers(), training=True)
    y_fast, buf_fast = m_nhwc.apply(m_nchw.params, to_nhwc(x),
                                    buffers=m_nhwc.init_buffers(), training=True)
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    for k in buf_ref:
        np.testing.assert_allclose(np.asarray(buf_fast[k]), np.asarray(buf_ref[k]),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_resnet_layout_parity_forward_and_grad(nprng):
    """Same params, same input -> same logits and same param gradients in
    both layouts (the NHWC model takes NHWC input)."""
    m_ref = ResNet(class_num=10, depth=8, dataset="cifar10").build(seed=11)
    m_fast = ResNet(class_num=10, depth=8, dataset="cifar10",
                    data_format="NHWC")
    x = jnp.asarray(nprng.randn(4, 3, 32, 32).astype(np.float32))
    y = jnp.asarray((nprng.randint(0, 10, 4) + 1).astype(np.float32))
    crit = nn.ClassNLLCriterion()

    def loss_ref(p):
        out, _ = m_ref.apply(p, x, buffers=m_ref.buffers, training=False)
        return crit.loss(out, y)

    def loss_fast(p):
        out, _ = m_fast.apply(p, to_nhwc(x), buffers=m_ref.buffers,
                              training=False)
        return crit.loss(out, y)

    l_ref, g_ref = jax.value_and_grad(loss_ref)(m_ref.params)
    l_fast, g_fast = jax.value_and_grad(loss_fast)(m_ref.params)
    np.testing.assert_allclose(float(l_fast), float(l_ref), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(g_ref)
    flat_fast = jax.tree_util.tree_leaves(g_fast)
    assert len(flat_ref) == len(flat_fast)
    for a, b in zip(flat_ref, flat_fast):
        assert a.shape == b.shape  # identical pytree incl. OIHW weights
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_resnet_imagenet_nhwc_builds(nprng):
    m = ResNet(class_num=1000, depth=50, dataset="imagenet",
               data_format="NHWC").build(seed=1)
    x = jnp.asarray(nprng.randn(2, 17, 17, 3).astype(np.float32))
    # tiny spatial size still exercises the stem; avg-pool kernel needs 7x7
    # input so use the real 224 path only for shapes via eval_shape (no
    # compute): the driver bench runs the full-size step on hardware.
    full = jax.eval_shape(
        lambda p, xx: m.apply(p, xx, buffers=m.buffers, training=False)[0],
        m.params, jax.ShapeDtypeStruct((2, 224, 224, 3), jnp.float32))
    assert full.shape == (2, 1000)


@pytest.mark.slow
def test_vgg_cifar_layout_parity(nprng):
    from bigdl_tpu.models import VggForCifar10
    m_ref = VggForCifar10(10).build(seed=5)
    m_fast = VggForCifar10(10, data_format="NHWC")
    x = jnp.asarray(nprng.randn(2, 3, 32, 32).astype(np.float32))
    y_ref, _ = m_ref.apply(m_ref.params, x, buffers=m_ref.buffers, training=False)
    y_fast, _ = m_fast.apply(m_ref.params, to_nhwc(x), buffers=m_ref.buffers,
                             training=False)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)


def test_vgg16_imagenet_layout_pytree_and_shape(nprng):
    from bigdl_tpu.models import Vgg_16
    m_ref = Vgg_16(1000)
    m_fast = Vgg_16(1000, data_format="NHWC")
    p_ref = jax.eval_shape(lambda: m_ref.init(jax.random.PRNGKey(0)))
    p_fast = jax.eval_shape(lambda: m_fast.init(jax.random.PRNGKey(0)))
    assert jax.tree_util.tree_structure(p_ref) == jax.tree_util.tree_structure(p_fast)
    out = jax.eval_shape(
        lambda p, xx: m_fast.apply(p, xx, buffers=m_fast.init_buffers(),
                                   training=False)[0],
        p_fast, jax.ShapeDtypeStruct((2, 224, 224, 3), jnp.float32))
    assert out.shape == (2, 1000)


def test_inception_module_layout_parity(nprng):
    from bigdl_tpu.models.inception import _inception_v1_module
    m_ref = _inception_v1_module(16, ((4,), (4, 8), (2, 4), (4,))).build(seed=2)
    m_fast = _inception_v1_module(16, ((4,), (4, 8), (2, 4), (4,)), "NHWC")
    x = jnp.asarray(nprng.randn(2, 16, 9, 9).astype(np.float32))
    y_ref, _ = m_ref.apply(m_ref.params, x, buffers=m_ref.buffers, training=False)
    y_fast, _ = m_fast.apply(m_ref.params, to_nhwc(x), buffers=m_ref.buffers,
                             training=False)
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_lrn_layout_parity(nprng):
    x = jnp.asarray(nprng.randn(2, 8, 6, 6).astype(np.float32))
    m_ref = nn.SpatialCrossMapLRN(5, 0.0001, 0.75)
    m_fast = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, data_format="NHWC")
    y_ref = m_ref.f({}, x)
    y_fast = m_fast.f({}, to_nhwc(x))
    np.testing.assert_allclose(np.asarray(to_nchw(y_fast)), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_inception_v1_nhwc_builds():
    from bigdl_tpu.models import Inception_v1
    m = Inception_v1(1000, data_format="NHWC")
    p = jax.eval_shape(lambda: m.init(jax.random.PRNGKey(0)))
    out = jax.eval_shape(
        lambda pp, xx: m.apply(pp, xx, buffers=m.init_buffers(),
                               training=False)[0],
        p, jax.ShapeDtypeStruct((2, 224, 224, 3), jnp.float32))
    assert out.shape == (2, 1000)


@pytest.mark.slow
def test_bench_recipe_lock_tpu_hlo():
    """Recipe lock for the flagship bench step (MFU work, VERDICT r3 #3):
    the TPU-lowered StableHLO of the ResNet-50 NHWC bf16 train step must
    keep every convolution's inputs in bf16 (MXU operands) and contain
    NO rank-4 activation transposes (layout churn around convs is the
    classic NCHW tax bench.py's recipe exists to avoid; the only
    transposes allowed are 2-D weight transposes from the classifier
    head's matmul grad).  Runs the real TPU lowering via jax.export on
    the CPU host — no chip needed, so the recipe cannot silently rot
    between hardware windows."""
    import re

    from jax import export as jax_export

    from bigdl_tpu.models import ResNet
    from bigdl_tpu.nn._util import cast_f32_leaves
    from bigdl_tpu.optim import SGD

    model = ResNet(class_num=1000, depth=50, dataset="imagenet",
                   data_format="NHWC").build(seed=1)
    crit = nn.ClassNLLCriterion()
    method = SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
    params, buffers = model.params, model.buffers
    opt = method.init_state(params)

    def step(params, buffers, opt_state, x, y, rng):
        def loss_fn(p, b):
            out, nb = model.apply(cast_f32_leaves(p, jnp.bfloat16), x,
                                  buffers=b, training=True, rng=rng)
            return crit.loss(out.astype(jnp.float32), y), nb
        (loss, nb), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, buffers)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        new_params, new_opt = method.update(grads, opt_state, params)
        return new_params, nb, new_opt, loss

    sds = lambda a: jax.ShapeDtypeStruct(jnp.asarray(a).shape,  # noqa: E731
                                         jnp.asarray(a).dtype)
    jtu = jax.tree_util
    exp = jax_export.export(jax.jit(step), platforms=["tpu"])(
        jtu.tree_map(sds, params), jtu.tree_map(sds, buffers),
        jtu.tree_map(sds, opt),
        jax.ShapeDtypeStruct((32, 224, 224, 3), jnp.bfloat16),
        jax.ShapeDtypeStruct((32,), jnp.float32),
        jax.ShapeDtypeStruct((2,), jnp.uint32))
    text = exp.mlir_module()

    conv_lines = [l for l in text.splitlines()
                  if "stablehlo.convolution" in l]
    assert len(conv_lines) > 100  # fwd + dgrad/wgrad of 53 convs
    f32_convs = [l for l in conv_lines
                 if "xf32>" in l.split("->")[0]]
    assert not f32_convs, (
        f"{len(f32_convs)} convolution(s) take f32 operands - the bf16 "
        f"MXU recipe regressed: {f32_convs[0][:200]}")

    rank4_transposes = []
    for l in text.splitlines():
        if "stablehlo.transpose" not in l:
            continue
        m = re.search(r"tensor<([0-9x]+)x(?:bf16|f32)>", l)
        if m and m.group(1).count("x") >= 3:
            rank4_transposes.append(l)
    assert not rank4_transposes, (
        f"{len(rank4_transposes)} rank-4 transpose(s) in the lowered "
        f"step - activation relayout crept back in: "
        f"{rank4_transposes[0][:200]}")
