"""LM serving: continuous batching, slot KV cache, bucketed prefill.

Fast tier-1 tests cover the scheduler mechanics (slot insert/free,
bucket selection, EOS early-exit), the donation contract (the decode
loop reuses the resident cache buffers — no realloc per step), the
prefill compile-count contract (executables == distinct buckets), and
small-scale token-exactness vs offline ``generate``.  The slow soak
replays a staggered-arrival, mixed-length workload and asserts
bit-exact agreement for EVERY request.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.models.transformer.generate import generate
from bigdl_tpu.serving import (CompileCache, LMServingEngine,
                               ServingClosed, ServingQueueFull,
                               prefill_bucket_lengths)
from bigdl_tpu.serving.lm_engine import LMMetrics

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _wait(pred, timeout=30.0):
    """Streams resolve a beat before the worker frees slots / bumps
    counters — poll instead of asserting the instant result() returns."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _lm(vocab=31, hidden=16, heads=2, layers=1, max_len=32, seed=0,
        pos="rope"):
    return TransformerLM(vocab_size=vocab, hidden_size=hidden,
                         n_head=heads, n_layers=layers, max_len=max_len,
                         pos_encoding=pos).build(seed=seed)


@pytest.fixture(scope="module")
def lm_model():
    return _lm()


@pytest.fixture(scope="module")
def lm_engine(lm_model):
    """One shared engine for the read-only fast tests (each engine
    compiles prefill buckets + decode + insert; sharing keeps tier-1
    inside budget)."""
    eng = LMServingEngine(lm_model, slots=2, cache_len=24,
                          max_new_tokens=6, prefill_buckets=(4, 8, 16))
    eng.warmup()
    yield eng
    eng.close()


# --------------------------------------------------------------------------- #
# buckets                                                                     #
# --------------------------------------------------------------------------- #

def test_prefill_bucket_lengths():
    assert prefill_bucket_lengths(64) == (8, 16, 32, 64)
    assert prefill_bucket_lengths(48) == (8, 16, 32, 48)
    assert prefill_bucket_lengths(8) == (8,)
    assert prefill_bucket_lengths(5) == (5,)


def test_bucket_selection_and_overflow(lm_engine, lm_model):
    assert lm_engine.bucket_for(1) == 4
    assert lm_engine.bucket_for(4) == 4
    assert lm_engine.bucket_for(5) == 8
    assert lm_engine.bucket_for(16) == 16
    # ACCEPTANCE: a prompt longer than the largest prefill bucket (the
    # old per-slot cache region) is admitted — chunked paged prefill —
    # and served bit-exact vs offline generate
    p = np.arange(1, 19)  # 18 > largest bucket 16
    out = lm_engine.generate(p, max_new_tokens=6, timeout=120)
    ref = np.asarray(generate(lm_model, lm_model.params,
                              p[None].astype(np.int32), 6))
    np.testing.assert_array_equal(out, ref[0])


def test_submit_rejects_over_cache_len(lm_engine):
    with pytest.raises(ValueError):
        lm_engine.submit(np.arange(1, 11), max_new_tokens=15)  # 10+15>24


# --------------------------------------------------------------------------- #
# compile cache: pytree keys, prefill compile-count contract                  #
# --------------------------------------------------------------------------- #

def test_compile_cache_pytree_inputs():
    """The generalized cache keys on per-leaf (shape, dtype) + treedef:
    multi-tensor inputs (the prefill case) hit and miss correctly."""
    calls = []

    def fn(params, buffers, x):
        calls.append(1)
        return x["ids"] * params + x["len"]

    cache = CompileCache(fn, max_entries=4)
    import jax.numpy as jnp
    p = jnp.float32(2.0)
    a = {"ids": np.ones((1, 8), np.float32), "len": np.float32(3)}
    b = {"ids": np.ones((1, 8), np.float32), "len": np.float32(9)}
    c = {"ids": np.ones((1, 16), np.float32), "len": np.float32(3)}
    y = np.asarray(cache(p, None, a))
    np.testing.assert_allclose(y, 2.0 + 3.0)
    cache(p, None, b)  # same signature, new values: HIT
    cache(p, None, c)  # new leaf shape: MISS
    st = cache.stats()
    assert st["misses"] == 2 and st["hits"] == 1 and st["entries"] == 2
    # warmup_inputs pre-compiles without counting traffic
    d = {"ids": np.ones((1, 32), np.float32), "len": np.float32(0)}
    assert cache.warmup_inputs(p, None, [d, d]) == 1
    st = cache.stats()
    assert st["entries"] == 3 and st["misses"] == 2


def test_prefill_compiles_equal_distinct_buckets(lm_model):
    """Acceptance: prefill executable count == distinct (bucket, dtype)
    pairs, and warmed traffic is all hits."""
    eng = LMServingEngine(lm_model, slots=2, cache_len=24,
                          max_new_tokens=4, prefill_buckets=(4, 8, 16))
    try:
        assert eng.warmup() == 3  # one per bucket
        st = eng.prefill_cache.stats()
        assert st["entries"] == 3 and st["misses"] == 0
        # traffic across all three buckets: hits only, no new compiles
        for t in (2, 4, 6, 9, 16):
            eng.generate(np.arange(1, t + 1) % 30 + 1, timeout=60,
                         max_new_tokens=3)
        st = eng.prefill_cache.stats()
        assert st["entries"] == 3
        assert st["misses"] == 0 and st["hits"] == 5
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# slots: insert/free, EOS early-exit, donation                                #
# --------------------------------------------------------------------------- #

def test_slot_insert_free_and_exactness(lm_engine, lm_model):
    """More requests than slots: continuous admission recycles freed
    slots and every stream matches offline generate bit-for-bit."""
    prompts = [np.arange(1, 5), np.arange(2, 9), np.arange(3, 7),
               np.arange(1, 8)]
    streams = [lm_engine.submit(p, max_new_tokens=3) for p in prompts]
    for p, s in zip(prompts, streams):
        out = s.result(timeout=120)
        ref = np.asarray(generate(lm_model, lm_model.params,
                                  p[None].astype(np.int32), 3))
        np.testing.assert_array_equal(out, ref[0])
    assert _wait(lambda: sorted(lm_engine._free) == [0, 1])  # recycled
    st = lm_engine.stats()
    assert st["active"] == 0 and st["queued"] == 0


def test_decode_reuses_donated_cache_buffers(lm_engine):
    """Acceptance: the decode loop never reallocates the resident k/v
    caches — the donated output IS the input buffer, so the device
    addresses stay fixed across steps and requests."""
    lm_engine.generate(np.arange(1, 6), timeout=60)  # ensure warm+used
    p0 = lm_engine.cache_buffer_pointers()
    assert all(p is not None for p in p0)
    for t in (3, 7, 11):
        lm_engine.generate(np.arange(1, t + 1), max_new_tokens=4,
                           timeout=60)
    assert lm_engine.cache_buffer_pointers() == p0


def test_eos_early_exit_frees_slot(lm_engine):
    """A request hitting EOS stops streaming immediately (its tokens
    are the offline prefix through the first EOS) and its slot is
    reusable; completion is counted."""
    done0 = lm_engine.metrics.completed
    p = np.arange(1, 5)
    full = lm_engine.generate(p, max_new_tokens=6, timeout=60)
    gen = full[len(p):]
    eos = int(gen[2])  # stop at the 3rd token's value
    first_hit = int(np.argmax(gen == eos))  # may appear earlier
    out = lm_engine.generate(p, max_new_tokens=6, eos_id=eos, timeout=60)
    np.testing.assert_array_equal(out, full[:len(p) + first_hit + 1])
    assert out[-1] == eos
    # the slot is free again and serves the next request
    assert _wait(lambda: lm_engine.stats()["active"] == 0)
    assert lm_engine.generate(p, max_new_tokens=2,
                              timeout=60).shape == (6,)
    assert _wait(lambda: lm_engine.metrics.completed == done0 + 3)


def test_first_token_eos_never_occupies_slot(lm_engine, lm_model):
    """max_new=1 (and first-token EOS) complete from prefill alone —
    no insert, no decode step."""
    steps0 = lm_engine.metrics.decode_steps
    out = lm_engine.generate(np.arange(1, 5), max_new_tokens=1,
                             timeout=60)
    assert out.shape == (5,)
    assert lm_engine.metrics.decode_steps == steps0
    ref = np.asarray(generate(lm_model, lm_model.params,
                              np.arange(1, 5)[None].astype(np.int32), 1))
    np.testing.assert_array_equal(out, ref[0])


# --------------------------------------------------------------------------- #
# sampling parity, streaming, lifecycle                                       #
# --------------------------------------------------------------------------- #

def test_sampled_parity_with_offline(lm_model):
    """temperature > 0: the engine replays offline generate()'s exact
    key chain, so sampled streams are bit-exact too."""
    import jax
    eng = LMServingEngine(lm_model, slots=2, cache_len=24,
                          temperature=0.7, prefill_buckets=(8,))
    try:
        p = np.arange(1, 6)
        for seed in (0, 3):  # same shapes: the 2nd seed reuses compiles
            out = eng.generate(p, max_new_tokens=3, rng=seed, timeout=60)
            ref = np.asarray(generate(
                lm_model, lm_model.params, p[None].astype(np.int32), 3,
                temperature=0.7, rng=jax.random.PRNGKey(seed)))
            np.testing.assert_array_equal(out, ref[0])
    finally:
        eng.close()


def test_prefix_sharing_greedy_and_sampled_exact(lm_model):
    """ACCEPTANCE: with paging + radix sharing ON and a prefix actually
    reused (hit rate > 0), greedy AND sampled streams stay bit-exact vs
    offline generate — sharing changes memory traffic, never tokens."""
    import jax
    eng = LMServingEngine(lm_model, slots=2, cache_len=24, block_len=4,
                          prefill_buckets=(4, 8, 16))
    try:
        p = np.arange(1, 13)  # 12 tokens = 3 full blocks, 2 matchable
        ref = np.asarray(generate(lm_model, lm_model.params,
                                  p[None].astype(np.int32), 6))[0]
        np.testing.assert_array_equal(
            eng.generate(p, max_new_tokens=6, timeout=120), ref)
        hits0 = eng.radix.hits
        # identical prompt: served THROUGH the shared chain, still exact
        np.testing.assert_array_equal(
            eng.generate(p, max_new_tokens=6, timeout=120), ref)
        assert eng.radix.hits == hits0 + 1
        assert eng.radix.matched_tokens >= 8
        # sampled: the replayed key chain survives the prefix-hit path
        sref = np.asarray(generate(
            lm_model, lm_model.params, p[None].astype(np.int32), 6,
            temperature=0.7, rng=jax.random.PRNGKey(7)))[0]
        out = eng.generate(p, max_new_tokens=6, temperature=0.7, rng=7,
                           timeout=120)
        np.testing.assert_array_equal(out, sref)
        assert eng.radix.hits == hits0 + 2
    finally:
        eng.close()


def test_stream_tokens_iterator(lm_engine):
    s = lm_engine.submit(np.arange(1, 5), max_new_tokens=4)
    toks = list(s.tokens(timeout=60))
    assert len(toks) == 4
    np.testing.assert_array_equal(toks, s.result(timeout=60)[4:])
    assert s.ttft_s is not None and s.ttft_s >= 0


def test_queue_full_and_closed(lm_model):
    eng = LMServingEngine(lm_model, slots=1, cache_len=24, max_queue=0,
                          max_new_tokens=4, prefill_buckets=(8,))
    try:
        with pytest.raises(ServingQueueFull):
            eng.submit(np.arange(1, 4))
        assert eng.metrics.rejected == 1
    finally:
        eng.close()
    with pytest.raises(ServingClosed):
        eng.submit(np.arange(1, 4))


def test_close_resolves_streams(lm_model):
    """close() drains accepted work; a stream submitted before close
    still resolves (with tokens, since drain finishes it)."""
    eng = LMServingEngine(lm_model, slots=1, cache_len=24,
                          prefill_buckets=(8,))
    s = eng.submit(np.arange(1, 5), max_new_tokens=4)
    eng.close(timeout=60)
    assert s.result(timeout=5).shape == (8,)


def test_lm_metrics_snapshot_and_registry():
    from bigdl_tpu.obs import get_registry
    m = LMMetrics(slots=4).publish_to(get_registry())
    m.record_submit()
    m.record_first_token(0.010)
    m.record_step(2, [0.002, 0.003])
    m.record_complete()
    snap = m.snapshot()
    assert snap["tokens"] == 3 and snap["completed"] == 1
    assert snap["slot_occupancy"] == 0.5  # 2 of 4 slots decoded
    assert snap["ttft"]["count"] == 1 and snap["itl"]["count"] == 2
    reg = get_registry().snapshot()
    assert "serving/lm/tokens_per_s" in reg
    assert reg["serving/lm/slot_occupancy"]["value"] == 0.5


def test_learned_pos_exactness():
    """Per-slot learned position embeddings (not just RoPE) stay exact
    through padded prefill + slot decode."""
    model = _lm(pos="learned", max_len=24, seed=2)
    eng = LMServingEngine(model, slots=2, cache_len=20,
                          prefill_buckets=(8,))
    try:
        p = np.arange(1, 7)  # bucket-padded to 8: pos rows must align
        out = eng.generate(p, max_new_tokens=4, timeout=60)
        ref = np.asarray(generate(model, model.params,
                                  p[None].astype(np.int32), 4))
        np.testing.assert_array_equal(out, ref[0])
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# slow: mixed-length staggered soak + bench CLI                               #
# --------------------------------------------------------------------------- #

@pytest.mark.slow
def test_soak_continuous_batching_token_exact():
    """THE acceptance soak: staggered arrivals, mixed prompt lengths,
    mixed budgets, EOS early-exit — every request's streamed tokens are
    bit-exact vs offline generate, under real slot churn."""
    model = _lm(vocab=61, hidden=32, heads=2, layers=2, max_len=64,
                seed=5)
    eng = LMServingEngine(model, slots=3, cache_len=48,
                          prefill_buckets=(4, 8, 16, 32))
    rng = np.random.RandomState(0)
    try:
        eng.warmup()
        work = []
        for i in range(24):
            t = int(rng.choice((2, 5, 9, 14, 23, 32)))
            m = int(rng.choice((3, 8, 15)))
            work.append((rng.randint(1, 62, size=t).astype(np.int32), m,
                         int(rng.randint(1, 62)) if i % 3 == 0 else None))
        streams = []
        for prompt, m, eos in work:
            streams.append(eng.submit(prompt, max_new_tokens=m,
                                      eos_id=eos))
            time.sleep(float(rng.exponential(0.004)))
        for (prompt, m, eos), s in zip(work, streams):
            out = s.result(timeout=300)
            ref = np.asarray(generate(model, model.params, prompt[None],
                                      m))[0]
            gen = out[len(prompt):]
            if eos is not None and eos in ref[len(prompt):]:
                stop = int(np.argmax(ref[len(prompt):] == eos))
                assert len(gen) == stop + 1 and gen[-1] == eos
                np.testing.assert_array_equal(out, ref[:len(prompt)
                                                       + stop + 1])
            else:
                assert len(gen) == m
                np.testing.assert_array_equal(out, ref)
        assert _wait(lambda: eng.metrics.completed == len(work))
        st = eng.stats()
        assert st["prefill_cache"]["misses"] == 0  # warmup covered all
        assert st["metrics"]["slot_occupancy"] > 0.3
    finally:
        eng.close()


@pytest.mark.slow
def test_serve_lm_bench_cli(tmp_path):
    """bench.py --serve-lm end to end on CPU: resumable artifact with
    both continuous and static numbers and a final summary."""
    out = tmp_path / "BENCH_LM_SERVE.json"
    env = dict(os.environ, BIGDL_TPU_BENCH_PLATFORM="cpu",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--serve-lm", "--json", str(out),
         "--requests", "8", "--slots", "2", "--cache-len", "128",
         "--mean-gap-ms", "4", "--probes", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(out.read_text())
    assert doc["complete"] is True
    stages = {r["stage"] for r in doc["rows"]}
    assert {"warmup", "continuous", "static_baseline"} <= stages
    s = doc["summary"]
    assert s["agreement"] == 1.0
    assert s["tokens_per_s"] > 0 and s["static_tokens_per_s"] > 0
    last = json.loads(proc.stdout.strip().splitlines()[-1])
    assert last["metric"] == "lm_serving_continuous_tokens_per_sec"


def test_int8_lm_serves_and_generates_exactly(lm_model):
    """An int8 Module.quantize() clone both serves through the slot
    engine AND runs offline generate (the jit-entry dequant seam covers
    generate's prefill/decode too), bit-exact with each other."""
    qlm = lm_model.quantize("int8")
    assert qlm.quant_report["bytes_saved"] > 0  # really quantized
    eng = LMServingEngine(qlm, slots=2, cache_len=24,
                          prefill_buckets=(8,))
    try:
        p = np.arange(1, 7)
        out = eng.generate(p, max_new_tokens=4, timeout=120)
        ref = np.asarray(generate(qlm, qlm.params,
                                  p[None].astype(np.int32), 4))
        np.testing.assert_array_equal(out, ref[0])
    finally:
        eng.close()
